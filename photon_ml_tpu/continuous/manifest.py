"""Append-only corpus manifest: what the continuous trainer has already seen.

Production GLMix corpora grow by PART FILES: an upstream ETL drops new
``part-*.avro`` files into the corpus directories and never rewrites old ones
(the reference's daily-partition layout, GameDriver inputDataDateRange). The
manifest is the trainer's durable record of that contract: an ordered list of
every part file ingested so far with its size and content fingerprint. It is
persisted INSIDE each committed checkpoint generation (io/checkpoint.py
``extra_state``), so a restarted trainer knows exactly which files its
warm-start model has already absorbed — the set difference against a fresh
directory scan IS the delta.

The append-only contract is verified, not assumed: a known file whose size
changed, or a known file that disappeared, fails the scan loudly (a rewritten
part file would silently corrupt the incremental corpus — rows the model
trained on would no longer exist in any re-ingest).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Optional, Sequence

from photon_ml_tpu.io.checkpoint import sha256_file
from photon_ml_tpu.resilience import faultpoint, register_fault_point

FP_SCAN = register_fault_point("continuous.scan")


class CorpusContractViolation(Exception):
    """The corpus broke the append-only contract (a known part file changed
    size or vanished). Not recoverable by retrying: the incremental state no
    longer describes the corpus, so the operator must retrain from scratch
    (clear the checkpoint directory) or restore the corpus."""


def file_fingerprint(path: str) -> str:
    """SHA-256 of the file's content (the shared store fingerprint primitive,
    io/checkpoint.py). Computed once per NEW file at ingest time (O(delta)
    I/O per generation, never O(corpus))."""
    return sha256_file(path)


@dataclasses.dataclass(frozen=True)
class PartFile:
    """One ingested part file. ``path`` is stored ABSOLUTE: the persisted
    manifest must compare equal to a fresh scan after a restart from a
    different working directory, where the same relative corpus path spells
    differently. Order in the manifest is ingest order — the row order of
    the accumulated corpus."""

    path: str
    size: int
    sha256: str


@dataclasses.dataclass(frozen=True)
class CompactedHistory:
    """The folded prefix of the manifest after a ``continuous.compact`` step.

    Once a compaction has re-materialized the accumulated corpus into the
    cold tier (continuous/store.py), the original part files are no longer
    the corpus of record — the checksummed cold blocks are. The per-file
    history truncates to this record: the ordered ``(path, size)`` pairs
    (still needed so a scan can tell already-ingested files from genuinely
    new ones, and so a same-path rewrite with a different size still fails
    the append-only contract), the folded row count, and ONE rolled-up
    SHA-256 over the per-file fingerprints for audit. Compacted part files
    MAY disappear from the corpus directories (the upstream ETL is free to
    archive them) — the cold tier owns those bytes now — and restart no
    longer re-reads or re-verifies them.
    """

    n_files: int
    n_rows: int
    rollup_sha256: str
    files: tuple = ()  # ordered (path, size) pairs

    @property
    def paths(self) -> tuple:
        return tuple(p for p, _ in self.files)


@dataclasses.dataclass(frozen=True)
class CorpusManifest:
    """Immutable ordered part-file record; ``extend`` returns a grown copy.

    ``compacted`` (when set) is the folded prefix: files already
    re-materialized into the cold tier. ``entries`` are the LIVE suffix —
    files ingested since the last compaction, still verified against the
    corpus directories and still needed to rebuild the hot tier on restart.
    """

    entries: tuple = ()
    compacted: Optional[CompactedHistory] = None

    @property
    def paths(self) -> tuple:
        head = self.compacted.paths if self.compacted is not None else ()
        return head + tuple(e.path for e in self.entries)

    @property
    def live_paths(self) -> tuple:
        """Paths NOT yet folded into the cold tier (the restart re-decode set)."""
        return tuple(e.path for e in self.entries)

    def __len__(self) -> int:
        n = self.compacted.n_files if self.compacted is not None else 0
        return n + len(self.entries)

    def scan(self, corpus_paths: Sequence[str]) -> list[str]:
        """List the corpus and return part files NOT yet in the manifest, in
        listing order (the order they will be ingested). Known files are
        verified cheaply (existence + size); any append-only violation raises
        :class:`CorpusContractViolation`."""
        from photon_ml_tpu.data import avro_io

        faultpoint(FP_SCAN)
        listed = [
            os.path.abspath(p)
            for p in avro_io.container_files(list(corpus_paths))
        ]
        listed_set = set(listed)
        known = {e.path: e for e in self.entries}
        for path, entry in known.items():
            if path not in listed_set:
                raise CorpusContractViolation(
                    f"ingested part file disappeared from the corpus: {path}"
                )
            size = os.path.getsize(path)
            if size != entry.size:
                raise CorpusContractViolation(
                    f"ingested part file changed size ({entry.size} -> {size}); "
                    f"the corpus is append-only: {path}"
                )
        compacted: dict = (
            dict(self.compacted.files) if self.compacted is not None else {}
        )
        for path, size in compacted.items():
            # a compacted file MAY vanish (the cold tier owns its bytes), but
            # a PRESENT one whose size changed is still a path reuse / rewrite
            # the append-only contract must refuse — silently treating it as
            # "already ingested" would drop the new rows forever
            if path in listed_set and os.path.getsize(path) != size:
                raise CorpusContractViolation(
                    f"compacted part file changed size ({size} -> "
                    f"{os.path.getsize(path)}); the corpus is append-only "
                    f"(a new file must use a new path): {path}"
                )
        return [p for p in listed if p not in known and p not in compacted]

    def extend(self, new_files: Sequence[str]) -> "CorpusManifest":
        """Grown manifest with ``new_files`` appended. Call BEFORE decoding
        them and :meth:`verify_sizes` the new entries after: recording the
        size/fingerprint first and re-checking after the decode brackets the
        read, so a file an upstream writer was still appending to fails
        loudly instead of persisting a record that disagrees with the rows
        the model actually absorbed."""
        new_entries = tuple(
            PartFile(
                path=os.path.abspath(p),
                size=os.path.getsize(p),
                sha256=file_fingerprint(p),
            )
            for p in new_files
        )
        return CorpusManifest(
            entries=self.entries + new_entries, compacted=self.compacted
        )

    def verify_sizes(self, entries: Sequence[PartFile] = None) -> None:
        """Loud check that ``entries`` (default: all) still match their
        recorded on-disk sizes — the torn-write guard around a delta decode."""
        for e in self.entries if entries is None else entries:
            size = os.path.getsize(e.path) if os.path.exists(e.path) else -1
            if size != e.size:
                raise CorpusContractViolation(
                    f"part file changed size during ingest ({e.size} -> {size}); "
                    f"the corpus is append-only: {e.path}"
                )

    def verify_fingerprints(self) -> None:
        """Full content verification of every LIVE part file against its
        persisted SHA-256: catches a SAME-SIZE rewrite that the cheap per-scan
        size check cannot. O(live corpus) I/O, so this runs at restart only —
        where the trainer re-reads the live files anyway — never per poll.
        Compacted files are NOT verified (they may be archived away; their
        rows live in the cold tier under its own per-block checksums)."""
        for e in self.entries:
            if not os.path.exists(e.path):
                raise CorpusContractViolation(
                    f"ingested part file disappeared from the corpus: {e.path}"
                )
            actual = file_fingerprint(e.path)
            if actual != e.sha256:
                raise CorpusContractViolation(
                    f"part file content changed since ingest (sha256 "
                    f"{e.sha256[:12]}… -> {actual[:12]}…); the corpus is "
                    f"append-only: {e.path}"
                )

    # -- compaction ------------------------------------------------------------

    def compact(self, n_rows: int) -> "CorpusManifest":
        """Fold EVERY entry (and any previously compacted prefix) into one
        :class:`CompactedHistory` covering ``n_rows`` accumulated rows. The
        rollup SHA-256 chains the previous rollup with each folded entry's
        fingerprint, so the digest is a pure function of the ingest history.
        Call only after the cold tier durably holds those rows
        (continuous/store.py writes the cold generation FIRST; the checkpoint
        commit carrying this manifest is the atomic cut-over)."""
        h = hashlib.sha256()
        if self.compacted is not None:
            h.update(self.compacted.rollup_sha256.encode())
        for e in self.entries:
            h.update(e.sha256.encode())
        files = (
            self.compacted.files if self.compacted is not None else ()
        ) + tuple((e.path, e.size) for e in self.entries)
        return CorpusManifest(
            entries=(),
            compacted=CompactedHistory(
                n_files=len(self),
                n_rows=int(n_rows),
                rollup_sha256=h.hexdigest(),
                files=files,
            ),
        )

    # -- persistence (rides in the checkpoint manifest's extra_state) ----------

    def to_dict(self) -> dict:
        out = {
            "entries": [dataclasses.asdict(e) for e in self.entries],
        }
        if self.compacted is not None:
            out["compacted"] = {
                "n_files": self.compacted.n_files,
                "n_rows": self.compacted.n_rows,
                "rollup_sha256": self.compacted.rollup_sha256,
                "files": [list(f) for f in self.compacted.files],
            }
        return out

    @staticmethod
    def from_dict(d: dict) -> "CorpusManifest":
        compacted = None
        if d.get("compacted") is not None:
            c = d["compacted"]
            compacted = CompactedHistory(
                n_files=int(c["n_files"]),
                n_rows=int(c["n_rows"]),
                rollup_sha256=c["rollup_sha256"],
                files=tuple((str(p), int(s)) for p, s in c.get("files", [])),
            )
        return CorpusManifest(
            entries=tuple(PartFile(**e) for e in d.get("entries", [])),
            compacted=compacted,
        )
