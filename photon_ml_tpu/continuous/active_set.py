"""Active-set selection: which work a delta pass actually does.

Layer 2's selection rule (the shrinking-working-set trick of the
distributed-CD literature — arxiv 1611.02101's blockwise updates, Snap ML
1803.06333's hierarchical local solves — recast for generational retraining):
a random-effect entity is RE-SOLVED in the delta pass iff

1. it received new rows in the delta (its subproblem changed), or
2. it is new (no previous-generation model row to keep), or
3. its gradient norm at the warm-start coefficients exceeds a threshold —
   the catch-up rule for entities whose RESIDUAL moved because other
   coordinates updated, even though their own data did not
   (algorithm/random_effect.random_effect_gradient_norms; opt-in, one cheap
   vmapped forward/backward pass, no solver iterations).

Everything else keeps the previous generation's coefficients bit for bit
(algorithm/random_effect.train_random_effect_delta scatters around them).

The FIXED effect has no per-entity structure to shrink; its refresh cost is
bounded by a weight-masking reservoir instead: all delta rows keep weight 1,
old rows keep a seeded without-replacement sample re-scaled by n_old/reservoir
(unbiased, the down_sampler re-weighting argument), and dropped rows get
weight 0 — masking, not filtering, because dropping rows would make device
shapes dynamic (the same design as sampling/down_sampler.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.resilience import faultpoint, register_fault_point

FP_ACTIVE_SELECT = register_fault_point("continuous.active_select")


@dataclasses.dataclass
class ActiveSelection:
    """One coordinate's delta-pass working set, with the why."""

    mask: np.ndarray  # bool [E] over the dataset's entity rows
    n_new_data: int
    n_new_entities: int
    n_gradient: int  # selected by the gradient screen alone

    @property
    def n_active(self) -> int:
        return int(self.mask.sum())


def select_active_entities(
    dataset,
    delta_entity_ids: set,
    prev_model=None,
    gradient_norms: Optional[np.ndarray] = None,
    gradient_threshold: Optional[float] = None,
) -> ActiveSelection:
    """The selection rule over one RandomEffectDataset's entity rows.

    ``delta_entity_ids``: entities with new rows (DeltaInfo.delta_entities).
    ``prev_model``: the warm-start RandomEffectModel; entities not in its
    ``entity_ids`` are forced active. ``gradient_norms`` (host [E], from
    random_effect_gradient_norms) with ``gradient_threshold`` arms rule 3.
    """
    faultpoint(FP_ACTIVE_SELECT)
    entity_ids = dataset.entity_ids
    E = len(entity_ids)
    # vectorized membership (np.isin), not per-entity Python loops: selection
    # must stay O(E) C work, never O(E) interpreter work — it runs over the
    # FULL entity set every poll of a pass whose claim is delta-proportional
    ids_arr = np.asarray(entity_ids)
    if delta_entity_ids:
        new_data = np.isin(ids_arr, np.asarray(tuple(delta_entity_ids)))
    else:
        new_data = np.zeros(E, dtype=bool)
    if prev_model is not None and len(prev_model.entity_ids):
        new_entity = ~np.isin(ids_arr, np.asarray(prev_model.entity_ids))
    else:
        new_entity = np.ones(E, dtype=bool)
    mask = new_data | new_entity
    n_gradient = 0
    if gradient_norms is not None and gradient_threshold is not None:
        norms = np.asarray(gradient_norms, dtype=np.float64)
        if norms.shape != (E,):
            raise ValueError(f"gradient_norms shape {norms.shape} != ({E},)")
        screened = (norms > float(gradient_threshold)) & ~mask
        n_gradient = int(screened.sum())
        mask = mask | screened
    return ActiveSelection(
        mask=mask,
        n_new_data=int(new_data.sum()),
        n_new_entities=int((new_entity & ~new_data).sum()),
        n_gradient=n_gradient,
    )


@dataclasses.dataclass(frozen=True)
class ReservoirDownSampler:
    """Fixed-effect refresh reservoir (the ``down_sampler`` protocol of
    FixedEffectCoordinate): rows at or beyond ``n_old`` (the delta) always
    train at full weight; of the ``n_old`` historical rows, a seeded
    without-replacement sample of ``reservoir_size`` keeps weight scaled by
    n_old/reservoir_size (unbiased loss estimate), the rest are weight-0
    masked. ``reservoir_size >= n_old`` is the identity."""

    n_old: int
    reservoir_size: int
    seed: int = 0

    def down_sample(self, data, sample_ids=None):
        n = int(data.weights.shape[0])
        n_old = min(self.n_old, n)
        if self.reservoir_size >= n_old:
            return data
        rng = np.random.default_rng(self.seed)
        keep = rng.choice(n_old, size=self.reservoir_size, replace=False)
        factor = np.zeros(n, dtype=np.float64)
        factor[keep] = n_old / self.reservoir_size
        factor[n_old:] = 1.0
        return dataclasses.replace(
            data,
            weights=data.weights * jnp.asarray(factor, dtype=data.weights.dtype),
        )
