"""Manifest compaction and entity eviction: bounding the unbounded horizon.

Two independent growth axes of a run-forever trainer get folded here:

- **corpus history** — ``run_compaction`` folds the previous cold generation
  plus every hot delta into a NEW cold generation (continuous/store.py) and
  truncates the corpus manifest's per-file history
  (:meth:`~continuous.manifest.CorpusManifest.compact`). The only observable
  state change is the checkpoint commit that carries the folded manifest +
  the cold pointer — the cold write itself lands staged+renamed beforehand
  and stays an unreferenced orphan if the commit never happens (crash-replay
  rewrites it deterministically).
- **entity tables** — ``plan_eviction`` picks random-effect entities with no
  rows in the last ``idle_generations`` generations; ``drop_entities`` shrinks
  the model's device tables around them; their coefficients are parked in the
  store archive. Serving degrades to the engine's existing missing-entity
  contract (an evicted entity scores EXACTLY like one never seen — 0 from the
  random-effect coordinate), and ``inject_archived_rows`` warm-starts a
  reappearing entity's re-solve from the archived coefficients instead of
  zero.

``merge_carried_entities`` is the sliding-window companion: an entity whose
rows all aged out of the training view (but which is NOT evicted yet) simply
has no dataset rows that pass, so the descent output cannot carry it — the
merge re-attaches its previous-generation coefficients verbatim, keeping
"out of the window" and "evicted" two distinct states (frozen-and-serving vs
archived-and-score-0).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.continuous.store import pad_columns
from photon_ml_tpu.models.game import RandomEffectModel
from photon_ml_tpu.resilience import faultpoint, register_fault_point

FP_COMPACT = register_fault_point("continuous.compact")
FP_EVICT = register_fault_point("continuous.evict")


@dataclasses.dataclass
class EvictionPlan:
    """One coordinate's eviction verdict for a pass."""

    evict: list  # entity ids leaving the table this pass
    readmit: list  # previously evicted ids reappearing in the delta


def plan_eviction(
    model: Optional[RandomEffectModel],
    last_active: Mapping,
    delta_entities: set,
    evicted: set,
    current_gen: int,
    idle_generations: int,
) -> EvictionPlan:
    """The eviction rule: an entity leaves the device tables iff its last
    data arrived at or before generation ``current_gen - 1 - idle_generations``
    (no rows in the last ``idle_generations`` committed generations) and it
    has no rows in the CURRENT delta. Entities in ``evicted`` that reappear
    in the delta re-admit. Deterministic: a pure function of the bookkeeping
    a crash-replayed pass restores from the previous checkpoint."""
    faultpoint(FP_EVICT)
    readmit = sorted(e for e in evicted if e in delta_entities)
    if model is None or idle_generations < 1:
        return EvictionPlan(evict=[], readmit=readmit)
    cutoff = int(current_gen) - 1 - int(idle_generations)
    evict = sorted(
        e
        for e in model.entity_ids
        if e not in delta_entities
        and int(last_active.get(e, current_gen)) <= cutoff
    )
    return EvictionPlan(evict=evict, readmit=readmit)


def drop_entities(model: RandomEffectModel, entity_ids: Sequence) -> RandomEffectModel:
    """Shrink the model's entity rows: everything except ``entity_ids``
    survives IN ORDER (surviving rows keep their relative positions, so the
    next dataset build's ``entity_order`` pin still aligns by construction)."""
    drop = set(entity_ids)
    if not drop:
        return model
    keep = [i for i, e in enumerate(model.entity_ids) if e not in drop]
    kept_ids = tuple(model.entity_ids[i] for i in keep)
    idx = np.asarray(keep, dtype=np.int64)
    return dataclasses.replace(
        model,
        entity_ids=kept_ids,
        coeffs=jnp.asarray(np.asarray(model.coeffs)[idx]),
        proj_indices=jnp.asarray(np.asarray(model.proj_indices)[idx]),
        variances=(
            None
            if model.variances is None
            else jnp.asarray(np.asarray(model.variances)[idx])
        ),
    )


def archived_rows_for(model: RandomEffectModel, entity_ids: Sequence) -> dict:
    """The archive payload for entities about to be dropped: coefficient and
    projection rows in the model's CURRENT layout (re-admission remaps by
    global column id, so the layout is self-describing)."""
    rows = [model.row_for_entity(e) for e in entity_ids]
    if any(r < 0 for r in rows):
        missing = [e for e, r in zip(entity_ids, rows) if r < 0]
        raise ValueError(f"cannot archive entities without model rows: {missing}")
    idx = np.asarray(rows, dtype=np.int64)
    return {
        "entity_ids": list(entity_ids),
        "coeffs": np.asarray(model.coeffs)[idx],
        "proj": np.asarray(model.proj_indices)[idx],
        "variances": (
            None if model.variances is None else np.asarray(model.variances)[idx]
        ),
    }


def inject_archived_rows(
    model: RandomEffectModel,
    archive: Optional[dict],
    entity_ids: Sequence,
    min_evicted_at: Optional[int] = None,
) -> tuple[RandomEffectModel, int]:
    """Warm-start re-admitted entities from their archived coefficients: for
    each entity in ``entity_ids`` with an archive row, remap archived slots
    into the model's CURRENT projection layout by global column id (the
    ``aligned_to`` slot-matching rule applied to one row) and overwrite the
    zero row ``aligned_to`` gave the "new" entity. Returns (model, n_injected);
    entities without an archive row stay zero-initialized.

    ``min_evicted_at`` is the archive age-out horizon applied AT INJECTION
    TIME: rows evicted before it never warm-start, whether or not
    ``archive_compact`` has physically deleted them yet. The horizon is a
    pure function of the pass generation, so a crash-replayed pass makes the
    same warm/cold decision as the original attempt even when the crash
    landed between the archive rewrite and the checkpoint commit — physical
    deletion is lazy bookkeeping, never training math."""
    if archive is None or not len(entity_ids):
        return model, 0
    arch_row = {e: i for i, e in enumerate(archive["entity_ids"].tolist())}
    if min_evicted_at is not None:
        gens = np.asarray(archive["evicted_at"])
        arch_row = {
            e: i for e, i in arch_row.items()
            if int(gens[i]) >= int(min_evicted_at)
        }
    coeffs = np.asarray(model.coeffs).copy()
    variances = (
        None if model.variances is None else np.asarray(model.variances).copy()
    )
    dst_proj = np.asarray(model.proj_indices)
    arch_var = archive.get("variances")
    injected = 0
    for e in entity_ids:
        src = arch_row.get(e)
        dst = model.row_for_entity(e)
        if src is None or dst < 0:
            continue
        src_cols = np.asarray(archive["proj"][src])
        src_vals = np.asarray(archive["coeffs"][src])
        slot_of = {int(c): k for k, c in enumerate(src_cols) if c >= 0}
        row = np.zeros_like(coeffs[dst])
        var_row = None if variances is None else np.zeros_like(variances[dst])
        for k, c in enumerate(dst_proj[dst]):
            s = slot_of.get(int(c)) if c >= 0 else None
            if s is not None:
                row[k] = src_vals[s]
                if var_row is not None and arch_var is not None:
                    var_row[k] = arch_var[src][s]
        coeffs[dst] = row
        if var_row is not None:
            variances[dst] = var_row
        injected += 1
    if not injected:
        return model, 0
    return (
        dataclasses.replace(
            model,
            coeffs=jnp.asarray(coeffs),
            variances=None if variances is None else jnp.asarray(variances),
        ),
        injected,
    )


def merge_carried_entities(
    prev_model: RandomEffectModel,
    trained_model: RandomEffectModel,
    evicted: set,
) -> RandomEffectModel:
    """Re-attach entities the training dataset no longer carries (their rows
    aged out of the sliding window) but which are NOT evicted: their previous-
    generation coefficient rows append verbatim at the tail — frozen, still
    served, still eligible for eviction later. Both sides' slot widths pad to
    the wider K (padding slots are proj -1 / coeff 0: inert by construction)."""
    carried = [
        e
        for e in prev_model.entity_ids
        if e not in evicted and trained_model.row_for_entity(e) < 0
    ]
    if not carried:
        return trained_model
    idx = np.asarray(
        [prev_model.row_for_entity(e) for e in carried], dtype=np.int64
    )
    t_coeffs = np.asarray(trained_model.coeffs)
    t_proj = np.asarray(trained_model.proj_indices)
    p_coeffs = np.asarray(prev_model.coeffs)[idx]
    p_proj = np.asarray(prev_model.proj_indices)[idx]
    k = max(t_coeffs.shape[1], p_coeffs.shape[1])

    coeffs = np.concatenate(
        [pad_columns(t_coeffs, k, 0), pad_columns(p_coeffs, k, 0).astype(t_coeffs.dtype)]
    )
    proj = np.concatenate([pad_columns(t_proj, k, -1), pad_columns(p_proj, k, -1)])
    variances = None
    if trained_model.variances is not None:
        t_var = np.asarray(trained_model.variances)
        p_var = (
            np.asarray(prev_model.variances)[idx]
            if prev_model.variances is not None
            else np.zeros_like(p_coeffs)
        )
        variances = np.concatenate(
            [pad_columns(t_var, k, 0), pad_columns(p_var, k, 0).astype(t_var.dtype)]
        )
    return dataclasses.replace(
        trained_model,
        entity_ids=tuple(trained_model.entity_ids) + tuple(carried),
        coeffs=jnp.asarray(coeffs),
        proj_indices=jnp.asarray(proj.astype(np.int32)),
        variances=None if variances is None else jnp.asarray(variances),
    )
