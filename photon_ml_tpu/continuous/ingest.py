"""Incremental corpus ingest: decode ONLY the delta, append to the corpus.

Layer 1 of the continuous-training subsystem. A delta pass must never pay
O(corpus) decode: new part files (manifest.scan's output) go through the PR 5
parallel streaming pipeline (data/readers.read_merged_avro) WITHOUT index
maps, producing a self-contained delta block; the accumulated corpus then
grows by

- **stable index-map growth** — every shard's IndexMap is ``extend()``-ed
  with the delta's unseen feature keys: existing (key -> index) pairs are
  frozen, new keys append at the tail. The old feature matrices stay valid
  verbatim (their column ids never move; widening a CSR matrix is a shape
  annotation), and a previous generation's fixed-effect coefficient vector
  aligns with the grown feature space by zero-padding at the tail — alignment
  BY CONSTRUCTION, no remapping of old state ever;
- **column remap of the delta** — the delta block was decoded against its own
  (sorted, local) index maps; a permutation per shard rewrites its CSR column
  ids into the grown map's space (O(delta nnz));
- **row append** — labels/offsets/weights/id columns/uids concatenate; new
  rows occupy ``[n_old, n_new)`` on the global sample axis, so "which entities
  received data" falls out of the delta's id columns directly.

Determinism contract (the chaos bar leans on it): re-ingesting the WHOLE
manifest in order with the final frozen index maps reproduces the
progressively accumulated corpus bit for bit — that is how a restarted
trainer rebuilds its in-memory corpus from a checkpoint generation.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional, Sequence

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data.game_data import GameInput
from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.readers import read_merged_avro
from photon_ml_tpu.resilience import faultpoint, register_fault_point

FP_DELTA_INGEST = register_fault_point("continuous.delta_ingest")


@dataclasses.dataclass
class CorpusSnapshot:
    """The accumulated in-memory corpus at one generation.

    With the tiered store (continuous/store.py) this is the MATERIALIZED
    TRAINING VIEW: ``row_gens`` stamps each row with the generation that
    ingested it (the sliding-window / time-decay weighting input), and
    ``start_row`` is the view's first row on the GLOBAL accumulated sample
    axis (0 unless a sliding window dropped aged-out head rows)."""

    data: GameInput
    index_maps: dict[str, IndexMap]
    uids: np.ndarray
    row_gens: Optional[np.ndarray] = None  # [N] int64, generation per row
    start_row: int = 0

    @property
    def n_rows(self) -> int:
        return self.data.n

    @property
    def nbytes(self) -> int:
        """Resident host bytes of the materialized arrays (the hot-tier
        memory-accounting input; sparse shards count their CSR triplets)."""
        total = 0
        for m in self.data.features.values():
            c = m.tocsr() if sp.issparse(m) else None
            if c is not None:
                total += c.data.nbytes + c.indices.nbytes + c.indptr.nbytes
            else:
                total += np.asarray(m).nbytes
        for arr in (
            self.data.labels, self.data.offsets, self.data.weights,
            self.row_gens, self.uids,
        ):
            if arr is not None:
                total += np.asarray(arr).nbytes
        for col in self.data.id_columns.values():
            total += np.asarray(col).nbytes
        return total


@dataclasses.dataclass
class DeltaInfo:
    """What one incremental ingest added."""

    n_new_rows: int
    row_start: int  # delta rows occupy [row_start, row_start + n_new_rows)
    # id tag -> entity ids observed in the delta rows (the new-data half of
    # the active-set selection rule)
    delta_entities: dict[str, set]
    # shard -> feature count growth (tail-appended columns)
    new_features: dict[str, int]
    n_new_files: int


def _widen_csr(m: sp.csr_matrix, width: int) -> sp.csr_matrix:
    """Tail growth is a shape annotation: existing column ids stay valid."""
    if m.shape[1] == width:
        return m
    return sp.csr_matrix((m.data, m.indices, m.indptr), shape=(m.shape[0], width))


def _remap_columns(m: sp.csr_matrix, perm: np.ndarray, width: int) -> sp.csr_matrix:
    """Rewrite a delta matrix's column ids through ``perm`` (delta-map index
    -> grown-map index) and re-canonicalize (sorted indices per row)."""
    out = sp.csr_matrix(
        (m.data.copy(), perm[m.indices], m.indptr.copy()), shape=(m.shape[0], width)
    )
    out.sort_indices()
    return out


def read_corpus(
    files: Sequence[str],
    shard_configs: Mapping,
    index_maps: Optional[dict],
    id_tags: Sequence[str],
    ingest_workers: Optional[int] = None,
):
    """One read_merged_avro call over an explicit ordered file list (the PR 5
    pipeline underneath). With ``index_maps`` given the maps are FROZEN:
    this is the corpus-rebuild path of a restarted trainer."""
    data, maps, uids = read_merged_avro(
        list(files),
        shard_configs,
        index_maps=dict(index_maps) if index_maps else None,
        id_tags=tuple(id_tags),
        ingest_workers=ingest_workers,
    )
    return data, maps, np.asarray(uids, dtype=object)


def ingest_delta(
    snapshot: Optional[CorpusSnapshot],
    new_files: Sequence[str],
    shard_configs: Mapping,
    id_tags: Sequence[str],
    ingest_workers: Optional[int] = None,
    generation: Optional[int] = None,
) -> tuple[CorpusSnapshot, DeltaInfo]:
    """Decode ``new_files`` only and append them to ``snapshot`` (None =
    bootstrap: the delta IS the corpus). Returns the grown snapshot and what
    changed. Decode and column remap are O(delta); the row append is an
    O(view) host memcpy (``sp.vstack``/``np.concatenate`` rebuild the old
    block and transiently hold ~2x the view) — bounded by the sliding window
    when one is configured (continuous/store.py), O(corpus) otherwise.

    ``generation`` (when given) stamps the delta's rows with the generation
    that ingested them (``row_gens``) — the row-age metadata the sliding-
    window / time-decay weighting modes derive their weights from."""
    faultpoint(FP_DELTA_INGEST)
    if not new_files:
        raise ValueError("ingest_delta called with no new files")

    delta_data, delta_maps, delta_uids = read_corpus(
        new_files, shard_configs, None, id_tags, ingest_workers
    )
    if delta_data.labels is None:
        raise ValueError(
            f"delta part files carry no labels; a training corpus must "
            f"(files: {list(new_files)[:3]}...)"
        )

    def _gens(n: int) -> Optional[np.ndarray]:
        if generation is None:
            return None
        return np.full(n, int(generation), dtype=np.int64)

    if snapshot is None:
        grown = CorpusSnapshot(
            data=delta_data,
            index_maps=dict(delta_maps),
            uids=delta_uids,
            row_gens=_gens(delta_data.n),
        )
        info = DeltaInfo(
            n_new_rows=delta_data.n,
            row_start=0,
            delta_entities={
                tag: set(delta_data.ids(tag)) for tag in id_tags
            },
            new_features={s: m.size for s, m in delta_maps.items()},
            n_new_files=len(new_files),
        )
        return grown, info

    old = snapshot.data
    if old.labels is None:
        raise ValueError("accumulated corpus lost its labels")

    grown_maps: dict[str, IndexMap] = {}
    features: dict[str, sp.csr_matrix] = {}
    new_features: dict[str, int] = {}
    for shard in shard_configs:
        old_map = snapshot.index_maps[shard]
        delta_map = delta_maps[shard]
        ext = old_map.extend(delta_map.keys())
        grown_maps[shard] = ext
        new_features[shard] = ext.size - old_map.size
        perm = np.fromiter(
            (ext.get_index(k) for k in delta_map.keys()),
            dtype=np.int64,
            count=delta_map.size,
        )
        if (perm < 0).any():  # cannot happen: ext covers every delta key
            raise AssertionError(f"grown index map lost delta keys for {shard!r}")
        old_m = _widen_csr(old.shard(shard).tocsr(), ext.size)
        delta_m = _remap_columns(delta_data.shard(shard).tocsr(), perm, ext.size)
        features[shard] = sp.vstack([old_m, delta_m], format="csr")

    row_gens = None
    if generation is not None:
        old_gens = snapshot.row_gens
        if old_gens is None:
            # an un-stamped snapshot's rows all predate this delta: stamp them
            # one generation older so age-based weighting stays well-defined
            old_gens = np.full(old.n, int(generation) - 1, dtype=np.int64)
        row_gens = np.concatenate([old_gens, _gens(delta_data.n)])

    grown_data = GameInput(
        features=features,
        labels=np.concatenate([np.asarray(old.labels), np.asarray(delta_data.labels)]),
        offsets=np.concatenate([np.asarray(old.offsets), np.asarray(delta_data.offsets)]),
        weights=np.concatenate([np.asarray(old.weights), np.asarray(delta_data.weights)]),
        id_columns={
            tag: np.concatenate(
                [np.asarray(old.ids(tag)), np.asarray(delta_data.ids(tag))]
            )
            for tag in id_tags
        },
    )
    grown = CorpusSnapshot(
        data=grown_data,
        index_maps=grown_maps,
        uids=np.concatenate([snapshot.uids, delta_uids]),
        row_gens=row_gens,
        start_row=snapshot.start_row,
    )
    info = DeltaInfo(
        n_new_rows=delta_data.n,
        row_start=old.n,
        delta_entities={tag: set(delta_data.ids(tag)) for tag in id_tags},
        new_features=new_features,
        n_new_files=len(new_files),
    )
    return grown, info
