"""Continuous training: incremental ingest, active-set coordinate descent,
the tiered out-of-core corpus store, and the closed train→serve generation
loop.

The subsystem's layers (docs/ARCHITECTURE.md "Continuous training" and
"Corpus store & compaction"):

- :mod:`photon_ml_tpu.continuous.manifest` — the append-only corpus manifest
  (what the model has already absorbed; the scan diff IS the delta), with
  the compacted-history fold that truncates per-file records once the cold
  tier owns their rows;
- :mod:`photon_ml_tpu.continuous.ingest` — delta-only decode with stable
  index-map growth (old indices frozen, unseen features append at the tail)
  and per-row generation stamps (the row-age metadata);
- :mod:`photon_ml_tpu.continuous.store` — the tiered :class:`CorpusStore`:
  hot deltas in RAM, a cold tier of checksummed pow2-row blocks in a
  content-addressed pool (incremental compaction reuses unchanged blocks by
  reference — O(delta) bytes written — and the manifests double as the pool
  refcount), re-materialized blockwise; sliding-window view trimming;
  time-decay weighting; retention deletion of aged-out cold rows; the
  evicted-entity coefficient archive with age-out compaction;
- :mod:`photon_ml_tpu.continuous.compaction` — manifest compaction and
  entity-level eviction/re-admission (long-idle random effects leave the
  device tables; serving degrades to the missing-entity score-0 contract;
  reappearing entities warm-start from the archive);
- :mod:`photon_ml_tpu.continuous.active_set` /
  :mod:`photon_ml_tpu.continuous.trainer` — the working-set selection rule,
  the fixed-effect refresh reservoir, and the ``ContinuousTrainer`` driver
  that commits each delta pass as a PR 3 checkpoint generation for PR 6's
  hot-swap watcher to serve.

Fault points ``continuous.{scan,delta_ingest,active_select,commit,compact,
evict,cold_write,cold_link,cold_delete}`` make every phase of the loop
chaos-testable (tests/test_chaos.py, tests/test_continuous.py).
"""

from photon_ml_tpu.continuous.active_set import (
    ActiveSelection,
    ReservoirDownSampler,
    select_active_entities,
)
from photon_ml_tpu.continuous.compaction import (
    EvictionPlan,
    drop_entities,
    inject_archived_rows,
    merge_carried_entities,
    plan_eviction,
)
from photon_ml_tpu.continuous.ingest import CorpusSnapshot, DeltaInfo, ingest_delta
from photon_ml_tpu.continuous.manifest import (
    CompactedHistory,
    CorpusContractViolation,
    CorpusManifest,
    PartFile,
    file_fingerprint,
)
from photon_ml_tpu.continuous.store import (
    ColdStoreCorruption,
    CorpusStore,
    LiveSegment,
    decay_weights,
)
from photon_ml_tpu.continuous.trainer import (
    ContinuousTrainer,
    ContinuousTrainerConfig,
    GenerationResult,
)

__all__ = [
    "ActiveSelection",
    "ColdStoreCorruption",
    "CompactedHistory",
    "ContinuousTrainer",
    "ContinuousTrainerConfig",
    "CorpusContractViolation",
    "CorpusManifest",
    "CorpusSnapshot",
    "CorpusStore",
    "DeltaInfo",
    "EvictionPlan",
    "GenerationResult",
    "LiveSegment",
    "PartFile",
    "ReservoirDownSampler",
    "decay_weights",
    "drop_entities",
    "file_fingerprint",
    "ingest_delta",
    "inject_archived_rows",
    "merge_carried_entities",
    "plan_eviction",
    "select_active_entities",
]
