"""Continuous training: incremental ingest, active-set coordinate descent,
and the closed train→serve generation loop.

The subsystem's three layers (docs/ARCHITECTURE.md "Continuous training"):

- :mod:`photon_ml_tpu.continuous.manifest` — the append-only corpus manifest
  (what the model has already absorbed; the scan diff IS the delta);
- :mod:`photon_ml_tpu.continuous.ingest` — delta-only decode with stable
  index-map growth (old indices frozen, unseen features append at the tail);
- :mod:`photon_ml_tpu.continuous.active_set` /
  :mod:`photon_ml_tpu.continuous.trainer` — the working-set selection rule,
  the fixed-effect refresh reservoir, and the ``ContinuousTrainer`` driver
  that commits each delta pass as a PR 3 checkpoint generation for PR 6's
  hot-swap watcher to serve.

Fault points ``continuous.{scan,delta_ingest,active_select,commit}`` make
every phase of the loop chaos-testable (tests/test_chaos.py).
"""

from photon_ml_tpu.continuous.active_set import (
    ActiveSelection,
    ReservoirDownSampler,
    select_active_entities,
)
from photon_ml_tpu.continuous.ingest import CorpusSnapshot, DeltaInfo, ingest_delta
from photon_ml_tpu.continuous.manifest import (
    CorpusContractViolation,
    CorpusManifest,
    PartFile,
    file_fingerprint,
)
from photon_ml_tpu.continuous.trainer import (
    ContinuousTrainer,
    ContinuousTrainerConfig,
    GenerationResult,
)

__all__ = [
    "ActiveSelection",
    "ContinuousTrainer",
    "ContinuousTrainerConfig",
    "CorpusContractViolation",
    "CorpusManifest",
    "CorpusSnapshot",
    "DeltaInfo",
    "GenerationResult",
    "PartFile",
    "ReservoirDownSampler",
    "file_fingerprint",
    "ingest_delta",
    "select_active_entities",
]
