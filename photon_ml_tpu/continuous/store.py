"""Tiered out-of-core corpus store: hot deltas in RAM, cold blocks on disk.

The PR 7 continuous-training loop held the whole accumulated corpus as one
in-memory snapshot and rebuilt it on restart by re-decoding every part file
the manifest ever recorded — O(history) RAM and O(history) Avro decode, which
falls over exactly at the unbounded horizon the subsystem exists for. The
:class:`CorpusStore` is the hierarchical-storage fix (Snap ML, arXiv
1803.06333: hot working set in fast memory, cold corpus one tier down,
re-materialized blockwise):

- **hot tier** — the deltas ingested since the last compaction, decoded and
  index-remapped, tracked as :class:`LiveSegment` records (generation, the
  manifest entries that fed it, row count). Only the rows inside the training
  window stay materialized in the view.
- **cold tier** — a content-addressed block POOL (``blocks/<sha256>.npz``:
  decoded, index-remapped row blocks, up to ``block_rows`` pow2 rows each —
  PR 5's framing discipline applied to our own storage). Each block's feature
  shards are COLUMN RE-ENCODED at block level: the block persists its own
  sorted column-id vocabulary (``feat__<shard>__colids`` — global ids under
  the frozen ``IndexMap``) plus indices local to it, remapped back to global
  at read time. Block bytes thus depend only on the columns the block's rows
  touch, so the feature axis growing 100x (``IndexMap.extend``) rewrites
  ZERO existing blocks — width growth is purely a read-time shape annotation.
  Alongside the pool sit ``cold-<n>/``
  COLD GENERATIONS, each just a checksummed manifest ordering pool blocks
  into the accumulated corpus: no Avro decode and no index-map application
  ever again for compacted rows. Because the pool is content-addressed, a
  new cold generation REUSES every unchanged block of the previous one by
  reference — zero bytes re-encoded, O(delta + tail block) written per
  compaction, never O(history) — and the manifests ARE the block refcount:
  :meth:`CorpusStore.prune_cold` deletes a pool block only when no surviving
  generation's manifest references it. Legacy (format-1) cold generations
  kept their blocks inside the generation directory; they still read, and
  the next compaction adopts their blocks into the pool by hard link
  (fallback: copy) instead of re-encoding. Retention policies
  (``retain_min_gen`` row age / ``max_cold_rows``) DELETE expired rows at
  compaction time: whole-block drops for fully expired blocks, a row-sliced
  rewrite for the one seam block, block reuse for everything else. Each
  manifest carries its own checksum sidecar and lands by staged-write +
  atomic rename (the PR 3 commit pattern); pool writes are idempotent
  (content-addressed ``os.replace``) — a crash mid-compaction leaves only
  unreferenced pool blocks and a ``.tmp`` staging dir, both swept.
- **view** — the materialized :class:`~continuous.ingest.CorpusSnapshot` the
  trainer actually trains on: cold blocks intersecting the window are read
  back blockwise through the PR 5 pipeline (``map_ordered``: bounded,
  order-preserving, parallel), in-window live segments re-decode through the
  normal reader with FROZEN index maps, and each row carries its ingest
  generation (``row_gens``) — the row-age metadata the sliding-window /
  time-decay weighting modes consume.

Determinism contract (the chaos bar leans on it): materializing the view from
(cold blocks + live segments) reproduces the progressively accumulated view
bit for bit — cold blocks store exactly the decoded+remapped arrays (the
vocabulary round-trip ``colids[searchsorted(colids, indices)]`` restores the
global column indices bit-exactly, dtype included), and CSR row
slicing/stacking is content-preserving. The only durable writes are the
staged+renamed cold generation and archive files, both UNREFERENCED until the
checkpoint generation that points at them commits atomically — so a crash
anywhere leaves at worst an orphaned cold dir that the next compaction
replaces.

The **archive** (``archive/<coordinate>.npz``) is the eviction parking lot:
long-idle random-effect entities dropped from the device tables keep their
coefficients here (checksummed, staged+renamed, merged on rewrite) so a
reappearing entity re-admits WARM instead of re-learning from zero.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import re
import shutil
from typing import Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.continuous.ingest import CorpusSnapshot, ingest_delta, read_corpus
from photon_ml_tpu.data.game_data import GameInput
from photon_ml_tpu.data.pipeline import map_ordered
from photon_ml_tpu.io.checkpoint import sha256_file as _sha256_file
from photon_ml_tpu.resilience import corrupt_file, faultpoint, register_fault_point

logger = logging.getLogger(__name__)

FP_COLD_WRITE = register_fault_point("continuous.cold_write")
# fires before a compaction ADOPTS an unchanged block by reference (pool
# dedup, or the hard-link/copy migration of a legacy in-dir block) instead of
# re-encoding it. Corrupt actions are ignored on purpose: a reused block's
# bytes are shared with the generation that wrote them, so damaging the link
# target would damage the corpus of record itself — that failure class is the
# read-side checksum's job, not a recoverable write fault.
FP_COLD_LINK = register_fault_point("continuous.cold_link")
# fires before a retention/refcount DELETE: a fully expired block dropped
# from the fold, an unreferenced pool block garbage-collected by prune_cold,
# or an archive age-out rewrite/remove.
FP_COLD_DELETE = register_fault_point("continuous.cold_delete")

COLD_PREFIX = "cold-"
BLOCK_PREFIX = "block-"  # legacy (format-1) in-dir block file prefix
POOL_DIR = "blocks"
ARCHIVE_DIR = "archive"
MANIFEST_FILE = "manifest.json"
MANIFEST_SHA_FILE = "manifest.json.sha256"
_TMP_SUFFIX = ".tmp"
DEFAULT_BLOCK_ROWS = 8192  # pow2: a few MB per block at production widths
DEFAULT_KEEP_COLD = 2  # the referenced cold gen + one rollback step
# cold-manifest schema: 1 = blocks live inside the generation directory
# (``block-<k>.npz``), 2 = blocks live in the shared content-addressed pool
# (``blocks/<sha256>.npz``) and the manifest references them by digest. Both
# read; only 2 is written.
_FORMAT = 2
_POOL_RE = re.compile(r"^([0-9a-f]{64})\.npz$")


class ColdStoreCorruption(Exception):
    """A cold block or archive failed integrity verification. Loud by design:
    the cold tier is the corpus of record for compacted rows, so silently
    skipping damage would train against a corpus the model never saw."""


# ------------------------------------------------------------ array encoding
# np.savez(allow_pickle=False) refuses object arrays, but Avro-decoded entity
# id / uid columns arrive as object-of-str. Store their '<U*' form next to a
# marker and restore the object dtype on load, so a materialized view is
# indistinguishable from the progressively accumulated one.

_OBJ_MARKER = "__objstr__"
_DIGEST_KEY = "__sha256__"


def _arrays_digest(arrays: Mapping) -> str:
    """Content digest over a dict of arrays (name + dtype + shape + bytes,
    name-sorted): integrity that can ride INSIDE the npz it protects, so the
    file commits with one atomic rename instead of a content/sidecar pair."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        arr = np.ascontiguousarray(np.asarray(arrays[name]))
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def id_array(ids) -> np.ndarray:
    """Entity ids as a pickle-free array: int64 when every id is integral,
    else their string form — the ONE encoding rule for persisting entity ids
    (archive rows here, eviction bookkeeping aux arrays in the trainer), so
    both sides round-trip identically and re-admission matching never
    diverges from the bookkeeping."""
    ids = list(ids)
    if not ids:
        return np.asarray([], dtype="<U1")
    if all(isinstance(e, (int, np.integer)) and not isinstance(e, bool) for e in ids):
        return np.asarray([int(e) for e in ids], dtype=np.int64)
    return np.asarray([str(e) for e in ids])


def _encode_column(name: str, arr: np.ndarray, out: dict) -> None:
    arr = np.asarray(arr)
    if arr.dtype == object:
        out[name] = arr.astype(str)
        out[_OBJ_MARKER + name] = np.asarray(True)
    else:
        out[name] = arr


def _decode_column(name: str, z: Mapping) -> np.ndarray:
    arr = z[name]
    if _OBJ_MARKER + name in z:
        arr = arr.astype(object)
    return arr


@dataclasses.dataclass(frozen=True)
class LiveSegment:
    """One hot-tier delta: which generation ingested it, which manifest
    entries (by position in the live-entry list at that time — persisted as a
    count so paths stay single-sourced in the corpus manifest), how many rows."""

    generation: int
    n_files: int
    n_rows: int

    def to_list(self) -> list:
        return [int(self.generation), int(self.n_files), int(self.n_rows)]

    @staticmethod
    def from_list(v: Sequence) -> "LiveSegment":
        return LiveSegment(int(v[0]), int(v[1]), int(v[2]))


# ------------------------------------------------------- in-trace row aging


@jax.jit
def _decay_factors(row_gens, current_gen, half_life):
    """Per-row exponential age decay, derived IN-TRACE from row-age metadata:
    ``current_gen`` and ``half_life`` are traced scalars, so every generation
    of a steady-state loop hits the ONE compiled program per view shape (no
    per-generation retrace), and crash-replay of a pass recomputes the exact
    same bits from the same (row_gens, generation) inputs."""
    age = (current_gen - row_gens).astype(jnp.float32)
    return jnp.exp2(-age / half_life)


def decay_weights(
    weights: np.ndarray,
    row_gens: np.ndarray,
    current_gen: int,
    half_life: float,
) -> np.ndarray:
    """Host wrapper: base weights x 2^(-age/half_life). The factors compute on
    device in float32 (the dtype every training program consumes weights at);
    the multiply returns float64 so GameInput's weight schema is unchanged."""
    f = _decay_factors(
        jnp.asarray(np.asarray(row_gens, dtype=np.int32)),
        jnp.asarray(np.int32(current_gen)),
        jnp.asarray(np.float32(half_life)),
    )
    return np.asarray(weights, dtype=np.float64) * np.asarray(f, dtype=np.float64)


# ----------------------------------------------------------------- the store


class CorpusStore:
    """Owns the tiers under ``<directory>/`` (conventionally
    ``<checkpoint_directory>/corpus-store``). All mutating entry points are
    crash-safe: staged writes + atomic rename, nothing referenced until the
    caller's checkpoint commit lands."""

    def __init__(
        self,
        directory: str,
        shard_configs: Mapping,
        id_tags: Sequence[str],
        block_rows: int = DEFAULT_BLOCK_ROWS,
        ingest_workers: Optional[int] = None,
        keep_cold: int = DEFAULT_KEEP_COLD,
    ):
        if block_rows < 1 or (block_rows & (block_rows - 1)):
            raise ValueError(f"block_rows must be a power of two, got {block_rows}")
        if keep_cold < 1:
            raise ValueError(f"keep_cold must be >= 1, got {keep_cold}")
        self.directory = os.path.abspath(directory)
        self.shard_configs = dict(shard_configs)
        self.id_tags = tuple(id_tags)
        self.block_rows = int(block_rows)
        self.ingest_workers = ingest_workers
        self.keep_cold = int(keep_cold)
        # runtime state
        self.cold: Optional[dict] = None  # verified cold manifest, or None
        self.segments: list[LiveSegment] = []
        self.view: Optional[CorpusSnapshot] = None
        self.min_gen: int = 0  # oldest generation materialized in the view
        self._staged: Optional[tuple] = None  # (prev segments, prev min_gen)

    # ------------------------------------------------------------- accounting

    @property
    def cold_rows(self) -> int:
        return int(self.cold["n_rows"]) if self.cold is not None else 0

    @property
    def total_rows(self) -> int:
        """Accumulated corpus rows across BOTH tiers (the unbounded axis;
        includes a staged-but-uncommitted delta, mirroring the staged view)."""
        return self.cold_rows + sum(s.n_rows for s in self.segments)

    @property
    def resident_corpus_bytes(self) -> int:
        """Host bytes the store currently keeps materialized — the bounded-
        memory claim's measured quantity (O(view), never O(history))."""
        return 0 if self.view is None else self.view.nbytes

    def to_state(self, compacted_as: Optional[tuple] = None) -> dict:
        """JSON state for the checkpoint's ``extra_state`` (paths stay
        single-sourced in the corpus manifest; this is tier bookkeeping).
        ``compacted_as=(cold_id, n_rows)`` renders the POST-compaction state
        for the commit that carries a freshly written cold generation —
        before :meth:`install_cold` has adopted it — so both commit branches
        share one schema."""
        if compacted_as is not None:
            cold_id, n_rows = compacted_as
            return {
                "cold_id": int(cold_id),
                "cold_rows": int(n_rows),
                "segments": [],
                "block_rows": self.block_rows,
            }
        return {
            "cold_id": None if self.cold is None else int(self.cold["cold_id"]),
            "cold_rows": self.cold_rows,
            "segments": [s.to_list() for s in self.segments],
            "block_rows": self.block_rows,
        }

    # -------------------------------------------------------------- cold tier

    def _cold_dir(self, cold_id: int) -> str:
        return os.path.join(self.directory, f"{COLD_PREFIX}{cold_id:08d}")

    def _pool_dir(self) -> str:
        return os.path.join(self.directory, POOL_DIR)

    def _pool_path(self, sha256: str) -> str:
        return os.path.join(self._pool_dir(), f"{sha256}.npz")

    def _block_path(self, cold_dir: str, block: dict) -> str:
        """Where a manifest block's bytes live: inside the generation
        directory for legacy (format-1) manifests, in the content-addressed
        pool for format-2 (the block's NAME is its digest)."""
        if "name" in block:
            return os.path.join(cold_dir, block["name"])
        return self._pool_path(block["sha256"])

    def _load_cold_manifest(self, cold_id: int) -> dict:
        cold_dir = self._cold_dir(cold_id)
        man_path = os.path.join(cold_dir, MANIFEST_FILE)
        sha_path = os.path.join(cold_dir, MANIFEST_SHA_FILE)
        try:
            with open(sha_path) as f:
                expected = f.read().strip()
            actual = _sha256_file(man_path)
        except OSError as e:
            raise ColdStoreCorruption(
                f"cold generation {cold_id} is unreadable: {e}"
            ) from e
        if actual != expected:
            raise ColdStoreCorruption(
                f"cold manifest checksum mismatch in {cold_dir}"
            )
        with open(man_path) as f:
            meta = json.load(f)
        fmt = int(meta.get("format", 1))
        if fmt not in (1, _FORMAT):
            raise ColdStoreCorruption(
                f"cold generation {cold_id} has unknown manifest format {fmt} "
                f"(this build reads formats 1 and {_FORMAT})"
            )
        meta["path"] = cold_dir
        return meta

    def _read_block(self, cold_dir: str, block: dict, widths: Mapping) -> dict:
        """Verify + load one cold block back into (csr shards, columns)."""
        path = self._block_path(cold_dir, block)
        try:
            actual = _sha256_file(path)
        except OSError as e:
            raise ColdStoreCorruption(f"missing cold block {path}: {e}") from e
        if actual != block["sha256"]:
            raise ColdStoreCorruption(f"cold block checksum mismatch: {path}")
        with np.load(path, allow_pickle=False) as z:
            arrs = {k: z[k] for k in z.files}
        shards = {}
        for shard, width in widths.items():
            indices = arrs[f"feat__{shard}__indices"]
            colids_key = f"feat__{shard}__colids"
            if colids_key in arrs:
                # colids encoding: stored indices are positions in the
                # block's own sorted column-id vocabulary; remap local ->
                # global through the frozen-map ids the vocabulary recorded
                # at write time (IndexMap.extend never moves them). Blocks
                # without the key predate the encoding and stored global ids
                # directly — both read.
                indices = arrs[colids_key][indices]
            m = sp.csr_matrix(
                (
                    arrs[f"feat__{shard}__data"],
                    indices,
                    arrs[f"feat__{shard}__indptr"],
                ),
                # widen to the CURRENT map width: tail growth is a shape
                # annotation, stored column ids never move (index_map.extend)
                shape=(len(arrs["labels"]), int(width)),
            )
            shards[shard] = m
        cols = {
            name: _decode_column(name, arrs)
            for name in ("labels", "offsets", "weights", "row_gens", "uids")
        }
        cols["ids"] = {
            tag: _decode_column(f"id__{tag}", arrs) for tag in self.id_tags
        }
        cols["features"] = shards
        return cols

    def _iter_cold_chunks(self, min_gen: int, widths: Mapping, workers=None):
        """Yield decoded cold chunks (oldest first) whose rows can reach the
        window ``gen >= min_gen`` — blocks entirely below it are skipped
        WITHOUT touching their bytes; the seam block is row-sliced. Reads go
        through the PR 5 bounded order-preserving pool."""
        if self.cold is None:
            return
        cold_dir = self.cold["path"]
        blocks = [
            b for b in self.cold["blocks"] if int(b["gen_hi"]) >= int(min_gen)
        ]
        n_workers = workers if workers is not None else (self.ingest_workers or 1)
        for chunk in map_ordered(
            blocks,
            lambda b: self._read_block(cold_dir, b, widths),
            workers=n_workers,
            window=max(2, n_workers * 2),
        ):
            keep = np.asarray(chunk["row_gens"]) >= int(min_gen)
            if not keep.all():
                idx = np.flatnonzero(keep)
                chunk = _slice_chunk(chunk, idx)
            if len(chunk["labels"]):
                yield chunk

    # --------------------------------------------------------- materialization

    def materialize(
        self,
        index_maps: Mapping,
        manifest,
        min_gen: int = 0,
        segments: Optional[list] = None,
    ) -> CorpusSnapshot:
        """Rebuild the training view from the tiers: cold blocks (blockwise,
        verified) + in-window live segments re-decoded with the FROZEN index
        maps — bitwise the progressively accumulated view. ``manifest`` is the
        corpus manifest whose live entries feed the segments, in order."""
        segments = self.segments if segments is None else segments
        widths = {s: m.size for s, m in index_maps.items()}
        chunks = list(self._iter_cold_chunks(min_gen, widths))
        chunks.extend(
            self._iter_live_chunks(manifest, segments, index_maps, widths, min_gen)
        )
        if chunks:
            view = _chunks_to_snapshot(chunks, dict(index_maps), widths)
        else:
            # a window that excluded every accumulated row (e.g.
            # window_generations=1 between passes) is a legitimate state —
            # the next delta appends onto the empty view; raising here would
            # wedge abort_delta/restore behind a masked ValueError
            view = _empty_snapshot(dict(index_maps), widths, self.id_tags)
        # global start row: everything accumulated minus what the view holds
        total = self.cold_rows + sum(s.n_rows for s in segments)
        view.start_row = total - view.n_rows
        self.view = view
        self.min_gen = int(min_gen)
        return view

    def _iter_live_chunks(
        self, manifest, segments, index_maps: Mapping, widths: Mapping,
        min_gen: int,
    ):
        """Re-decode live segments (generation >= ``min_gen``) with the
        frozen maps, one chunk per segment, with the row-count check — the
        ONE decode path both materialization and the compaction fold share,
        so neither can silently fold rows the bookkeeping never recorded."""
        live_paths = list(manifest.live_paths)
        if sum(s.n_files for s in segments) != len(live_paths):
            raise ValueError(
                f"store segments cover {sum(s.n_files for s in segments)} live "
                f"files but the manifest records {len(live_paths)}"
            )
        offset = 0
        for seg in segments:
            paths = live_paths[offset : offset + seg.n_files]
            offset += seg.n_files
            if seg.generation < int(min_gen):
                continue  # aged out of the window: never re-decoded
            data, _maps, uids = read_corpus(
                paths, self.shard_configs, index_maps, self.id_tags,
                self.ingest_workers,
            )
            if data.n != seg.n_rows:
                raise ColdStoreCorruption(
                    f"live segment for generation {seg.generation} re-decoded "
                    f"to {data.n} rows, recorded {seg.n_rows}"
                )
            yield {
                "features": {s: data.shard(s).tocsr() for s in widths},
                "labels": np.asarray(data.labels),
                "offsets": np.asarray(data.offsets),
                "weights": np.asarray(data.weights),
                "row_gens": np.full(data.n, seg.generation, dtype=np.int64),
                "uids": np.asarray(uids, dtype=object),
                "ids": {tag: np.asarray(data.ids(tag)) for tag in self.id_tags},
            }

    # ------------------------------------------------------------ window/delta

    def trim_view(self, min_gen: int) -> CorpusSnapshot:
        """Advance the sliding window: drop view head rows whose generation
        aged below ``min_gen``. Rows append in generation order, so the drop
        is a contiguous head slice — O(view) memcpy, no decode."""
        if self.view is None:
            raise ValueError("no materialized view to trim")
        if int(min_gen) <= self.min_gen:
            return self.view
        gens = self.view.row_gens
        if gens is None:
            raise ValueError("view carries no row_gens; window modes need them")
        start = int(np.searchsorted(gens, int(min_gen), side="left"))
        if start:
            self.view = _slice_snapshot(self.view, start)
        self.min_gen = int(min_gen)
        return self.view

    def stage_delta(self, new_files: Sequence[str], generation: int):
        """Decode + append a delta to the view for pass ``generation``.
        Nothing durable moves; call :meth:`commit_delta` after the checkpoint
        commit lands or :meth:`abort_delta` (which re-materializes the
        previous view from the tiers) on failure. The PREVIOUS view's arrays
        are released eagerly — the store never holds two generations' views
        beyond the concat itself."""
        if self._staged is not None:
            raise RuntimeError("a staged delta is already pending")
        prev_segments = list(self.segments)
        prev_min_gen = self.min_gen
        grown, info = ingest_delta(
            self.view,
            new_files,
            self.shard_configs,
            self.id_tags,
            self.ingest_workers,
            generation=int(generation),
        )
        # eager drop: the pre-delta view is re-creatable from (cold, live
        # segments); keeping it alive across the whole pass would double the
        # hot tier for no benefit (satellite: no step holds more than the
        # hot tier + one block of cold reads)
        self.view = grown
        self.segments = prev_segments + [
            LiveSegment(
                generation=int(generation),
                n_files=len(new_files),
                n_rows=info.n_new_rows,
            )
        ]
        self._staged = (prev_segments, prev_min_gen)
        return grown, info

    def commit_delta(self) -> None:
        self._staged = None

    def abort_delta(self, index_maps: Mapping, manifest) -> Optional[CorpusSnapshot]:
        """Roll the staged delta back: restore segment bookkeeping and
        re-materialize the previous view (deterministic re-read — the price
        of releasing it eagerly on stage). A failed BOOTSTRAP ingest rolls
        back to the empty store (no view)."""
        if self._staged is None:
            raise RuntimeError("no staged delta to abort")
        prev_segments, prev_min_gen = self._staged
        self.segments = prev_segments
        self._staged = None
        self.view = None  # release the staged view before rebuilding
        self.min_gen = prev_min_gen
        if not prev_segments and self.cold is None:
            return None
        return self.materialize(
            index_maps, manifest, min_gen=prev_min_gen, segments=prev_segments
        )

    # -------------------------------------------------------------- compaction

    def write_cold_generation(
        self,
        cold_id: int,
        index_maps: Mapping,
        manifest,
        retain_min_gen: int = 0,
        max_cold_rows: Optional[int] = None,
        protect_min_gen: int = 0,
    ) -> dict:
        """Fold the previous cold generation plus EVERY live segment into
        ``cold-<cold_id>/`` — INCREMENTALLY. Unchanged previous blocks are
        adopted by reference into the content-addressed pool (zero re-encode,
        zero re-read; legacy in-dir blocks enter the pool by hard link,
        fallback copy), only the partial tail block and the live segments
        re-encode, so bytes written per compaction are O(delta + tail block)
        and cold-tier read I/O is O(seam blocks), never O(history). Peak RAM
        stays O(block + largest segment).

        Retention: rows with generation below ``retain_min_gen`` are DELETED
        from the fold — fully expired blocks drop whole (no read), the one
        seam block rewrites row-sliced, everything younger reuses. With
        ``max_cold_rows`` set, oldest surviving blocks additionally drop at
        BLOCK granularity until the fold fits the cap — but never a block
        that still reaches generation ``protect_min_gen`` (the training
        window), so retention can only delete rows whose training weight is
        already zero and the training math is untouched by construction.

        Staged + atomic rename; the caller's checkpoint commit is what makes
        it authoritative (pool writes are content-addressed and idempotent —
        unreferenced until then, garbage-collected if the commit never
        lands). Returns the new cold manifest with an ``io`` stats dict
        (bytes/blocks written, reused, dropped — the honest-ratio inputs;
        not persisted in the manifest file); call :meth:`install_cold` with
        it AFTER the commit lands."""
        # compaction permanently EXEMPTS the folded files from every future
        # verification (the cold tier becomes their corpus of record), so
        # this is the last chance to catch a same-size rewrite: full-content
        # fingerprint check of every live entry about to fold — O(live) I/O,
        # paid only at compaction cadence
        manifest.verify_fingerprints()
        widths = {s: m.size for s, m in index_maps.items()}
        final = self._cold_dir(cold_id)
        tmp = final + _TMP_SUFFIX
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        os.makedirs(self._pool_dir(), exist_ok=True)

        writer = _BlockWriter(
            self._pool_dir(), self.block_rows, widths, self.id_tags
        )
        prev_blocks = list(self.cold["blocks"]) if self.cold is not None else []
        prev_dir = self.cold["path"] if self.cold is not None else None
        retain_min = int(retain_min_gen)
        rows_dropped = 0
        blocks_dropped = 0

        def _n(b):
            return int(b["rows"][1]) - int(b["rows"][0])

        def _expired(b):
            return int(b["gen_hi"]) < retain_min

        # block-granular row cap: drop oldest surviving blocks until the fold
        # fits, stopping at the first block that reaches the protected window.
        # The estimate counts the retention seam block WHOLE (its expired
        # prefix is sliced later without a read here), so the cap can drop up
        # to one block more than strictly needed — best-effort at block
        # granularity in both directions, and only ever below-window rows.
        # With protect_min_gen <= 0 the window still needs EVERY generation,
        # so the cap waits — it can only ever delete zero-weight rows.
        cap_drop: set = set()
        if max_cold_rows is not None and int(protect_min_gen) > 0:
            total = sum(s.n_rows for s in self.segments) + sum(
                _n(b) for b in prev_blocks if not _expired(b)
            )
            for i, b in enumerate(prev_blocks):
                if total <= int(max_cold_rows):
                    break
                if _expired(b):
                    continue
                if int(b["gen_hi"]) >= int(protect_min_gen):
                    break
                cap_drop.add(i)
                total -= _n(b)

        keep_idx = [
            i
            for i, b in enumerate(prev_blocks)
            if not _expired(b) and i not in cap_drop
        ]
        last_keep = keep_idx[-1] if keep_idx else -1
        for i, b in enumerate(prev_blocks):
            if _expired(b) or i in cap_drop:
                faultpoint(FP_COLD_DELETE)
                rows_dropped += _n(b)
                blocks_dropped += 1
                continue
            seam = int(b["gen_lo"]) < retain_min
            tail_partial = i == last_keep and _n(b) < self.block_rows
            if seam or tail_partial:
                # the only cold reads of the fold: the retention seam block
                # and the partial tail block (merged with the delta)
                chunk = self._read_block(prev_dir, b, widths)
                if seam:
                    keep = np.asarray(chunk["row_gens"]) >= retain_min
                    rows_dropped += int((~keep).sum())
                    chunk = _slice_chunk(chunk, np.flatnonzero(keep))
                if len(chunk["labels"]):
                    writer.push(chunk)
            else:
                writer.reuse(b, prev_dir)
        for chunk in self._iter_live_chunks(
            manifest, self.segments, index_maps, widths, min_gen=0
        ):
            writer.push(chunk)
        blocks, n_rows = writer.finish()

        meta = {
            "format": _FORMAT,
            "cold_id": int(cold_id),
            "n_rows": int(n_rows),
            "block_rows": self.block_rows,
            "shards": {s: int(w) for s, w in widths.items()},
            "id_tags": list(self.id_tags),
            "blocks": blocks,
        }
        man_path = os.path.join(tmp, MANIFEST_FILE)
        with open(man_path, "w") as f:
            json.dump(meta, f)
        with open(os.path.join(tmp, MANIFEST_SHA_FILE), "w") as f:
            f.write(_sha256_file(man_path) + "\n")

        # an orphaned final dir from a crashed earlier attempt (written but
        # never referenced by a committed checkpoint) is replaced wholesale
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        meta["path"] = final
        # io stats ride on the RETURNED meta only (never in manifest.json):
        # the manifest must stay a pure function of the folded rows
        meta["io"] = {
            **writer.io_stats(),
            "rows_dropped": int(rows_dropped),
            "blocks_dropped": int(blocks_dropped),
        }
        return meta

    def install_cold(self, meta: dict, clear_segments: bool = True) -> None:
        """Adopt a written cold generation as current (call alongside folding
        the manifest): live segments are now cold rows."""
        self.cold = meta
        if clear_segments:
            self.segments = []
        self.prune_cold(referenced=int(meta["cold_id"]))

    def prune_cold(self, referenced: Optional[int] = None) -> None:
        """Drop cold generations the retention policy no longer needs, and
        sweep staging leftovers a real crash mid-write leaked (cold ``*.tmp``
        dirs and archive ``*.tmp-<pid>.npz`` files — a store whose point is
        bounded growth must not accumulate dead bytes).

        ``referenced`` is the cold id the NEWEST committed checkpoint points
        at. Anything NEWER is a crash orphan a replayed compaction will
        rewrite — deleted, and never counted toward retention: an orphan that
        displaced a referenced generation from the keep window would make
        rollback (or with ``keep_cold=1`` the normal restart) unrecoverable
        once the original part files are archived away. Of the
        referenced-and-older generations, the newest ``keep_cold`` are kept
        (the referenced one plus rollback steps). With ``referenced=None``
        (nothing is known to reference any cold generation) NO cold dirs are
        deleted — only staging leftovers sweep."""
        if not os.path.isdir(self.directory):
            return
        if referenced is not None:
            gens = sorted(
                n
                for n in os.listdir(self.directory)
                if n.startswith(COLD_PREFIX) and not n.endswith(_TMP_SUFFIX)
            )
            orphans = [
                n for n in gens if int(n[len(COLD_PREFIX):]) > int(referenced)
            ]
            gens = [n for n in gens if n not in set(orphans)]
            for name in orphans:
                logger.info("removing orphaned cold generation %s", name)
                shutil.rmtree(
                    os.path.join(self.directory, name), ignore_errors=True
                )
            for name in gens[: -self.keep_cold]:
                shutil.rmtree(
                    os.path.join(self.directory, name), ignore_errors=True
                )
        for name in os.listdir(self.directory):
            if name.endswith(_TMP_SUFFIX):
                shutil.rmtree(
                    os.path.join(self.directory, name), ignore_errors=True
                )
        archive_dir = os.path.join(self.directory, ARCHIVE_DIR)
        if os.path.isdir(archive_dir):
            for name in os.listdir(archive_dir):
                if f"{_TMP_SUFFIX}-" in name:
                    try:
                        os.remove(os.path.join(archive_dir, name))
                    except OSError:
                        pass
        self._gc_pool()

    def _gc_pool(self) -> None:
        """Refcount sweep of the content-addressed block pool: a pool block
        survives iff SOME surviving cold generation's manifest references its
        digest — the manifests ARE the refcount, recomputed from disk so it
        can never go stale. Everything else (a crashed compaction's published
        blocks, blocks whose last referencing generation aged out of
        ``keep_cold``, stale staging files) deletes. Conservative on damage:
        an unreadable manifest makes the reference set unknowable, so the
        sweep SKIPS deleting rather than risk a block a generation still
        needs (the damage itself fails loudly on the next read)."""
        pool = self._pool_dir()
        if not os.path.isdir(pool):
            return
        referenced: set = set()
        for name in sorted(os.listdir(self.directory)):
            if not name.startswith(COLD_PREFIX) or name.endswith(_TMP_SUFFIX):
                continue
            try:
                meta = self._load_cold_manifest(int(name[len(COLD_PREFIX):]))
            except (ColdStoreCorruption, ValueError) as e:
                logger.warning(
                    "skipping pool garbage collection: cold manifest %s is "
                    "unreadable (%s)", name, e,
                )
                return
            referenced |= {
                b["sha256"] for b in meta["blocks"] if "name" not in b
            }
        for fname in sorted(os.listdir(pool)):
            path = os.path.join(pool, fname)
            m = _POOL_RE.match(fname)
            if m is None:
                if _TMP_SUFFIX in fname:  # staging leftovers from a crash
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                continue
            if m.group(1) in referenced:
                continue
            faultpoint(FP_COLD_DELETE)
            try:
                os.remove(path)
            except OSError:
                pass

    def adopt_state(self, state: Optional[dict]) -> None:
        """Restore tier bookkeeping from a checkpoint's ``extra_state`` blob
        (``to_state``'s output). Verifies the referenced cold generation's
        manifest; the blocks verify lazily as they are read."""
        if not state:
            self.cold = None
            self.segments = []
            self.prune_cold()  # sweep staging leftovers; keep dirs untouched
            return
        cold_id = state.get("cold_id")
        self.cold = None if cold_id is None else self._load_cold_manifest(int(cold_id))
        if self.cold is not None and self.cold_rows != int(state.get("cold_rows", -1)):
            raise ColdStoreCorruption(
                f"cold generation {cold_id} rows ({self.cold_rows}) disagree "
                f"with the checkpoint record ({state.get('cold_rows')})"
            )
        self.segments = [LiveSegment.from_list(v) for v in state.get("segments", [])]
        # prune AFTER the referenced manifest verified: crash orphans (newer
        # than the reference) go, retention counts only real generations
        if cold_id is not None:
            self.prune_cold(referenced=int(cold_id))
        else:
            self.prune_cold()

    # ---------------------------------------------------------------- archive

    def _archive_path(self, cid: str) -> str:
        safe = cid.replace(os.sep, "_").replace("/", "_")
        return os.path.join(self.directory, ARCHIVE_DIR, f"{safe}.npz")

    def archive_load(self, cid: str) -> Optional[dict]:
        """Verified archive for one coordinate: {entity_ids, coeffs, proj,
        variances?, evicted_at} or None when nothing was ever evicted. Raises
        :class:`ColdStoreCorruption` on damage — a silently dropped archive
        would re-admit entities cold and break replay determinism.

        Integrity is SELF-CONTAINED: the digest of the arrays rides inside
        the npz (``__sha256__``), so the archive commits as ONE atomic
        rename — there is no content/sidecar pair whose torn update could
        brick every later pass."""
        path = self._archive_path(cid)
        if not os.path.exists(path):
            return None
        try:
            with np.load(path, allow_pickle=False) as z:
                arrs = {k: z[k] for k in z.files}
        except Exception as e:  # torn zip, bad header — bit-rot, not a bug
            raise ColdStoreCorruption(
                f"archive for {cid!r} is unreadable: {e}"
            ) from e
        expected = str(arrs.pop(_DIGEST_KEY, ""))
        actual = _arrays_digest(arrs)
        if actual != expected:
            raise ColdStoreCorruption(f"archive checksum mismatch: {path}")
        out = {
            "entity_ids": _decode_column("entity_ids", arrs),
            "coeffs": arrs["coeffs"],
            "proj": arrs["proj"],
            "evicted_at": arrs["evicted_at"],
        }
        if "variances" in arrs:
            out["variances"] = arrs["variances"]
        return out

    def archive_write(
        self,
        cid: str,
        entity_ids: Sequence,
        coeffs: np.ndarray,
        proj: np.ndarray,
        variances: Optional[np.ndarray],
        evicted_at: int,
    ) -> str:
        """Merge newly evicted entities into the coordinate's archive
        (staged + renamed + checksummed). Re-evicting an entity overwrites its
        archived row — the archive always holds the LATEST pre-eviction
        coefficients. Idempotent: a crash-replayed pass rewrites identical
        bytes."""
        prev = self.archive_load(cid)
        ids_new = list(entity_ids)
        ids_new_set = set(ids_new)
        k_new = coeffs.shape[1] if len(ids_new) else 0
        if prev is not None:
            keep = [
                i
                for i, e in enumerate(prev["entity_ids"].tolist())
                if e not in ids_new_set
            ]
            k = max(int(prev["coeffs"].shape[1]), k_new)
            ids_all = [prev["entity_ids"][i] for i in keep] + ids_new
            coeffs_all = np.concatenate(
                [
                    pad_columns(prev["coeffs"][keep], k, 0),
                    pad_columns(np.asarray(coeffs), k, 0),
                ]
            )
            proj_all = np.concatenate(
                [
                    pad_columns(prev["proj"][keep], k, -1),
                    pad_columns(np.asarray(proj), k, -1),
                ]
            )
            gens_all = np.concatenate(
                [
                    np.asarray(prev["evicted_at"])[keep],
                    np.full(len(ids_new), int(evicted_at), dtype=np.int64),
                ]
            )
            var_all = None
            if variances is not None or "variances" in prev:
                pv = prev.get("variances")
                pv = (
                    np.zeros_like(prev["coeffs"]) if pv is None else pv
                )
                nv = (
                    np.zeros_like(np.asarray(coeffs))
                    if variances is None
                    else np.asarray(variances)
                )
                var_all = np.concatenate(
                    [pad_columns(pv[keep], k, 0), pad_columns(nv, k, 0)]
                )
        else:
            ids_all = ids_new
            coeffs_all = np.asarray(coeffs)
            proj_all = np.asarray(proj)
            gens_all = np.full(len(ids_new), int(evicted_at), dtype=np.int64)
            var_all = None if variances is None else np.asarray(variances)

        path = self._archive_path(cid)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        arrays: dict = {
            "coeffs": coeffs_all,
            "proj": proj_all,
            "evicted_at": gens_all,
        }
        _encode_column("entity_ids", id_array(ids_all), arrays)
        if var_all is not None:
            arrays["variances"] = var_all
        arrays[_DIGEST_KEY] = np.asarray(_arrays_digest(arrays))
        # np.savez appends ".npz" to names lacking it: stage under one too;
        # the embedded digest makes the single os.replace the WHOLE commit
        # (a content+sidecar pair would have a torn window between renames
        # that no replay could repair)
        tmp = path + f"{_TMP_SUFFIX}-{os.getpid()}.npz"
        action = faultpoint(FP_COLD_WRITE)
        np.savez(tmp, **arrays)
        if action == "corrupt":
            corrupt_file(tmp)  # detectable bit-rot: damage lands post-digest
        os.replace(tmp, path)
        return path

    def archive_compact(self, cid: str, min_evicted_at: int) -> int:
        """Age out one coordinate's archive: drop entries whose eviction
        generation predates ``min_evicted_at`` (their coefficients are past
        the re-admission horizon — a reappearing entity that old re-solves
        from zero like a brand-new one). Surviving entries rewrite in place
        (staged + renamed, digest inside); an emptied archive removes its
        file. Idempotent: a crash-replayed pass recomputes the same cutoff
        and finds nothing left to drop, so the bytes converge. Returns the
        number of entries dropped."""
        prev = self.archive_load(cid)
        if prev is None:
            return 0
        gens = np.asarray(prev["evicted_at"])
        keep = np.flatnonzero(gens >= int(min_evicted_at))
        dropped = int(len(gens) - len(keep))
        if not dropped:
            return 0
        path = self._archive_path(cid)
        faultpoint(FP_COLD_DELETE)
        if not len(keep):
            os.remove(path)
            return dropped
        arrays: dict = {
            "coeffs": np.asarray(prev["coeffs"])[keep],
            "proj": np.asarray(prev["proj"])[keep],
            "evicted_at": gens[keep],
        }
        _encode_column(
            "entity_ids",
            id_array([prev["entity_ids"][i] for i in keep]),
            arrays,
        )
        if "variances" in prev:
            arrays["variances"] = np.asarray(prev["variances"])[keep]
        arrays[_DIGEST_KEY] = np.asarray(_arrays_digest(arrays))
        tmp = path + f"{_TMP_SUFFIX}-{os.getpid()}.npz"
        action = faultpoint(FP_COLD_WRITE)
        np.savez(tmp, **arrays)
        if action == "corrupt":
            corrupt_file(tmp)
        os.replace(tmp, path)
        return dropped


# --------------------------------------------------------------- chunk plumbing


def pad_columns(m: np.ndarray, k: int, fill) -> np.ndarray:
    """Widen a 2-D table to ``k`` columns with ``fill`` (dtype-preserving);
    shared by the archive merge and the carried-entity merge
    (continuous/compaction.py)."""
    m = np.asarray(m)
    if m.ndim != 2 or m.shape[1] == k:
        return m
    out = np.full((m.shape[0], k), fill, dtype=m.dtype)
    out[:, : m.shape[1]] = m
    return out


def _slice_chunk(chunk: dict, idx: np.ndarray) -> dict:
    out = {
        "features": {s: m.tocsr()[idx] for s, m in chunk["features"].items()},
        "ids": {t: c[idx] for t, c in chunk["ids"].items()},
    }
    for name in ("labels", "offsets", "weights", "row_gens", "uids"):
        out[name] = chunk[name][idx]
    return out


def _slice_snapshot(view: CorpusSnapshot, start: int) -> CorpusSnapshot:
    data = view.data
    return CorpusSnapshot(
        data=GameInput(
            features={s: m.tocsr()[start:] for s, m in data.features.items()},
            labels=np.asarray(data.labels)[start:],
            offsets=np.asarray(data.offsets)[start:],
            weights=np.asarray(data.weights)[start:],
            id_columns={t: np.asarray(c)[start:] for t, c in data.id_columns.items()},
        ),
        index_maps=view.index_maps,
        uids=view.uids[start:],
        row_gens=None if view.row_gens is None else view.row_gens[start:],
        start_row=view.start_row + start,
    )


def _empty_snapshot(index_maps: dict, widths: dict, id_tags) -> CorpusSnapshot:
    return CorpusSnapshot(
        data=GameInput(
            features={
                s: sp.csr_matrix((0, int(w)), dtype=np.float64)
                for s, w in widths.items()
            },
            labels=np.zeros(0, dtype=np.float64),
            offsets=np.zeros(0, dtype=np.float64),
            weights=np.zeros(0, dtype=np.float64),
            id_columns={t: np.zeros(0, dtype=object) for t in id_tags},
        ),
        index_maps=index_maps,
        uids=np.zeros(0, dtype=object),
        row_gens=np.zeros(0, dtype=np.int64),
    )


def _chunks_to_snapshot(
    chunks: list, index_maps: dict, widths: dict
) -> CorpusSnapshot:
    if not chunks:
        raise ValueError("cannot materialize an empty view")
    features = {
        s: sp.vstack([c["features"][s].tocsr() for c in chunks], format="csr")
        if len(chunks) > 1
        else chunks[0]["features"][s].tocsr()
        for s in widths
    }
    cat = (
        lambda name: np.concatenate([c[name] for c in chunks])
        if len(chunks) > 1
        else chunks[0][name]
    )
    data = GameInput(
        features=features,
        labels=cat("labels"),
        offsets=cat("offsets"),
        weights=cat("weights"),
        id_columns={
            tag: np.concatenate([c["ids"][tag] for c in chunks])
            if len(chunks) > 1
            else chunks[0]["ids"][tag]
            for tag in chunks[0]["ids"]
        },
    )
    return CorpusSnapshot(
        data=data,
        index_maps=index_maps,
        uids=cat("uids"),
        row_gens=cat("row_gens"),
    )


class _BlockWriter:
    """Re-blocking accumulator: takes arbitrarily sized row chunks, emits
    ``block_rows``-row blocks (the last one partial) into the content-
    addressed pool, each written staged + ``os.replace``-committed under its
    own SHA-256 name (idempotent: a crash-replayed fold rewrites identical
    bytes to identical names). Feature columns are re-encoded per block
    against the block's own column-id vocabulary (see :meth:`_emit`), so a
    block's digest is invariant to later index-map growth and the reuse fast
    path survives the feature axis widening. Holds at most ~2 blocks of rows
    at a time.
    :meth:`reuse` adopts an unchanged previous block by reference instead —
    the zero-copy fast path of an incremental compaction."""

    def __init__(self, pool_dir: str, block_rows: int, widths: dict, id_tags):
        self.pool_dir = pool_dir
        self.block_rows = block_rows
        self.widths = widths
        self.id_tags = tuple(id_tags)
        self.pending: list[dict] = []
        self.pending_rows = 0
        self.blocks: list[dict] = []
        self.n_rows = 0
        self.bytes_written = 0
        self.bytes_reused = 0
        self.blocks_reused = 0

    def push(self, chunk: dict) -> None:
        self.pending.append(chunk)
        self.pending_rows += len(chunk["labels"])
        while self.pending_rows >= self.block_rows:
            self._emit(self.block_rows)

    def reuse(self, block: dict, src_dir: Optional[str]) -> None:
        """Adopt one unchanged previous block by reference: pool blocks cost
        nothing (the digest IS the address); a legacy in-dir block enters the
        pool by hard link (fallback: copy — then its bytes honestly count as
        written, docs/PERFORMANCE.md). Pending partial rows flush first so
        row order is preserved — reuse never reorders the corpus."""
        if self.pending_rows:
            self._emit(self.pending_rows)
        faultpoint(FP_COLD_LINK)
        sha = block["sha256"]
        final = os.path.join(self.pool_dir, f"{sha}.npz")
        copied = 0
        if not os.path.exists(final):
            if "name" not in block:
                raise ColdStoreCorruption(
                    f"cold block {sha} vanished from the pool"
                )
            src = os.path.join(src_dir, block["name"])
            try:
                os.link(src, final)
            except FileExistsError:
                pass  # a crash-replayed fold already linked it
            except OSError:
                tmp = final + f"{_TMP_SUFFIX}-{os.getpid()}"
                shutil.copyfile(src, tmp)
                os.replace(tmp, final)
                copied = os.path.getsize(final)
        n = int(block["rows"][1]) - int(block["rows"][0])
        nbytes = int(block.get("nbytes") or os.path.getsize(final))
        self.blocks.append(
            {
                "sha256": sha,
                "rows": [self.n_rows, self.n_rows + n],
                "gen_lo": int(block["gen_lo"]),
                "gen_hi": int(block["gen_hi"]),
                "nbytes": nbytes,
            }
        )
        self.n_rows += n
        if copied:
            # a copy is real write I/O at BOTH granularities: counting the
            # block as reused would show an O(delta) block profile on a fold
            # that physically wrote O(history) (honest-ratio rules,
            # docs/PERFORMANCE.md)
            self.bytes_written += copied
        else:
            self.bytes_reused += nbytes
            self.blocks_reused += 1

    def finish(self) -> tuple[list, int]:
        while self.pending_rows > 0:
            self._emit(min(self.block_rows, self.pending_rows))
        return self.blocks, self.n_rows

    def io_stats(self) -> dict:
        return {
            "bytes_written": int(self.bytes_written),
            "bytes_reused": int(self.bytes_reused),
            "blocks_written": len(self.blocks) - self.blocks_reused,
            "blocks_reused": int(self.blocks_reused),
        }

    def _emit(self, rows: int) -> None:
        take: list[dict] = []
        remaining = rows
        while remaining > 0:
            head = self.pending[0]
            n = len(head["labels"])
            if n <= remaining:
                take.append(self.pending.pop(0))
                remaining -= n
            else:
                idx = np.arange(remaining)
                take.append(_slice_chunk(head, idx))
                self.pending[0] = _slice_chunk(head, np.arange(remaining, n))
                remaining = 0
        self.pending_rows -= rows

        merged = take[0] if len(take) == 1 else {
            "features": {
                s: sp.vstack([c["features"][s] for c in take], format="csr")
                for s in self.widths
            },
            "ids": {
                t: np.concatenate([c["ids"][t] for c in take])
                for t in self.id_tags
            },
            **{
                name: np.concatenate([c[name] for c in take])
                for name in ("labels", "offsets", "weights", "row_gens", "uids")
            },
        }
        arrays: dict = {}
        for name in ("labels", "offsets", "weights", "row_gens"):
            arrays[name] = np.asarray(merged[name])
        _encode_column("uids", merged["uids"], arrays)
        for tag in self.id_tags:
            _encode_column(f"id__{tag}", merged["ids"][tag], arrays)
        for shard in self.widths:
            m = merged["features"][shard].tocsr()
            arrays[f"feat__{shard}__data"] = m.data
            # block-level column re-encoding: persist the block's OWN sorted
            # column-id vocabulary (``colids`` — global frozen-IndexMap ids,
            # original index dtype) plus indices LOCAL to it, in the smallest
            # unsigned dtype that spans the vocabulary. Block bytes therefore
            # depend only on the columns the block's rows actually touch —
            # the feature axis can grow 100x (IndexMap.extend) without a
            # single existing block changing content or digest, and a block
            # over a K-wide corpus costs O(distinct cols) not O(K) per index.
            colids = np.unique(np.asarray(m.indices))
            local = np.searchsorted(colids, m.indices).astype(
                np.min_scalar_type(max(len(colids) - 1, 0))
            )
            arrays[f"feat__{shard}__colids"] = colids
            arrays[f"feat__{shard}__indices"] = local
            arrays[f"feat__{shard}__indptr"] = m.indptr
        tmp = os.path.join(
            self.pool_dir,
            f"{_TMP_SUFFIX}-{os.getpid()}-{len(self.blocks):06d}.npz",
        )
        action = faultpoint(FP_COLD_WRITE)
        np.savez(tmp, **arrays)
        sha = _sha256_file(tmp)
        # content-addressed commit: the digest IS the file name, so a
        # crash-replayed fold re-lands identical bytes on identical names
        # (os.replace over an already-published block is a no-op by content)
        path = os.path.join(self.pool_dir, f"{sha}.npz")
        os.replace(tmp, path)
        if action == "corrupt":
            corrupt_file(path)  # post-checksum: exactly what reads must catch
        gens = np.asarray(merged["row_gens"])
        self.blocks.append(
            {
                "sha256": sha,
                "rows": [self.n_rows, self.n_rows + rows],
                "gen_lo": int(gens.min()),
                "gen_hi": int(gens.max()),
                "nbytes": os.path.getsize(path),
            }
        )
        self.n_rows += rows
        self.bytes_written += os.path.getsize(path)
