"""The continuous-training generation loop: poll → delta → commit → serve.

Layer 3 wires the subsystem into the full photon-ml-tpu story as ONE
unattended process per model:

1. **poll** — ``CorpusManifest.scan`` diffs the corpus directories against
   the manifest persisted in the last committed generation; no new part
   files, no work.
2. **delta pass** — ``ingest.ingest_delta`` decodes only the new files and
   grows the corpus (stable index-map growth); datasets rebuild with the
   previous generation's entity ROW ORDER pinned
   (``build_random_effect_dataset(entity_order=...)``) so the old coefficient
   tables align by construction; ``active_set.select_active_entities`` picks
   the working set (new data ∪ new entities ∪ gradient screen); coordinate
   descent runs with ``active_sets`` — random effects re-solve only the
   active entities via the shared vmapped solver body, the fixed effect
   refreshes over a weight-masked reservoir of old+new rows, and the
   divergence guard / incident machinery from PR 3/4 applies unchanged.
3. **commit** — the new model state lands as a PR 3 generational checkpoint
   ``gen-<n>/`` (staged + renamed, SHA-256 manifest) carrying the corpus
   manifest and delta stats in ``extra_state`` and the frozen index maps as
   ``aux`` artifacts — everything a restarted trainer needs to rebuild its
   corpus and resume, and exactly the layout PR 6's ``GenerationWatcher``
   polls, so a committed delta generation hot-swaps into live serving with
   zero downtime.

Crash safety: nothing durable mutates until the atomic checkpoint commit, so
a crash anywhere in a delta pass (``continuous.*`` fault points) simply
replays the pass on restart from the previous generation — bit-identically,
because every input (manifest order, frozen index maps, entity order, seeded
reservoir) is restored from the committed generation. The optional per-
generation model EXPORT (reference BayesianLinearModelAvro bytes, which are
byte-deterministic) is staged + renamed too, and re-exported idempotently on
restart if a crash separated it from its commit.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import shutil
import time
from typing import Mapping, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinate import FixedEffectCoordinate
from photon_ml_tpu.algorithm.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.algorithm.random_effect import random_effect_gradient_norms
from photon_ml_tpu.continuous.active_set import (
    ReservoirDownSampler,
    select_active_entities,
)
from photon_ml_tpu.continuous.compaction import (
    FP_COMPACT,
    archived_rows_for,
    drop_entities,
    inject_archived_rows,
    merge_carried_entities,
    plan_eviction,
)
from photon_ml_tpu.continuous.ingest import CorpusSnapshot
from photon_ml_tpu.continuous.manifest import CorpusManifest
from photon_ml_tpu.continuous.store import (
    DEFAULT_BLOCK_ROWS,
    CorpusStore,
    LiveSegment,
    decay_weights,
    id_array,
)
from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.estimators.config import RandomEffectDataConfiguration
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.io.checkpoint import load_checkpoint, save_checkpoint
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.resilience import faultpoint, register_fault_point
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)

FP_COMMIT = register_fault_point("continuous.commit")


def _native_id(e):
    """npz round-trip for entity ids: numpy scalars back to the native
    int/str the model's entity tuples carry."""
    if isinstance(e, (np.integer, int)) and not isinstance(e, bool):
        return int(e)
    return str(e)



_AUX_INDEX_MAP_PREFIX = "index-map-"
_AUX_LAST_ACTIVE_PREFIX = "last-active-"
_AUX_EVICTED_PREFIX = "evicted-"

WINDOW_MODES = ("full", "sliding", "decay")


@dataclasses.dataclass
class ContinuousTrainerConfig:
    """Static configuration of one continuous trainer process."""

    corpus_paths: Sequence[str]
    checkpoint_directory: str
    task: TaskType
    coordinate_configurations: Mapping  # {cid: CoordinateConfiguration}, ordered
    shard_configurations: Mapping  # {shard_id: FeatureShardConfiguration}
    delta_iterations: int = 1  # coordinate-descent iterations per delta pass
    initial_iterations: int = 1  # iterations for the bootstrap full train
    # rule-3 screen: re-solve entities whose subproblem gradient norm exceeds
    # this even without new rows (None = new-data/new-entity rules only)
    gradient_threshold: Optional[float] = None
    # fixed-effect refresh reservoir: how many OLD rows keep weight in a
    # delta pass (None = all of them; delta rows always train)
    fe_reservoir: Optional[int] = None
    export_directory: Optional[str] = None  # per-generation model export
    ingest_workers: Optional[int] = None
    keep_generations: int = 8
    seed: int = 0
    dtype: object = jnp.float32
    # random-effect inner bucket solver, inherited by BOTH the bootstrap full
    # train and every delta pass's active-set sub-bucket solves
    # (optimization/normal_equations.py): "lbfgs" | "direct" | "auto".
    # Direct solves fit continuous training's access pattern especially well:
    # delta passes are always warm-started from the previous generation, the
    # regime where the Newton loop converges in 1-2 steps.
    re_solver: str = "lbfgs"
    # device-resident working set for random-effect tables (data/
    # working_set.py), inherited by the bootstrap full train and every delta
    # pass: None = all-resident (status quo); an int bounds device-resident
    # table rows per coordinate; "auto" = all-resident when tables fit. The
    # streamed backlog bootstrap (max_files_per_pass) already feeds host
    # tables, so corpus -> host -> device becomes one pipeline. When the
    # gradient screen runs, its norms become the admission priorities.
    # Execution strategy, bitwise-neutral: stays out of the fingerprint.
    re_working_set_rows: object = None
    # SPMD backend: a jax.sharding.Mesh places every generation's datasets
    # (and the delta pass's gathered active sub-buckets) over the device
    # mesh — bootstrap and delta passes then run as sharded programs with
    # entity-sharded coefficient tables (parallel/placement.py). None =
    # single-device host placement.
    mesh: Optional[object] = None
    # ---- unbounded-horizon knobs (continuous/store.py, compaction.py) ----
    # fold the corpus into a new cold generation + truncate the manifest's
    # per-file history every N committed generations (None = never compact;
    # RAM/restart cost then grows with the live history)
    compact_every: Optional[int] = None
    # drop random-effect entities with no rows in the last G generations from
    # the device tables (archived; serving degrades to the missing-entity
    # score-0 contract; re-admission warm-starts from the archive)
    evict_idle_generations: Optional[int] = None
    # row aging: "full" trains on every accumulated row (PR 7 behavior);
    # "sliding" drops rows older than window_generations from the training
    # view (RAM O(window), shapes constant in steady state); "decay" also
    # down-weights rows in-view by 2^(-age/decay_half_life) — weights derived
    # in-trace from row-age metadata, so crash-replay stays bit-identical and
    # generation advance never retraces
    window_mode: str = "full"
    window_generations: Optional[int] = None
    decay_half_life: Optional[float] = None
    cold_block_rows: int = DEFAULT_BLOCK_ROWS
    # ---- retention & streaming knobs (the O(delta) cold tier, PR 15) ----
    # cold-tier row retention: at each compaction, DELETE rows older than
    # this many generations (must cover the training window, so deletion can
    # only touch rows whose training weight is already zero — the training
    # math is untouched by construction and the knob stays out of the
    # fingerprint). None = the cold tier preserves full history.
    max_row_age_gens: Optional[int] = None
    # best-effort cap on cold-tier rows, enforced at BLOCK granularity at
    # each compaction (oldest blocks drop first; blocks still reaching the
    # training window are never dropped, so the cap may be overshot while
    # the window needs the rows)
    max_cold_rows: Optional[int] = None
    # archive age-out: at each compaction, drop evicted-coefficient archive
    # entries whose eviction is older than this many generations (a that-old
    # reappearing entity re-solves from zero like a brand-new one)
    archive_max_age_gens: Optional[int] = None
    # streaming bootstrap / backlog pacing: ingest at most this many part
    # files per pass. A fresh trainer pointed at a DEEP pre-existing corpus
    # then replays the backlog incrementally through the same windowed delta
    # passes a live trainer runs — resident corpus bytes stay
    # O(window + delta) instead of one O(corpus) bootstrap materialization,
    # and the committed generations are byte-identical to a trainer that
    # lived through the history at the same file-per-pass pacing. Grouping
    # mirrors arrival pacing (external to the model), so it stays out of the
    # fingerprint. None = ingest everything the scan finds (PR 7 behavior).
    max_files_per_pass: Optional[int] = None


@dataclasses.dataclass
class GenerationResult:
    """One committed generation's paper trail."""

    generation: int
    kind: str  # "bootstrap" | "delta"
    n_rows: int  # TOTAL accumulated rows across both tiers
    n_new_rows: int
    checkpoint_path: str
    # cid -> {n_entities, n_active, active_fraction, n_new_data,
    #         n_new_entities, n_gradient, n_solved_lanes, n_evicted,
    #         n_readmitted, n_carried}
    active: dict
    incidents: list
    timings: dict  # phase -> seconds
    view_rows: int = 0  # rows materialized in the training view (the window)
    compacted: bool = False  # this commit folded the corpus into a cold gen
    # compaction I/O: {bytes_written, bytes_reused, blocks_written,
    # blocks_reused, blocks_dropped, rows_dropped} — the block-reuse /
    # retention paper trail (None on non-compacting passes)
    cold_stats: Optional[dict] = None

    @property
    def active_fraction(self) -> float:
        """Aggregate re-solved fraction across random-effect coordinates."""
        tot = sum(a["n_entities"] for a in self.active.values())
        act = sum(a["n_active"] for a in self.active.values())
        return act / tot if tot else 0.0


class ContinuousTrainer:
    """Drives the ingest → active-set train → commit loop for one model.

    Construct it pointed at a checkpoint directory: an existing continuous
    checkpoint is restored (warm state, corpus rebuilt from the persisted
    manifest with frozen index maps), otherwise the first ``poll_once`` with
    data bootstraps generation 1 with a full train. Call :meth:`poll_once`
    from a control loop (or :meth:`run`)."""

    def __init__(self, config: ContinuousTrainerConfig):
        self.config = config
        self.task = TaskType(config.task)
        from photon_ml_tpu.estimators.config import expand_game_configurations

        sweep = expand_game_configurations(config.coordinate_configurations)
        if len(sweep) != 1:
            raise ValueError(
                f"continuous training drives ONE optimization configuration; "
                f"the given coordinate configurations expand to {len(sweep)} "
                "(drop the extra regularization weights)"
            )
        self.opt_configs = sweep[0]
        self.estimator = GameEstimator(
            task=self.task,
            coordinate_configurations=config.coordinate_configurations,
            n_iterations=config.delta_iterations,
            dtype=config.dtype,
            re_solver=config.re_solver,
            mesh=config.mesh,
            re_working_set_rows=config.re_working_set_rows,
        )
        self.re_types = {
            cid: cfg.data_config.random_effect_type
            for cid, cfg in config.coordinate_configurations.items()
            if isinstance(cfg.data_config, RandomEffectDataConfiguration)
        }
        if config.fe_reservoir is not None:
            for cid, cfg in config.coordinate_configurations.items():
                if cid in self.re_types:
                    continue
                if 0.0 < getattr(cfg, "down_sampling_rate", 1.0) < 1.0:
                    # the reservoir REPLACES the coordinate's down-sampler on
                    # delta passes: combining them would train the bootstrap
                    # under the configured sampling weights and every delta
                    # under reservoir weights — two different FE objectives
                    raise ValueError(
                        f"fe_reservoir cannot be combined with coordinate "
                        f"{cid!r}'s down.sampling.rate="
                        f"{cfg.down_sampling_rate}; drop one of the two"
                    )
        self.id_tags = sorted(set(self.re_types.values()))
        self._validate_window_config()
        self.manifest = CorpusManifest()
        self.store = CorpusStore(
            os.path.join(config.checkpoint_directory, "corpus-store"),
            config.shard_configurations,
            self.id_tags,
            block_rows=config.cold_block_rows,
            ingest_workers=config.ingest_workers,
        )
        self.models: Optional[dict] = None
        self.generation = 0
        self.last_result: Optional[GenerationResult] = None
        # eviction bookkeeping (persisted as aux arrays in every commit):
        # cid -> {entity_id: last generation with data} and cid -> evicted ids
        self.last_active: dict = {cid: {} for cid in self.re_types}
        self.evicted: dict = {cid: set() for cid in self.re_types}
        self._restore()

    def _validate_window_config(self) -> None:
        cfg = self.config
        if cfg.window_mode not in WINDOW_MODES:
            raise ValueError(
                f"window_mode must be one of {WINDOW_MODES}, got {cfg.window_mode!r}"
            )
        if cfg.window_mode == "sliding" and not cfg.window_generations:
            raise ValueError("window_mode='sliding' requires window_generations")
        if cfg.window_mode == "decay" and not cfg.decay_half_life:
            raise ValueError("window_mode='decay' requires decay_half_life")
        if cfg.window_mode == "full" and cfg.window_generations:
            raise ValueError(
                "window_generations has no effect with window_mode='full'; "
                "pick 'sliding' or 'decay'"
            )
        if cfg.window_mode != "decay" and cfg.decay_half_life is not None:
            raise ValueError(
                f"decay_half_life has no effect with window_mode="
                f"{cfg.window_mode!r}; pass window_mode='decay' (a silently "
                "ignored half-life would train a different model than asked)"
            )
        for knob in (
            "window_generations", "evict_idle_generations", "compact_every",
            "max_row_age_gens", "max_cold_rows", "archive_max_age_gens",
            "max_files_per_pass",
        ):
            v = getattr(cfg, knob)
            if v is not None and v < 1:
                raise ValueError(f"{knob} must be >= 1, got {v}")
        if cfg.evict_idle_generations and not self.re_types:
            raise ValueError(
                "evict_idle_generations needs at least one random-effect "
                "coordinate (the fixed effect has no entities to evict)"
            )
        # retention may only delete rows the training window already weighs
        # zero — anything else would silently train a different model
        for knob in ("max_row_age_gens", "max_cold_rows"):
            if getattr(cfg, knob) is None:
                continue
            if cfg.window_mode == "full" or not cfg.window_generations:
                raise ValueError(
                    f"{knob} requires a bounded training window "
                    "(window_mode='sliding' or 'decay' with "
                    "window_generations): with an unbounded window every "
                    "accumulated row still trains, so retention would "
                    "delete rows the model needs"
                )
            if not cfg.compact_every:
                raise ValueError(
                    f"{knob} acts at compaction time; set compact_every "
                    "or the knob silently never fires"
                )
        if (
            cfg.max_row_age_gens is not None
            and cfg.window_generations
            and cfg.max_row_age_gens < cfg.window_generations
        ):
            raise ValueError(
                f"max_row_age_gens ({cfg.max_row_age_gens}) must cover the "
                f"training window ({cfg.window_generations} generations): "
                "retention inside the window would delete rows that still "
                "carry training weight"
            )
        if cfg.archive_max_age_gens is not None:
            if not cfg.evict_idle_generations:
                raise ValueError(
                    "archive_max_age_gens ages out the EVICTION archive; "
                    "it needs evict_idle_generations"
                )
            if not cfg.compact_every:
                raise ValueError(
                    "archive_max_age_gens acts at compaction time; set "
                    "compact_every or the knob silently never fires"
                )

    @property
    def snapshot(self) -> Optional[CorpusSnapshot]:
        """The materialized training view (the store's hot surface). In
        ``full`` window mode this is the whole accumulated corpus — the PR 7
        snapshot, unchanged; with a sliding window it is the in-window tail."""
        return self.store.view

    def _window_min_gen(self, generation: int) -> int:
        """Oldest generation whose rows the view for pass ``generation``
        keeps (0 = everything)."""
        w = self.config.window_generations
        if self.config.window_mode == "full" or not w:
            return 0
        return max(0, int(generation) - int(w) + 1)

    def _retention_min_gen(self, generation: int) -> int:
        """Oldest generation the cold tier RETAINS at pass ``generation``'s
        compaction (0 = keep everything). Validation pins this at or below
        the window floor, so deletion only ever reaches zero-weight rows."""
        r = self.config.max_row_age_gens
        if not r:
            return 0
        return max(0, int(generation) - int(r) + 1)

    def _archive_min_evicted_at(self, generation: int) -> Optional[int]:
        """Archive age-out horizon at pass ``generation``: entries evicted
        before this never warm re-admit, and ``archive_compact`` physically
        drops them at compaction cadence. None when age-out is off."""
        a = self.config.archive_max_age_gens
        if not a:
            return None
        return int(generation) - int(a)

    # ------------------------------------------------------------- restore

    def _fingerprint(self) -> str:
        parts = [f"continuous|{self.task.value}"]
        for cid in sorted(self.config.coordinate_configurations):
            parts.append(f"{cid}={self.opt_configs[cid]!r}")
        # window/eviction change the TRAINING MATH (which rows carry weight,
        # which entities keep tables): a rerun with different settings must
        # retrain, not silently adopt the other regime's state (the stale-
        # restore lesson). Compaction cadence and block size do NOT — they
        # only move bytes between tiers bit-preservingly — so they stay out.
        cfg = self.config
        if cfg.window_mode != "full":
            parts.append(
                f"window={cfg.window_mode}:{cfg.window_generations}"
                f":{cfg.decay_half_life}"
            )
        if cfg.evict_idle_generations:
            parts.append(f"evict={cfg.evict_idle_generations}")
        # the archive horizon decides which re-admissions warm-start — that
        # IS training math, unlike max_row_age_gens/max_cold_rows, which
        # only delete rows the window already weighs zero
        if cfg.archive_max_age_gens:
            parts.append(f"archive_age={cfg.archive_max_age_gens}")
        return "|".join(parts)

    def _restore(self) -> None:
        restored = load_checkpoint(
            self.config.checkpoint_directory,
            dtype=self.config.dtype,
            fingerprint=self._fingerprint(),
        )
        if restored is None:
            return
        extra = (restored.get("extra") or {}).get("continuous")
        if extra is None:
            logger.warning(
                "checkpoint %s carries no continuous-training state; starting "
                "a fresh corpus history on top of it",
                self.config.checkpoint_directory,
            )
            return
        index_maps = {}
        aux = restored.get("aux") or {}
        for shard in self.config.shard_configurations:
            arrs = aux.get(f"{_AUX_INDEX_MAP_PREFIX}{shard}")
            if arrs is None:
                raise ValueError(
                    f"continuous checkpoint is missing the frozen index map "
                    f"for shard {shard!r}; cannot rebuild the corpus"
                )
            index_maps[shard] = IndexMap([str(n) for n in arrs["names"]])
        self.manifest = CorpusManifest.from_dict(extra["corpus_manifest"])
        # full-content check BEFORE the rebuild read: a same-size rewrite of
        # a LIVE part file (size checks pass) would otherwise rebuild a
        # corpus that silently differs from what the warm-start model
        # absorbed. Compacted files are exempt: the cold tier owns their
        # bytes under its own per-block checksums.
        self.manifest.verify_fingerprints()
        self.models = restored["models"]
        self.generation = int(restored.get("generation") or 0)
        self._restore_eviction_state(aux)

        store_state = extra.get("store")
        if store_state is None:
            # pre-store checkpoint layout: the whole manifest is one live
            # segment stamped with the committed generation (row ages are
            # only consumed by window modes, which always persist store state)
            self.store.adopt_state(None)
            self.store.segments = [
                LiveSegment(
                    generation=self.generation,
                    n_files=len(self.manifest.entries),
                    n_rows=int(extra["n_rows"]),
                )
            ]
        else:
            self.store.adopt_state(store_state)
        self.store.materialize(
            index_maps,
            self.manifest,
            min_gen=self._window_min_gen(self.generation),
        )
        logger.info(
            "restored continuous state: generation %d, %d corpus rows "
            "(%d materialized in the view, %d cold), %d part files",
            self.generation,
            self.store.total_rows,
            self.store.view.n_rows,
            self.store.cold_rows,
            len(self.manifest),
        )
        # a crash between commit and export leaves the export missing: redo
        # it idempotently (export bytes are a pure function of the models)
        self._maybe_export(self.generation)

    def _restore_eviction_state(self, aux: dict) -> None:
        for cid in self.re_types:
            la = aux.get(f"{_AUX_LAST_ACTIVE_PREFIX}{cid}")
            if la is not None:
                ids = [_native_id(e) for e in la["ids"]]
                self.last_active[cid] = dict(
                    zip(ids, (int(g) for g in la["gens"]))
                )
            ev = aux.get(f"{_AUX_EVICTED_PREFIX}{cid}")
            if ev is not None:
                self.evicted[cid] = {_native_id(e) for e in ev["ids"]}

    # --------------------------------------------------------------- export

    def _index_maps_by_coord(self) -> dict:
        return {
            cid: self.snapshot.index_maps[cfg.data_config.feature_shard_id]
            for cid, cfg in self.config.coordinate_configurations.items()
        }

    def _maybe_export(self, generation: int) -> Optional[str]:
        if self.config.export_directory is None or self.models is None:
            return None
        from photon_ml_tpu.io.model_io import save_game_model

        target = os.path.join(
            self.config.export_directory, f"gen-{generation:08d}"
        )
        if os.path.isdir(target):
            return target
        tmp = target + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        save_game_model(tmp, GameModel(models=self.models), self._index_maps_by_coord())
        os.rename(tmp, target)
        return target

    # ------------------------------------------------------------ delta pass

    def _pad_fixed_effect(self, model: FixedEffectModel, dim: int) -> FixedEffectModel:
        """Stable feature growth for the fixed effect: unseen features append
        at the index-map tail, so the previous coefficient vector aligns by
        zero-padding at the tail — no remapping."""
        coef = model.model.coefficients
        means = coef.means
        if means.shape[0] >= dim:
            return model
        pad = dim - means.shape[0]
        means = jnp.concatenate([means, jnp.zeros((pad,), dtype=means.dtype)])
        variances = coef.variances
        if variances is not None:
            variances = jnp.concatenate(
                [variances, jnp.zeros((pad,), dtype=variances.dtype)]
            )
        new_coef = dataclasses.replace(coef, means=means, variances=variances)
        return dataclasses.replace(
            model, model=dataclasses.replace(model.model, coefficients=new_coef)
        )

    def _base_offsets(self):
        """The [N] base-offset vector at the backend's placement: padded and
        sample-sharded on a mesh (placed datasets carry a padded sample axis,
        so every score/offset array must match), plain device array on the
        host backend."""
        off = np.asarray(self.snapshot.data.offsets)
        if self.config.mesh is not None:
            from photon_ml_tpu.parallel.placement import pad_and_shard_vector

            return pad_and_shard_vector(
                off, self.config.mesh, dtype=self.config.dtype
            )
        return jnp.asarray(off, dtype=self.config.dtype)

    def _adapted_models(self, datasets: dict, prev_models: dict) -> dict:
        """Previous-generation models adapted to the grown datasets: fixed
        effects zero-pad to the grown feature dim, random effects re-layout
        by entity id (tail growth makes this a cheap identity-or-append)."""
        out = {}
        for cid, model in prev_models.items():
            ds = datasets[cid]
            if isinstance(model, FixedEffectModel):
                out[cid] = self._pad_fixed_effect(model, ds.dim)
            elif isinstance(model, RandomEffectModel):
                out[cid] = model.aligned_to(ds)
            else:
                out[cid] = model
        return out

    def _select_active_sets(
        self, datasets: dict, adapted: dict, delta_entities: dict,
        prev_models: dict,
    ) -> tuple[dict, dict]:
        """Per-RE-coordinate active masks + stats. The optional gradient
        screen evaluates each coordinate's subproblem gradient at the
        warm-start coefficients against the OTHER coordinates' current
        scores (one cheap vmapped pass per bucket shape)."""
        base_offsets = self._base_offsets()
        scores = None
        if self.config.gradient_threshold is not None:
            from photon_ml_tpu.algorithm.coordinate import score_model_on_dataset

            scores = {
                cid: score_model_on_dataset(adapted[cid], datasets[cid])
                for cid in datasets
            }
            total = sum(scores.values())
        active_sets: dict = {}
        stats: dict = {}
        for cid, re_type in self.re_types.items():
            ds = datasets[cid]
            norms = None
            if scores is not None:
                cfg = self.opt_configs[cid]
                norms = random_effect_gradient_norms(
                    ds,
                    adapted[cid],
                    base_offsets + (total - scores[cid]),
                    self.task,
                    l2=cfg.l2_weight,
                    per_entity_reg_weights=self.config.coordinate_configurations[
                        cid
                    ].per_entity_reg_weights,
                    dtype=self.config.dtype,
                )
            if norms is not None and self.config.re_working_set_rows is not None:
                # the gradient screen doubles as the working set's admission
                # priority: the hottest entities (by subproblem gradient at
                # the warm start) claim device residency on the coordinates
                # the NEXT build constructs
                import jax

                priorities = dict(self.estimator.re_working_set_priorities or {})
                priorities[cid] = np.asarray(jax.device_get(norms))  # jaxlint: disable=HS001 once-per-coordinate boundary read, admission priorities live host-side
                self.estimator.re_working_set_priorities = priorities
            sel = select_active_entities(
                ds,
                delta_entities.get(re_type, set()),
                prev_model=prev_models.get(cid),
                gradient_norms=norms,
                gradient_threshold=self.config.gradient_threshold,
            )
            active_sets[cid] = sel.mask
            stats[cid] = {
                "n_entities": ds.n_entities,
                "n_active": sel.n_active,
                "active_fraction": sel.n_active / ds.n_entities
                if ds.n_entities
                else 0.0,
                "n_new_data": sel.n_new_data,
                "n_new_entities": sel.n_new_entities,
                "n_gradient": sel.n_gradient,
            }
        return active_sets, stats

    # ----------------------------------------------------- eviction plumbing

    def _plan_evictions(
        self, prev_models: dict, delta_entities: dict, generation: int
    ) -> tuple[dict, dict, dict]:
        """Eviction/re-admission verdicts for one pass. Returns
        (pruned previous models, plans per cid, updated evicted sets).
        Without ``evict_idle_generations`` this is an identity pass (no
        fault point fires, no bookkeeping is consulted)."""
        if not self.config.evict_idle_generations:
            return prev_models, {}, {
                cid: set(s) for cid, s in self.evicted.items()
            }
        pruned = dict(prev_models)
        plans: dict = {}
        evicted_next: dict = {}
        for cid, re_type in self.re_types.items():
            model = prev_models.get(cid)
            plan = plan_eviction(
                model if isinstance(model, RandomEffectModel) else None,
                self.last_active.get(cid, {}),
                delta_entities.get(re_type, set()),
                self.evicted.get(cid, set()),
                generation,
                self.config.evict_idle_generations,
            )
            plans[cid] = plan
            evicted_next[cid] = (
                set(self.evicted.get(cid, set())) - set(plan.readmit)
            ) | set(plan.evict)
            if plan.evict and isinstance(model, RandomEffectModel):
                # park the coefficients BEFORE dropping the rows; the write is
                # staged+renamed and idempotent (a crash-replayed pass rewrites
                # identical bytes), so it may land ahead of the commit
                payload = archived_rows_for(model, plan.evict)
                self.store.archive_write(
                    cid,
                    payload["entity_ids"],
                    payload["coeffs"],
                    payload["proj"],
                    payload["variances"],
                    evicted_at=generation,
                )
                pruned[cid] = drop_entities(model, plan.evict)
        return pruned, plans, evicted_next

    def _updated_last_active(self, datasets: dict, delta_entities: dict,
                             generation: int) -> dict:
        """Next generation's last-data bookkeeping: entities with delta rows
        stamp ``generation``; entities seen for the first time (bootstrap or
        re-admitted) stamp too; everyone else keeps their stamp."""
        out = {}
        for cid, re_type in self.re_types.items():
            la = dict(self.last_active.get(cid, {}))
            fresh = delta_entities.get(re_type, set())
            for e in fresh:
                la[e] = generation
            for e in datasets[cid].entity_ids:
                la.setdefault(e, generation)
            out[cid] = la
        return out

    def _eviction_aux_arrays(self, last_active: dict, evicted: dict) -> dict:
        aux: dict = {}
        if not self.config.evict_idle_generations:
            return aux
        for cid in self.re_types:
            la = last_active.get(cid, {})
            ids = list(la)
            aux[f"{_AUX_LAST_ACTIVE_PREFIX}{cid}"] = {
                "ids": id_array(ids),
                "gens": np.asarray([la[e] for e in ids], dtype=np.int64),
            }
            aux[f"{_AUX_EVICTED_PREFIX}{cid}"] = {
                "ids": id_array(sorted(evicted.get(cid, set()))),
            }
        return aux

    def _train_data(self, view: CorpusSnapshot, generation: int):
        """The pass's training GameInput: the view verbatim, or the view with
        time-decayed weights (``decay`` mode — one device program per view
        shape, generation as a traced scalar, bit-identical on replay)."""
        if self.config.window_mode != "decay":
            return view.data
        if view.row_gens is None:
            raise ValueError("decay weighting needs row_gens on the view")
        return dataclasses.replace(
            view.data,
            weights=decay_weights(
                view.data.weights,
                view.row_gens,
                generation,
                self.config.decay_half_life,
            ),
        )

    def poll_once(self) -> Optional[GenerationResult]:
        """One turn of the loop: scan, and if the corpus grew, run a delta
        pass (or the bootstrap full train) and commit the next generation.
        Returns the committed generation's record, or None when idle."""
        timings: dict = {}
        t0 = time.perf_counter()
        new_files = self.manifest.scan(self.config.corpus_paths)
        timings["scan"] = time.perf_counter() - t0
        if not new_files:
            return None
        cap = self.config.max_files_per_pass
        if cap is not None and len(new_files) > cap:
            # streaming bootstrap / backlog pacing: drain a deep corpus in
            # bounded per-pass bites (oldest first — listing order IS ingest
            # order); the next poll picks up where this one stopped
            new_files = new_files[:cap]
        bootstrap = self.models is None
        gen_next = self.generation + 1

        t0 = time.perf_counter()
        # record each new file's size/fingerprint BEFORE decoding it and
        # re-verify after: the bracket turns a file an upstream writer was
        # still appending to into a loud CorpusContractViolation instead of
        # a manifest record that disagrees with the rows the model absorbed
        grown_manifest = self.manifest.extend(new_files)
        prev_maps = None if self.snapshot is None else self.snapshot.index_maps
        if not bootstrap:
            # advance the sliding window BEFORE the append: rows aged out of
            # the pass's view drop as one contiguous head slice
            self.store.trim_view(self._window_min_gen(gen_next))
        view, delta = self.store.stage_delta(new_files, gen_next)
        try:
            # from here on the delta is STAGED: every exit path that is not
            # the commit must run abort_delta (the except below), or the next
            # poll would refuse with a pending stage — including a torn-write
            # CorpusContractViolation from this verify
            grown_manifest.verify_sizes(
                grown_manifest.entries[len(self.manifest.entries):]
            )
            timings["ingest"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            prev_models = dict(self.models or {})
            prev_models, eviction_plans, evicted_next = self._plan_evictions(
                prev_models, delta.delta_entities, gen_next
            )
            entity_orders = None
            if not bootstrap:
                entity_orders = {
                    cid: prev_models[cid].entity_ids
                    for cid in self.re_types
                    if isinstance(prev_models.get(cid), RandomEffectModel)
                }
            datasets = self.estimator.prepare_training_datasets(
                self._train_data(view, gen_next),
                entity_orders=entity_orders,
                exclude_entities={
                    cid: evicted_next[cid]
                    for cid in self.re_types
                    if evicted_next.get(cid)
                },
            )
            if self.config.mesh is not None:
                from photon_ml_tpu.parallel.placement import place_game_datasets

                datasets = place_game_datasets(datasets, self.config.mesh)
            timings["datasets"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            active_sets = None
            active_stats: dict = {}
            initial_models = None
            if not bootstrap:
                adapted = self._adapted_models(datasets, prev_models)
                # re-admission: a previously evicted entity reappearing in the
                # delta warm-starts from its archived coefficients instead of
                # the zero row aligned_to gave the "new" entity
                readmitted: dict = {}
                for cid, plan in eviction_plans.items():
                    back = [
                        e
                        for e in plan.readmit
                        if isinstance(adapted.get(cid), RandomEffectModel)
                        and adapted[cid].row_for_entity(e) >= 0
                    ]
                    if back:
                        adapted[cid], n = inject_archived_rows(
                            adapted[cid],
                            self.store.archive_load(cid),
                            back,
                            min_evicted_at=self._archive_min_evicted_at(
                                gen_next
                            ),
                        )
                        readmitted[cid] = n
                    # a reappearing entity that got NO model row (its delta
                    # rows fell below active_data_lower_bound) stays evicted:
                    # dropping it from the set here would orphan its archived
                    # coefficients — the next reappearance would zero-init
                    not_back = set(plan.readmit) - set(back)
                    if not_back:
                        evicted_next[cid] = evicted_next[cid] | not_back
                active_sets, active_stats = self._select_active_sets(
                    datasets, adapted, delta.delta_entities, prev_models
                )
                for cid, plan in eviction_plans.items():
                    if cid in active_stats:
                        active_stats[cid]["n_evicted"] = len(plan.evict)
                        active_stats[cid]["n_readmitted"] = readmitted.get(cid, 0)
                initial_models = adapted
            else:
                for cid, re_type in self.re_types.items():
                    ds = datasets[cid]
                    active_stats[cid] = {
                        "n_entities": ds.n_entities,
                        "n_active": ds.n_entities,
                        "active_fraction": 1.0,
                        "n_new_data": ds.n_entities,
                        "n_new_entities": ds.n_entities,
                        "n_gradient": 0,
                    }
            timings["select"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            base_offsets = self._base_offsets()
            coordinates = {}
            for cid in self.config.coordinate_configurations:
                init = None if initial_models is None else initial_models.get(cid)
                coord = self.estimator.build_coordinate(
                    cid, datasets[cid], self.opt_configs[cid], base_offsets,
                    initial_model=init,
                )
                if (
                    not bootstrap
                    and isinstance(coord, FixedEffectCoordinate)
                    and self.config.fe_reservoir is not None
                ):
                    # deterministic per generation: a replayed delta pass
                    # (crash resume) redraws the identical reservoir
                    coord.down_sampler = ReservoirDownSampler(
                        n_old=delta.row_start,
                        reservoir_size=self.config.fe_reservoir,
                        seed=self.config.seed + self.generation + 1,
                    )
                coordinates[cid] = coord
            descent = run_coordinate_descent(
                coordinates,
                n_iterations=(
                    self.config.initial_iterations
                    if bootstrap
                    else self.config.delta_iterations
                ),
                initial_models=initial_models,
                active_sets=active_sets,
            )
            for cid, coord in coordinates.items():
                st = getattr(coord, "last_active_stats", None)
                if st is not None and cid in active_stats:
                    active_stats[cid]["n_solved_lanes"] = st.n_solved_lanes
            timings["descent"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            final_models = dict(descent.model.models)
            if self.config.window_mode != "full" and not bootstrap:
                # entities whose rows all aged out of the window carry their
                # previous-generation coefficients verbatim (frozen, still
                # served); only EVICTION removes an entity from the tables
                for cid in self.re_types:
                    prev = prev_models.get(cid)
                    cur = final_models.get(cid)
                    if isinstance(prev, RandomEffectModel) and isinstance(
                        cur, RandomEffectModel
                    ):
                        merged = merge_carried_entities(
                            prev, cur, evicted_next.get(cid, set())
                        )
                        if merged is not cur and cid in active_stats:
                            active_stats[cid]["n_carried"] = len(
                                merged.entity_ids
                            ) - len(cur.entity_ids)
                        final_models[cid] = merged

            # compaction: fold (previous cold generation + every live
            # segment) into cold-<gen> BEFORE the commit that references it —
            # the staged+renamed cold dir is unreferenced garbage until this
            # pass's checkpoint lands atomically
            do_compact = bool(
                self.config.compact_every
                and gen_next % self.config.compact_every == 0
            )
            cold_meta = None
            manifest_to_commit = grown_manifest
            if do_compact:
                faultpoint(FP_COMPACT)
                cold_meta = self.store.write_cold_generation(
                    gen_next,
                    view.index_maps,
                    grown_manifest,
                    retain_min_gen=self._retention_min_gen(gen_next),
                    max_cold_rows=self.config.max_cold_rows,
                    protect_min_gen=self._window_min_gen(gen_next),
                )
                if self.config.archive_max_age_gens:
                    for cid in self.re_types:
                        self.store.archive_compact(
                            cid, self._archive_min_evicted_at(gen_next)
                        )
                manifest_to_commit = grown_manifest.compact(
                    n_rows=cold_meta["n_rows"]
                )
                store_state = self.store.to_state(
                    compacted_as=(gen_next, cold_meta["n_rows"])
                )
            else:
                store_state = self.store.to_state()
            timings["compact"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            last_active_next = self._updated_last_active(
                datasets, delta.delta_entities, gen_next
            )
            faultpoint(FP_COMMIT)
            extra_state = {
                "continuous": {
                    "kind": "bootstrap" if bootstrap else "delta",
                    "corpus_manifest": manifest_to_commit.to_dict(),
                    # the total THIS COMMIT's store state holds: a retention
                    # compaction deletes rows at the fold, so the pre-install
                    # in-memory total would overstate the committed tier
                    "n_rows": (
                        int(cold_meta["n_rows"])
                        if do_compact
                        else self.store.total_rows
                    ),
                    "view_rows": view.n_rows,
                    "n_new_rows": delta.n_new_rows,
                    "n_new_files": delta.n_new_files,
                    "active": active_stats,
                    "store": store_state,
                    "window": {
                        "mode": self.config.window_mode,
                        "generations": self.config.window_generations,
                        "decay_half_life": self.config.decay_half_life,
                    },
                }
            }
            aux_arrays = {
                f"{_AUX_INDEX_MAP_PREFIX}{shard}": {
                    "names": np.asarray(imap.keys())
                }
                for shard, imap in view.index_maps.items()
            }
            aux_arrays.update(
                self._eviction_aux_arrays(last_active_next, evicted_next)
            )
            path = save_checkpoint(
                self.config.checkpoint_directory,
                final_models,
                completed_iterations=gen_next,
                fingerprint=self._fingerprint(),
                incidents=descent.incidents,
                keep_generations=self.config.keep_generations,
                extra_state=extra_state,
                aux_arrays=aux_arrays,
            )
        except BaseException:
            # the pass did not commit durably: forget the half-grown
            # in-memory state so a caller that survives (tests, control
            # loops catching InjectedFault) can retry the poll cleanly —
            # the retried poll re-scans the same delta and replays the pass
            # bit-identically against the previous generation's tiers (the
            # staged view was released eagerly, so the rollback re-reads it)
            self.store.abort_delta(prev_maps or view.index_maps, self.manifest)
            raise

        gen_num = int(os.path.basename(path).split("-")[-1])
        self.manifest = manifest_to_commit
        self.models = final_models
        self.generation = gen_num
        self.last_active = last_active_next
        self.evicted = evicted_next
        self.store.commit_delta()
        if cold_meta is not None:
            self.store.install_cold(cold_meta)
        timings["commit"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        self._maybe_export(gen_num)
        timings["export"] = time.perf_counter() - t0

        result = GenerationResult(
            generation=gen_num,
            kind="bootstrap" if bootstrap else "delta",
            n_rows=self.store.total_rows,
            n_new_rows=delta.n_new_rows,
            checkpoint_path=path,
            active=active_stats,
            incidents=[i.to_dict() for i in descent.incidents],
            timings=timings,
            view_rows=view.n_rows,
            compacted=do_compact,
            cold_stats=None if cold_meta is None else dict(cold_meta["io"]),
        )
        self.last_result = result
        logger.info(
            "committed generation %d (%s): %d rows (+%d, %d in view%s), "
            "active fraction %.3f, %.2fs descent",
            gen_num,
            result.kind,
            result.n_rows,
            result.n_new_rows,
            result.view_rows,
            ", compacted" if do_compact else "",
            result.active_fraction,
            timings["descent"],
        )
        return result

    def run(
        self,
        poll_interval_s: float = 10.0,
        max_generations: Optional[int] = None,
        max_idle_polls: Optional[int] = None,
        sleep=time.sleep,
        on_generation=None,
    ) -> list[GenerationResult]:
        """Unattended loop: poll forever (or until ``max_generations``
        commits / ``max_idle_polls`` consecutive empty scans). With
        ``on_generation`` given, each committed generation's record is
        STREAMED to the callback instead of accumulated (the returned list
        stays empty) — the run-forever mode, where an unbounded list would
        grow for the process lifetime."""
        results: list[GenerationResult] = []
        committed = 0
        idle = 0
        while True:
            result = self.poll_once()
            if result is not None:
                if on_generation is not None:
                    on_generation(result)
                else:
                    results.append(result)
                committed += 1
                idle = 0
                if max_generations is not None and committed >= max_generations:
                    return results
            else:
                idle += 1
                if max_idle_polls is not None and idle >= max_idle_polls:
                    return results
            sleep(poll_interval_s)
