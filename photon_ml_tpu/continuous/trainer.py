"""The continuous-training generation loop: poll → delta → commit → serve.

Layer 3 wires the subsystem into the full photon-ml-tpu story as ONE
unattended process per model:

1. **poll** — ``CorpusManifest.scan`` diffs the corpus directories against
   the manifest persisted in the last committed generation; no new part
   files, no work.
2. **delta pass** — ``ingest.ingest_delta`` decodes only the new files and
   grows the corpus (stable index-map growth); datasets rebuild with the
   previous generation's entity ROW ORDER pinned
   (``build_random_effect_dataset(entity_order=...)``) so the old coefficient
   tables align by construction; ``active_set.select_active_entities`` picks
   the working set (new data ∪ new entities ∪ gradient screen); coordinate
   descent runs with ``active_sets`` — random effects re-solve only the
   active entities via the shared vmapped solver body, the fixed effect
   refreshes over a weight-masked reservoir of old+new rows, and the
   divergence guard / incident machinery from PR 3/4 applies unchanged.
3. **commit** — the new model state lands as a PR 3 generational checkpoint
   ``gen-<n>/`` (staged + renamed, SHA-256 manifest) carrying the corpus
   manifest and delta stats in ``extra_state`` and the frozen index maps as
   ``aux`` artifacts — everything a restarted trainer needs to rebuild its
   corpus and resume, and exactly the layout PR 6's ``GenerationWatcher``
   polls, so a committed delta generation hot-swaps into live serving with
   zero downtime.

Crash safety: nothing durable mutates until the atomic checkpoint commit, so
a crash anywhere in a delta pass (``continuous.*`` fault points) simply
replays the pass on restart from the previous generation — bit-identically,
because every input (manifest order, frozen index maps, entity order, seeded
reservoir) is restored from the committed generation. The optional per-
generation model EXPORT (reference BayesianLinearModelAvro bytes, which are
byte-deterministic) is staged + renamed too, and re-exported idempotently on
restart if a crash separated it from its commit.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import shutil
import time
from typing import Mapping, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinate import FixedEffectCoordinate
from photon_ml_tpu.algorithm.coordinate_descent import run_coordinate_descent
from photon_ml_tpu.algorithm.random_effect import random_effect_gradient_norms
from photon_ml_tpu.continuous.active_set import (
    ReservoirDownSampler,
    select_active_entities,
)
from photon_ml_tpu.continuous.ingest import CorpusSnapshot, ingest_delta, read_corpus
from photon_ml_tpu.continuous.manifest import CorpusManifest
from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.estimators.config import RandomEffectDataConfiguration
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.io.checkpoint import load_checkpoint, save_checkpoint
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.resilience import faultpoint, register_fault_point
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)

FP_COMMIT = register_fault_point("continuous.commit")

_AUX_INDEX_MAP_PREFIX = "index-map-"


@dataclasses.dataclass
class ContinuousTrainerConfig:
    """Static configuration of one continuous trainer process."""

    corpus_paths: Sequence[str]
    checkpoint_directory: str
    task: TaskType
    coordinate_configurations: Mapping  # {cid: CoordinateConfiguration}, ordered
    shard_configurations: Mapping  # {shard_id: FeatureShardConfiguration}
    delta_iterations: int = 1  # coordinate-descent iterations per delta pass
    initial_iterations: int = 1  # iterations for the bootstrap full train
    # rule-3 screen: re-solve entities whose subproblem gradient norm exceeds
    # this even without new rows (None = new-data/new-entity rules only)
    gradient_threshold: Optional[float] = None
    # fixed-effect refresh reservoir: how many OLD rows keep weight in a
    # delta pass (None = all of them; delta rows always train)
    fe_reservoir: Optional[int] = None
    export_directory: Optional[str] = None  # per-generation model export
    ingest_workers: Optional[int] = None
    keep_generations: int = 8
    seed: int = 0
    dtype: object = jnp.float32
    # random-effect inner bucket solver, inherited by BOTH the bootstrap full
    # train and every delta pass's active-set sub-bucket solves
    # (optimization/normal_equations.py): "lbfgs" | "direct" | "auto".
    # Direct solves fit continuous training's access pattern especially well:
    # delta passes are always warm-started from the previous generation, the
    # regime where the Newton loop converges in 1-2 steps.
    re_solver: str = "lbfgs"
    # SPMD backend: a jax.sharding.Mesh places every generation's datasets
    # (and the delta pass's gathered active sub-buckets) over the device
    # mesh — bootstrap and delta passes then run as sharded programs with
    # entity-sharded coefficient tables (parallel/placement.py). None =
    # single-device host placement.
    mesh: Optional[object] = None


@dataclasses.dataclass
class GenerationResult:
    """One committed generation's paper trail."""

    generation: int
    kind: str  # "bootstrap" | "delta"
    n_rows: int
    n_new_rows: int
    checkpoint_path: str
    # cid -> {n_entities, n_active, active_fraction, n_new_data,
    #         n_new_entities, n_gradient, n_solved_lanes}
    active: dict
    incidents: list
    timings: dict  # phase -> seconds

    @property
    def active_fraction(self) -> float:
        """Aggregate re-solved fraction across random-effect coordinates."""
        tot = sum(a["n_entities"] for a in self.active.values())
        act = sum(a["n_active"] for a in self.active.values())
        return act / tot if tot else 0.0


class ContinuousTrainer:
    """Drives the ingest → active-set train → commit loop for one model.

    Construct it pointed at a checkpoint directory: an existing continuous
    checkpoint is restored (warm state, corpus rebuilt from the persisted
    manifest with frozen index maps), otherwise the first ``poll_once`` with
    data bootstraps generation 1 with a full train. Call :meth:`poll_once`
    from a control loop (or :meth:`run`)."""

    def __init__(self, config: ContinuousTrainerConfig):
        self.config = config
        self.task = TaskType(config.task)
        from photon_ml_tpu.estimators.config import expand_game_configurations

        sweep = expand_game_configurations(config.coordinate_configurations)
        if len(sweep) != 1:
            raise ValueError(
                f"continuous training drives ONE optimization configuration; "
                f"the given coordinate configurations expand to {len(sweep)} "
                "(drop the extra regularization weights)"
            )
        self.opt_configs = sweep[0]
        self.estimator = GameEstimator(
            task=self.task,
            coordinate_configurations=config.coordinate_configurations,
            n_iterations=config.delta_iterations,
            dtype=config.dtype,
            re_solver=config.re_solver,
            mesh=config.mesh,
        )
        self.re_types = {
            cid: cfg.data_config.random_effect_type
            for cid, cfg in config.coordinate_configurations.items()
            if isinstance(cfg.data_config, RandomEffectDataConfiguration)
        }
        if config.fe_reservoir is not None:
            for cid, cfg in config.coordinate_configurations.items():
                if cid in self.re_types:
                    continue
                if 0.0 < getattr(cfg, "down_sampling_rate", 1.0) < 1.0:
                    # the reservoir REPLACES the coordinate's down-sampler on
                    # delta passes: combining them would train the bootstrap
                    # under the configured sampling weights and every delta
                    # under reservoir weights — two different FE objectives
                    raise ValueError(
                        f"fe_reservoir cannot be combined with coordinate "
                        f"{cid!r}'s down.sampling.rate="
                        f"{cfg.down_sampling_rate}; drop one of the two"
                    )
        self.id_tags = sorted(set(self.re_types.values()))
        self.manifest = CorpusManifest()
        self.snapshot: Optional[CorpusSnapshot] = None
        self.models: Optional[dict] = None
        self.generation = 0
        self.last_result: Optional[GenerationResult] = None
        self._restore()

    # ------------------------------------------------------------- restore

    def _fingerprint(self) -> str:
        parts = [f"continuous|{self.task.value}"]
        for cid in sorted(self.config.coordinate_configurations):
            parts.append(f"{cid}={self.opt_configs[cid]!r}")
        return "|".join(parts)

    def _restore(self) -> None:
        restored = load_checkpoint(
            self.config.checkpoint_directory,
            dtype=self.config.dtype,
            fingerprint=self._fingerprint(),
        )
        if restored is None:
            return
        extra = (restored.get("extra") or {}).get("continuous")
        if extra is None:
            logger.warning(
                "checkpoint %s carries no continuous-training state; starting "
                "a fresh corpus history on top of it",
                self.config.checkpoint_directory,
            )
            return
        index_maps = {}
        aux = restored.get("aux") or {}
        for shard in self.config.shard_configurations:
            arrs = aux.get(f"{_AUX_INDEX_MAP_PREFIX}{shard}")
            if arrs is None:
                raise ValueError(
                    f"continuous checkpoint is missing the frozen index map "
                    f"for shard {shard!r}; cannot rebuild the corpus"
                )
            index_maps[shard] = IndexMap([str(n) for n in arrs["names"]])
        self.manifest = CorpusManifest.from_dict(extra["corpus_manifest"])
        # full-content check BEFORE the rebuild read: a same-size rewrite of
        # an ingested part file (size checks pass) would otherwise rebuild a
        # corpus that silently differs from what the warm-start model absorbed
        self.manifest.verify_fingerprints()
        data, _maps, uids = read_corpus(
            self.manifest.paths,
            self.config.shard_configurations,
            index_maps,
            self.id_tags,
            self.config.ingest_workers,
        )
        self.snapshot = CorpusSnapshot(data=data, index_maps=index_maps, uids=uids)
        self.models = restored["models"]
        self.generation = int(restored.get("generation") or 0)
        logger.info(
            "restored continuous state: generation %d, %d corpus rows, "
            "%d part files",
            self.generation,
            data.n,
            len(self.manifest),
        )
        # a crash between commit and export leaves the export missing: redo
        # it idempotently (export bytes are a pure function of the models)
        self._maybe_export(self.generation)

    # --------------------------------------------------------------- export

    def _index_maps_by_coord(self) -> dict:
        return {
            cid: self.snapshot.index_maps[cfg.data_config.feature_shard_id]
            for cid, cfg in self.config.coordinate_configurations.items()
        }

    def _maybe_export(self, generation: int) -> Optional[str]:
        if self.config.export_directory is None or self.models is None:
            return None
        from photon_ml_tpu.io.model_io import save_game_model

        target = os.path.join(
            self.config.export_directory, f"gen-{generation:08d}"
        )
        if os.path.isdir(target):
            return target
        tmp = target + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        save_game_model(tmp, GameModel(models=self.models), self._index_maps_by_coord())
        os.rename(tmp, target)
        return target

    # ------------------------------------------------------------ delta pass

    def _pad_fixed_effect(self, model: FixedEffectModel, dim: int) -> FixedEffectModel:
        """Stable feature growth for the fixed effect: unseen features append
        at the index-map tail, so the previous coefficient vector aligns by
        zero-padding at the tail — no remapping."""
        coef = model.model.coefficients
        means = coef.means
        if means.shape[0] >= dim:
            return model
        pad = dim - means.shape[0]
        means = jnp.concatenate([means, jnp.zeros((pad,), dtype=means.dtype)])
        variances = coef.variances
        if variances is not None:
            variances = jnp.concatenate(
                [variances, jnp.zeros((pad,), dtype=variances.dtype)]
            )
        new_coef = dataclasses.replace(coef, means=means, variances=variances)
        return dataclasses.replace(
            model, model=dataclasses.replace(model.model, coefficients=new_coef)
        )

    def _base_offsets(self):
        """The [N] base-offset vector at the backend's placement: padded and
        sample-sharded on a mesh (placed datasets carry a padded sample axis,
        so every score/offset array must match), plain device array on the
        host backend."""
        off = np.asarray(self.snapshot.data.offsets)
        if self.config.mesh is not None:
            from photon_ml_tpu.parallel.placement import pad_and_shard_vector

            return pad_and_shard_vector(
                off, self.config.mesh, dtype=self.config.dtype
            )
        return jnp.asarray(off, dtype=self.config.dtype)

    def _adapted_models(self, datasets: dict) -> dict:
        """Previous-generation models adapted to the grown datasets: fixed
        effects zero-pad to the grown feature dim, random effects re-layout
        by entity id (tail growth makes this a cheap identity-or-append)."""
        out = {}
        for cid, model in self.models.items():
            ds = datasets[cid]
            if isinstance(model, FixedEffectModel):
                out[cid] = self._pad_fixed_effect(model, ds.dim)
            elif isinstance(model, RandomEffectModel):
                out[cid] = model.aligned_to(ds)
            else:
                out[cid] = model
        return out

    def _select_active_sets(
        self, datasets: dict, adapted: dict, delta_entities: dict
    ) -> tuple[dict, dict]:
        """Per-RE-coordinate active masks + stats. The optional gradient
        screen evaluates each coordinate's subproblem gradient at the
        warm-start coefficients against the OTHER coordinates' current
        scores (one cheap vmapped pass per bucket shape)."""
        base_offsets = self._base_offsets()
        scores = None
        if self.config.gradient_threshold is not None:
            from photon_ml_tpu.algorithm.coordinate import score_model_on_dataset

            scores = {
                cid: score_model_on_dataset(adapted[cid], datasets[cid])
                for cid in datasets
            }
            total = sum(scores.values())
        active_sets: dict = {}
        stats: dict = {}
        for cid, re_type in self.re_types.items():
            ds = datasets[cid]
            norms = None
            if scores is not None:
                cfg = self.opt_configs[cid]
                norms = random_effect_gradient_norms(
                    ds,
                    adapted[cid],
                    base_offsets + (total - scores[cid]),
                    self.task,
                    l2=cfg.l2_weight,
                    per_entity_reg_weights=self.config.coordinate_configurations[
                        cid
                    ].per_entity_reg_weights,
                    dtype=self.config.dtype,
                )
            sel = select_active_entities(
                ds,
                delta_entities.get(re_type, set()),
                prev_model=self.models.get(cid),
                gradient_norms=norms,
                gradient_threshold=self.config.gradient_threshold,
            )
            active_sets[cid] = sel.mask
            stats[cid] = {
                "n_entities": ds.n_entities,
                "n_active": sel.n_active,
                "active_fraction": sel.n_active / ds.n_entities
                if ds.n_entities
                else 0.0,
                "n_new_data": sel.n_new_data,
                "n_new_entities": sel.n_new_entities,
                "n_gradient": sel.n_gradient,
            }
        return active_sets, stats

    def poll_once(self) -> Optional[GenerationResult]:
        """One turn of the loop: scan, and if the corpus grew, run a delta
        pass (or the bootstrap full train) and commit the next generation.
        Returns the committed generation's record, or None when idle."""
        timings: dict = {}
        t0 = time.perf_counter()
        new_files = self.manifest.scan(self.config.corpus_paths)
        timings["scan"] = time.perf_counter() - t0
        if not new_files:
            return None
        bootstrap = self.models is None

        t0 = time.perf_counter()
        # record each new file's size/fingerprint BEFORE decoding it and
        # re-verify after: the bracket turns a file an upstream writer was
        # still appending to into a loud CorpusContractViolation instead of
        # a manifest record that disagrees with the rows the model absorbed
        grown_manifest = self.manifest.extend(new_files)
        self_snapshot, delta = ingest_delta(
            self.snapshot,
            new_files,
            self.config.shard_configurations,
            self.id_tags,
            self.config.ingest_workers,
        )
        grown_manifest.verify_sizes(grown_manifest.entries[len(self.manifest):])
        timings["ingest"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        snapshot_prev = self.snapshot
        self.snapshot = self_snapshot  # datasets/export helpers read it
        try:
            entity_orders = None
            if self.models is not None:
                entity_orders = {
                    cid: self.models[cid].entity_ids
                    for cid in self.re_types
                    if isinstance(self.models.get(cid), RandomEffectModel)
                }
            datasets = self.estimator.prepare_training_datasets(
                self.snapshot.data, entity_orders=entity_orders
            )
            if self.config.mesh is not None:
                from photon_ml_tpu.parallel.placement import place_game_datasets

                datasets = place_game_datasets(datasets, self.config.mesh)
            timings["datasets"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            active_sets = None
            active_stats: dict = {}
            initial_models = None
            if not bootstrap:
                adapted = self._adapted_models(datasets)
                active_sets, active_stats = self._select_active_sets(
                    datasets, adapted, delta.delta_entities
                )
                initial_models = adapted
            else:
                for cid, re_type in self.re_types.items():
                    ds = datasets[cid]
                    active_stats[cid] = {
                        "n_entities": ds.n_entities,
                        "n_active": ds.n_entities,
                        "active_fraction": 1.0,
                        "n_new_data": ds.n_entities,
                        "n_new_entities": ds.n_entities,
                        "n_gradient": 0,
                    }
            timings["select"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            base_offsets = self._base_offsets()
            coordinates = {}
            for cid in self.config.coordinate_configurations:
                init = None if initial_models is None else initial_models.get(cid)
                coord = self.estimator.build_coordinate(
                    cid, datasets[cid], self.opt_configs[cid], base_offsets,
                    initial_model=init,
                )
                if (
                    not bootstrap
                    and isinstance(coord, FixedEffectCoordinate)
                    and self.config.fe_reservoir is not None
                ):
                    # deterministic per generation: a replayed delta pass
                    # (crash resume) redraws the identical reservoir
                    coord.down_sampler = ReservoirDownSampler(
                        n_old=delta.row_start,
                        reservoir_size=self.config.fe_reservoir,
                        seed=self.config.seed + self.generation + 1,
                    )
                coordinates[cid] = coord
            descent = run_coordinate_descent(
                coordinates,
                n_iterations=(
                    self.config.initial_iterations
                    if bootstrap
                    else self.config.delta_iterations
                ),
                initial_models=initial_models,
                active_sets=active_sets,
            )
            for cid, coord in coordinates.items():
                st = getattr(coord, "last_active_stats", None)
                if st is not None and cid in active_stats:
                    active_stats[cid]["n_solved_lanes"] = st.n_solved_lanes
            timings["descent"] = time.perf_counter() - t0

            t0 = time.perf_counter()
            faultpoint(FP_COMMIT)
            extra_state = {
                "continuous": {
                    "kind": "bootstrap" if bootstrap else "delta",
                    "corpus_manifest": grown_manifest.to_dict(),
                    "n_rows": self.snapshot.n_rows,
                    "n_new_rows": delta.n_new_rows,
                    "n_new_files": delta.n_new_files,
                    "active": active_stats,
                }
            }
            aux_arrays = {
                f"{_AUX_INDEX_MAP_PREFIX}{shard}": {
                    "names": np.asarray(imap.keys())
                }
                for shard, imap in self.snapshot.index_maps.items()
            }
            path = save_checkpoint(
                self.config.checkpoint_directory,
                dict(descent.model.models),
                completed_iterations=self.generation + 1,
                fingerprint=self._fingerprint(),
                incidents=descent.incidents,
                keep_generations=self.config.keep_generations,
                extra_state=extra_state,
                aux_arrays=aux_arrays,
            )
        except BaseException:
            # the pass did not commit durably: forget the half-grown
            # in-memory state so a caller that survives (tests, control
            # loops catching InjectedFault) can retry the poll cleanly —
            # the retried poll re-scans the same delta and replays the pass
            # bit-identically against the previous generation's snapshot
            self.snapshot = snapshot_prev
            raise

        gen_num = int(os.path.basename(path).split("-")[-1])
        self.manifest = grown_manifest
        self.models = dict(descent.model.models)
        self.generation = gen_num
        timings["commit"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        self._maybe_export(gen_num)
        timings["export"] = time.perf_counter() - t0

        result = GenerationResult(
            generation=gen_num,
            kind="bootstrap" if bootstrap else "delta",
            n_rows=self.snapshot.n_rows,
            n_new_rows=delta.n_new_rows,
            checkpoint_path=path,
            active=active_stats,
            incidents=[i.to_dict() for i in descent.incidents],
            timings=timings,
        )
        self.last_result = result
        logger.info(
            "committed generation %d (%s): %d rows (+%d), active fraction "
            "%.3f, %.2fs descent",
            gen_num,
            result.kind,
            result.n_rows,
            result.n_new_rows,
            result.active_fraction,
            timings["descent"],
        )
        return result

    def run(
        self,
        poll_interval_s: float = 10.0,
        max_generations: Optional[int] = None,
        max_idle_polls: Optional[int] = None,
        sleep=time.sleep,
        on_generation=None,
    ) -> list[GenerationResult]:
        """Unattended loop: poll forever (or until ``max_generations``
        commits / ``max_idle_polls`` consecutive empty scans). With
        ``on_generation`` given, each committed generation's record is
        STREAMED to the callback instead of accumulated (the returned list
        stays empty) — the run-forever mode, where an unbounded list would
        grow for the process lifetime."""
        results: list[GenerationResult] = []
        committed = 0
        idle = 0
        while True:
            result = self.poll_once()
            if result is not None:
                if on_generation is not None:
                    on_generation(result)
                else:
                    results.append(result)
                committed += 1
                idle = 0
                if max_generations is not None and committed >= max_generations:
                    return results
            else:
                idle += 1
                if max_idle_polls is not None and idle >= max_idle_polls:
                    return results
            sleep(poll_interval_s)
