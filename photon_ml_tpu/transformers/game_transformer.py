"""GameTransformer: score GameInput with a trained GAME model.

Re-designs photon-api transformers/GameTransformer.scala:39-318. The reference
builds a GameDatum RDD and sums per-coordinate ModelDataScores via joins; here
scoring delegates by default to the fused serving engine (serving/engine.py):
one jitted XLA program per (model, batch-size bucket) with device-resident
coefficient tables and a single host transfer of the final [N] scores.

``engine="eager"`` keeps the original per-coordinate path — each coordinate's
scoring dataset built from the model's own metadata (shard id, random-effect
type), scored with one dispatch per coordinate — used for parity testing and
as the fallback for whatever ``GameServingEngine.mesh_capable`` (the one
owner of the fused-vs-eager placement decision) refuses. 2-D training meshes
serve FUSED since PR 10: tables replicate, batches shard along the data axis.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.algorithm.coordinate import score_model_on_dataset
from photon_ml_tpu.data.game_data import (
    GameInput,
    build_fixed_effect_scoring_dataset,
    build_random_effect_scoring_dataset,
)
from photon_ml_tpu.evaluation.evaluators import EvaluationSuite, resolve_evaluator
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel


@dataclasses.dataclass
class GameTransformer:
    """Scores tables with a GameModel; optionally evaluates
    (GameTransformer.transform:150+)."""

    model: GameModel
    evaluators: Sequence = ()
    log_scores_per_coordinate: bool = False
    # SPMD scoring: place each scoring dataset over a jax.sharding.Mesh
    # (samples sharded) so the per-coordinate matvecs/gathers run distributed,
    # mirroring the reference's executor-parallel scoring
    # (GameTransformer.transform:150+, RandomEffectModel.score:83-101)
    mesh: object = None
    # "fused": the jit-cached serving engine (default, any mesh the
    # capability probe accepts); "eager": per-coordinate dataset rebuild +
    # dispatch (the pre-engine path, kept for parity tests)
    engine: str = "fused"

    def _serving_engine(self):
        """The fused engine for this model, or None when configured eager or
        when the engine's capability probe refuses the mesh. The probe
        (``GameServingEngine.mesh_capable``) is THE owner of the fused-vs-
        eager placement decision — 2-D training meshes serve fused with the
        batch on the data axis since PR 10. Memoized per (model object,
        mesh): get_engine's content fingerprint hashes every coefficient
        table, which must not run on each score() call."""
        if self.engine != "fused":
            return None
        from photon_ml_tpu.serving import GameServingEngine

        if not GameServingEngine.mesh_capable(self.mesh):
            from photon_ml_tpu.analysis.fallbacks import log_fallback_once

            # stable, cheap description — never id()/content hashes: the
            # once-per-cause dedup must survive model reloads (same logical
            # model, fresh object) without hashing coefficient tables on a
            # scoring path
            coord_ids = ",".join(cid for cid, _ in self.model)
            log_fallback_once(
                "serving_engine",
                f"model[{coord_ids}]",
                f"mesh {self.mesh!r} refused by "
                "GameServingEngine.mesh_capable: eager per-coordinate "
                "scoring",
            )
            return None
        key = (id(self.model), self.mesh)
        cached = getattr(self, "_engine_memo", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        from photon_ml_tpu.serving import get_engine

        eng = get_engine(self.model, mesh=self.mesh)
        self._engine_memo = (key, eng)
        return eng

    def score(self, data: GameInput, include_offsets: bool = True) -> np.ndarray:
        """Total score per sample: sum of coordinate scores (+ offsets, matching the
        reference's scored output which folds the base offset into the score)."""
        eng = self._serving_engine()
        if eng is not None:
            return eng.score(data, include_offsets=include_offsets)
        per_coord = self._score_per_coordinate_eager(data)
        if per_coord:
            total = np.sum([np.asarray(s) for s in per_coord.values()], axis=0)
        else:
            # zero-coordinate model: np.sum([], axis=0) is a 0.0 SCALAR, which
            # silently broadcast offsets-only scoring to the wrong shape
            total = np.zeros(data.n)
        if include_offsets:
            total = total + np.asarray(data.offsets)
        return total

    def score_per_coordinate(self, data: GameInput) -> dict[str, np.ndarray]:
        eng = self._serving_engine()
        if eng is not None:
            return eng.score_per_coordinate(data)
        return self._score_per_coordinate_eager(data)

    def _score_per_coordinate_eager(self, data: GameInput) -> dict[str, np.ndarray]:
        scores: dict[str, np.ndarray] = {}
        n = data.n
        for cid, model in self.model:
            dataset = self._scoring_dataset(model, data)
            if self.mesh is not None:
                from photon_ml_tpu.parallel.placement import place_game_datasets

                dataset = place_game_datasets({cid: dataset}, self.mesh)[cid]
                # (RandomEffectModel.score_dataset re-aligns internally)
                if isinstance(model, FixedEffectModel):
                    from photon_ml_tpu.algorithm.coordinate import (
                        pad_fixed_effect_model,
                    )

                    # 2-D meshes pad the feature axis; coefficients follow
                    model = pad_fixed_effect_model(model, dataset)
            # mesh placement pads the sample axis; trim back to the true N
            scores[cid] = np.asarray(score_model_on_dataset(model, dataset))[:n]
        return scores

    def transform(self, data: GameInput) -> tuple[np.ndarray, Optional[dict]]:
        """(scores, metrics): metrics computed when evaluators are configured and
        the data has labels (GameTransformer.transform:180-195)."""
        raw = self.score(data, include_offsets=False)
        metrics = None
        if self.evaluators and data.has_labels:
            suite = EvaluationSuite(
                evaluators=[resolve_evaluator(s) for s in self.evaluators],
                labels=np.asarray(data.labels, dtype=np.float64),
                offsets=np.asarray(data.offsets, dtype=np.float64),
                weights=np.asarray(data.weights, dtype=np.float64),
                id_columns={t: np.asarray(c) for t, c in data.id_columns.items()},
            )
            metrics = suite.evaluate(raw)
        return raw + np.asarray(data.offsets), metrics

    @staticmethod
    def _scoring_dataset(model, data: GameInput):
        if isinstance(model, FixedEffectModel):
            return build_fixed_effect_scoring_dataset(data, model.feature_shard_id)
        if isinstance(model, RandomEffectModel):
            return build_random_effect_scoring_dataset(
                data, model.re_type, model.feature_shard_id,
                projector=model.projector,
            )
        raise TypeError(f"Cannot build scoring dataset for {type(model).__name__}")
