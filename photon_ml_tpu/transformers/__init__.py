from photon_ml_tpu.transformers.game_transformer import GameTransformer

__all__ = ["GameTransformer"]
