"""Iteration-level checkpoint/resume for coordinate descent.

The reference delegates failure recovery to Spark (RDD lineage recomputation +
DISK_ONLY persistence, CoordinateDescent.scala:130-160); it checkpoints models
only at the end of a full run (ModelProcessingUtils.saveGameModelToHDFS:77-141).
A single-controller JAX program has no lineage to replay, so recovery is explicit:
after every completed coordinate-descent iteration the full GAME model state —
current models, best-model snapshot, best metric — is written atomically to disk,
and a restarted run resumes from the last completed iteration. Training scores
are pure functions of the models, so nothing else needs saving: resume
reinitializes from the checkpointed models and recomputes scores exactly.

Format: one ``.npz`` per coordinate (raw arrays, no pickling) plus a
``state.json`` manifest; writes go to a temp directory renamed into place so a
crash mid-write can never corrupt the latest checkpoint. This is the *internal*
fast format — final model export still uses the reference-compatible
BayesianLinearModelAvro layout (io/model_io.py).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.models.glm import Coefficients, model_class_for_task
from photon_ml_tpu.types import TaskType

STATE_FILE = "state.json"
BEST_DIR = "best"
_TMP_SUFFIX = ".tmp"


# ------------------------------------------------------------- model <-> arrays


def _model_to_arrays(model) -> tuple[dict, dict]:
    """(json-metadata, arrays) for one coordinate model."""
    if isinstance(model, FixedEffectModel):
        glm = model.model
        meta = {
            "kind": "fixed",
            "feature_shard_id": model.feature_shard_id,
            "task": TaskType(glm.task).value,
        }
        arrays = {"means": np.asarray(glm.coefficients.means)}
        if glm.coefficients.variances is not None:
            arrays["variances"] = np.asarray(glm.coefficients.variances)
        return meta, arrays

    if isinstance(model, RandomEffectModel):
        entity_ids = list(model.entity_ids)
        ids_are_int = all(isinstance(e, (int, np.integer)) for e in entity_ids)
        meta = {
            "kind": "random",
            "re_type": model.re_type,
            "feature_shard_id": model.feature_shard_id,
            "task": TaskType(model.task).value,
            "entity_ids_int": ids_are_int,
        }
        arrays = {
            "coeffs": np.asarray(model.coeffs),
            "proj_indices": np.asarray(model.proj_indices),
            "entity_ids": (
                np.asarray(entity_ids, dtype=np.int64)
                if ids_are_int
                else np.asarray([str(e) for e in entity_ids])
            ),
        }
        if model.variances is not None:
            arrays["variances"] = np.asarray(model.variances)
        proj = model.projector
        if proj is not None:
            from photon_ml_tpu.data.projector import RandomProjector

            if not isinstance(proj, RandomProjector):
                raise TypeError(
                    f"Cannot checkpoint projector of type {type(proj).__name__}"
                )
            arrays["projector_matrix"] = np.asarray(proj.matrix)
            meta["projector_intercept_index"] = proj.intercept_index
            norm = proj.normalization
            if norm is not None:
                meta["projector_norm_intercept_index"] = norm.intercept_index
                if norm.factors is not None:
                    arrays["projector_norm_factors"] = np.asarray(norm.factors)
                if norm.shifts is not None:
                    arrays["projector_norm_shifts"] = np.asarray(norm.shifts)
        return meta, arrays

    raise TypeError(f"Unknown model type: {type(model).__name__}")


def _model_from_arrays(meta: dict, arrays, dtype) -> object:
    task = TaskType(meta["task"])
    if meta["kind"] == "fixed":
        variances = arrays.get("variances")
        coeffs = Coefficients(
            means=jnp.asarray(arrays["means"], dtype=dtype),
            variances=None if variances is None else jnp.asarray(variances, dtype=dtype),
        )
        return FixedEffectModel(
            model=model_class_for_task(task)(coeffs),
            feature_shard_id=meta["feature_shard_id"],
        )

    entity_ids = arrays["entity_ids"]
    ids = (
        tuple(int(e) for e in entity_ids)
        if meta["entity_ids_int"]
        else tuple(str(e) for e in entity_ids)
    )
    projector = None
    if "projector_matrix" in arrays:
        from photon_ml_tpu.data.projector import RandomProjector
        from photon_ml_tpu.normalization import NormalizationContext

        norm = None
        if "projector_norm_factors" in arrays or "projector_norm_shifts" in arrays:
            norm = NormalizationContext(
                factors=arrays.get("projector_norm_factors"),
                shifts=arrays.get("projector_norm_shifts"),
                intercept_index=meta.get("projector_norm_intercept_index"),
            )
        projector = RandomProjector(
            matrix=arrays["projector_matrix"],
            intercept_index=meta.get("projector_intercept_index"),
            normalization=norm,
        )
    variances = arrays.get("variances")
    return RandomEffectModel(
        re_type=meta["re_type"],
        feature_shard_id=meta["feature_shard_id"],
        task=task,
        entity_ids=ids,
        coeffs=jnp.asarray(arrays["coeffs"], dtype=dtype),
        proj_indices=jnp.asarray(arrays["proj_indices"], dtype=jnp.int32),
        variances=None if variances is None else jnp.asarray(variances, dtype=dtype),
        projector=projector,
    )


# ------------------------------------------------------------------ save / load


def _write_models(directory: str, models: dict, manifest: dict) -> None:
    for cid, model in models.items():
        meta, arrays = _model_to_arrays(model)
        manifest[cid] = meta
        np.savez(os.path.join(directory, f"{cid}.npz"), **arrays)


def _read_models(directory: str, manifest: dict, dtype) -> dict:
    models = {}
    for cid, meta in manifest.items():
        with np.load(os.path.join(directory, f"{cid}.npz"), allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files}
        models[cid] = _model_from_arrays(meta, arrays, dtype)
    return models


def save_checkpoint(
    directory: str,
    models: dict,
    completed_iterations: int,
    best_models: Optional[dict] = None,
    best_metric: Optional[float] = None,
    best_metrics: Optional[dict] = None,
    fingerprint: Optional[str] = None,
) -> None:
    """Atomically write a coordinate-descent checkpoint (tmp dir + rename).

    ``fingerprint`` identifies the run configuration; ``load_checkpoint`` with a
    different fingerprint refuses the checkpoint, so a rerun with changed
    hyperparameters/data cannot silently reuse stale trained state."""
    parent = os.path.dirname(os.path.abspath(directory)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.abspath(directory) + _TMP_SUFFIX
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)

    state = {
        "completed_iterations": int(completed_iterations),
        "fingerprint": fingerprint,
        "best_metric": None if best_metric is None else float(best_metric),
        "best_metrics": (
            None
            if best_metrics is None
            else {k: float(v) for k, v in best_metrics.items()}
        ),
        "models": {},
        "best_models": None,
    }
    _write_models(tmp, models, state["models"])
    if best_models is not None:
        best_dir = os.path.join(tmp, BEST_DIR)
        os.makedirs(best_dir)
        state["best_models"] = {}
        _write_models(best_dir, best_models, state["best_models"])

    with open(os.path.join(tmp, STATE_FILE), "w") as f:
        json.dump(state, f)

    final = os.path.abspath(directory)
    if os.path.exists(final):
        old = final + ".old"
        if os.path.exists(old):
            shutil.rmtree(old)
        os.rename(final, old)
        os.rename(tmp, final)
        shutil.rmtree(old)
    else:
        os.rename(tmp, final)


def load_checkpoint(
    directory: str, dtype=jnp.float32, fingerprint: Optional[str] = None
) -> Optional[dict]:
    """Returns {completed_iterations, models, best_models, best_metric} or None
    when no (complete) checkpoint exists. A leftover ``.tmp`` dir from a crash
    mid-write is ignored; a ``.old`` dir left by a crash *between* the two
    overwrite renames is recovered as the latest complete checkpoint. A saved
    ``fingerprint`` differing from the requested one rejects the checkpoint."""
    directory = os.path.abspath(directory)
    state_path = os.path.join(directory, STATE_FILE)
    if not os.path.exists(state_path):
        # crash window in save_checkpoint: final was renamed to .old but .tmp
        # was not yet promoted — the .old dir is the last complete checkpoint
        old = directory + ".old"
        if os.path.exists(os.path.join(old, STATE_FILE)):
            directory, state_path = old, os.path.join(old, STATE_FILE)
        else:
            return None
    with open(state_path) as f:
        state = json.load(f)
    if fingerprint is not None and state.get("fingerprint") not in (None, fingerprint):
        return None
    models = _read_models(directory, state["models"], dtype)
    best_models = None
    if state.get("best_models") is not None:
        best_models = _read_models(
            os.path.join(directory, BEST_DIR), state["best_models"], dtype
        )
    return {
        "completed_iterations": state["completed_iterations"],
        "best_metric": state["best_metric"],
        "best_metrics": state.get("best_metrics"),
        "models": models,
        "best_models": best_models,
    }


class CoordinateDescentCheckpointer:
    """Save/restore hook handed to ``run_coordinate_descent``.

    ``interval`` saves every k-th completed iteration; the descent loop passes
    ``force=True`` on the final iteration so the completed state is always
    saved regardless of the interval. ``fingerprint`` (optional) ties the
    checkpoint to a run configuration: restore returns None when it differs.
    """

    def __init__(
        self,
        directory: str,
        interval: int = 1,
        dtype=jnp.float32,
        fingerprint: Optional[str] = None,
    ):
        if interval < 1:
            raise ValueError(f"checkpoint interval must be >= 1, got {interval}")
        self.directory = directory
        self.interval = int(interval)
        self.dtype = dtype
        self.fingerprint = fingerprint

    def maybe_save(
        self,
        completed_iterations: int,
        models: dict,
        best_models: Optional[dict],
        best_metric: Optional[float],
        best_metrics: Optional[dict] = None,
        force: bool = False,
    ) -> bool:
        if not force and completed_iterations % self.interval != 0:
            return False
        save_checkpoint(
            self.directory,
            models,
            completed_iterations,
            best_models,
            best_metric,
            best_metrics,
            fingerprint=self.fingerprint,
        )
        return True

    def restore(self) -> Optional[dict]:
        return load_checkpoint(
            self.directory, dtype=self.dtype, fingerprint=self.fingerprint
        )

    def clear(self) -> None:
        # also drop the .old/.tmp siblings: load_checkpoint falls back to .old,
        # so leaving it would resurrect the state the caller tried to discard
        for path in (self.directory, self.directory + ".old", self.directory + _TMP_SUFFIX):
            if os.path.exists(path):
                shutil.rmtree(path)
