"""Generational, integrity-checked checkpoint/resume for coordinate descent.

The reference delegates failure recovery to Spark (RDD lineage recomputation +
DISK_ONLY persistence, CoordinateDescent.scala:130-160); it checkpoints models
only at the end of a full run (ModelProcessingUtils.saveGameModelToHDFS:77-141).
A single-controller JAX program has no lineage to replay, so recovery is
explicit — and *verified*:

- After every completed coordinate-descent iteration the full GAME model state
  (current models, best-model snapshot, best metric, incident history) is
  written as a NEW generation ``<dir>/gen-<n>/``: one ``.npz`` per coordinate
  (raw arrays, no pickling) plus a ``state.json`` manifest carrying a SHA-256
  checksum of every artifact, with the manifest's own checksum in a sidecar.
  Writes land in a ``gen-<n>.tmp`` staging dir renamed into place, so a crash
  at any instruction never damages an existing generation.
- ``load_checkpoint`` verifies every checksum and ROLLS BACK: a torn or
  bit-rotted generation is quarantined (renamed ``gen-<n>.corrupt``) with a
  logged incident, and restore proceeds from the newest generation that
  verifies — never a crash, never a silent load of bad data. The last
  ``keep_generations`` generations are retained for exactly this.
- Transient I/O errors (OSError) retry with exponential backoff + jitter
  (resilience/retry.py); the write path is instrumented with fault points
  (``checkpoint.write.arrays`` / ``.manifest`` / ``.commit``,
  ``checkpoint.restore``) so every failure window is replayable
  (resilience/faultpoints.py, tests/test_chaos.py).

Training scores are pure functions of the models, so nothing else needs
saving: resume reinitializes from the checkpointed models and recomputes
scores exactly (bit-identical resume, tests/test_checkpoint.py). This is the
*internal* fast format — final model export still uses the
reference-compatible BayesianLinearModelAvro layout (io/model_io.py).

Legacy layout (pre-generational: ``state.json`` directly in the checkpoint
directory, ``.old`` sibling from the old overwrite dance) is still read, with
the same never-raise contract: an unreadable legacy checkpoint is quarantined
and restore falls back (to ``.old``, else to a fresh start).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import shutil
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.models.glm import Coefficients, model_class_for_task
from photon_ml_tpu.resilience import (
    Retry,
    corrupt_file,
    faultpoint,
    register_fault_point,
)
from photon_ml_tpu.resilience.incidents import Incident
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)

STATE_FILE = "state.json"
STATE_SHA_FILE = "state.json.sha256"
BEST_DIR = "best"
AUX_DIR = "aux"
GEN_PREFIX = "gen-"
QUARANTINE_SUFFIX = ".corrupt"
DEFAULT_KEEP_GENERATIONS = 3
_TMP_SUFFIX = ".tmp"
_GEN_RE = re.compile(r"^gen-(\d{8})$")
_FORMAT = 2

FP_WRITE_ARRAYS = register_fault_point("checkpoint.write.arrays")
FP_WRITE_MANIFEST = register_fault_point("checkpoint.write.manifest")
FP_WRITE_COMMIT = register_fault_point("checkpoint.write.commit")
FP_RESTORE = register_fault_point("checkpoint.restore")

# checkpoint I/O rides a shared-filesystem in production: transient OSErrors
# get a bounded, jittered retry instead of killing the run
_DEFAULT_RETRY = Retry(max_attempts=3, base_delay=0.05, max_delay=1.0)


class CheckpointCorruption(Exception):
    """A generation failed integrity verification (internal control flow:
    load_checkpoint converts it into quarantine + rollback, never raises it)."""


# ---------------------------------------------------- reduced-dtype encoding
# np.save writes ml_dtypes arrays (bfloat16) as raw |V2 void: loading one back
# silently reinterprets the table bytes. Every .npz this module writes goes
# through _encode_arrays, which stores such arrays as their uint16 bit
# patterns next to a self-describing "__dtype__<name>" marker, so a bf16
# deployment's generational checkpoints round-trip BIT-EXACTLY and fleet
# replicas can load them. Native dtypes (incl. float16) pass through
# untouched — the marker only exists where np.save would lie.

_DTYPE_MARKER = "__dtype__"
_BITS_ENCODED_DTYPES = ("bfloat16",)


def _encode_arrays(arrays: dict) -> dict:
    out = {}
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        if str(arr.dtype) in _BITS_ENCODED_DTYPES:
            out[name] = arr.view(np.uint16)
            out[_DTYPE_MARKER + name] = np.asarray(str(arr.dtype))
        else:
            out[name] = arr
    return out


def _decode_arrays(arrays: dict) -> dict:
    out = {k: v for k, v in arrays.items() if not k.startswith(_DTYPE_MARKER)}
    for key, marker in arrays.items():
        if not key.startswith(_DTYPE_MARKER):
            continue
        name, dt = key[len(_DTYPE_MARKER):], str(marker)
        if dt not in _BITS_ENCODED_DTYPES:
            raise ValueError(f"unknown encoded dtype {dt!r} for artifact array {name!r}")
        out[name] = out[name].view(np.dtype(dt))  # ml_dtypes registers the name
    return out


def _load_npz(path: str) -> dict:
    with np.load(path, allow_pickle=False) as z:
        return _decode_arrays({k: z[k] for k in z.files})


# ------------------------------------------------------------- model <-> arrays


def _model_to_arrays(model) -> tuple[dict, dict]:
    """(json-metadata, arrays) for one coordinate model."""
    if isinstance(model, FixedEffectModel):
        glm = model.model
        meta = {
            "kind": "fixed",
            "feature_shard_id": model.feature_shard_id,
            "task": TaskType(glm.task).value,
        }
        arrays = {"means": np.asarray(glm.coefficients.means)}
        if glm.coefficients.variances is not None:
            arrays["variances"] = np.asarray(glm.coefficients.variances)
        return meta, arrays

    if isinstance(model, RandomEffectModel):
        entity_ids = list(model.entity_ids)
        ids_are_int = all(isinstance(e, (int, np.integer)) for e in entity_ids)
        meta = {
            "kind": "random",
            "re_type": model.re_type,
            "feature_shard_id": model.feature_shard_id,
            "task": TaskType(model.task).value,
            "entity_ids_int": ids_are_int,
        }
        arrays = {
            "coeffs": np.asarray(model.coeffs),
            "proj_indices": np.asarray(model.proj_indices),
            "entity_ids": (
                np.asarray(entity_ids, dtype=np.int64)
                if ids_are_int
                else np.asarray([str(e) for e in entity_ids])
            ),
        }
        if model.variances is not None:
            arrays["variances"] = np.asarray(model.variances)
        proj = model.projector
        if proj is not None:
            from photon_ml_tpu.data.projector import RandomProjector

            if not isinstance(proj, RandomProjector):
                raise TypeError(
                    f"Cannot checkpoint projector of type {type(proj).__name__}"
                )
            arrays["projector_matrix"] = np.asarray(proj.matrix)
            meta["projector_intercept_index"] = proj.intercept_index
            norm = proj.normalization
            if norm is not None:
                meta["projector_norm_intercept_index"] = norm.intercept_index
                if norm.factors is not None:
                    arrays["projector_norm_factors"] = np.asarray(norm.factors)
                if norm.shifts is not None:
                    arrays["projector_norm_shifts"] = np.asarray(norm.shifts)
        return meta, arrays

    raise TypeError(f"Unknown model type: {type(model).__name__}")


def _model_from_arrays(meta: dict, arrays, dtype) -> object:
    task = TaskType(meta["task"])
    if meta["kind"] == "fixed":
        variances = arrays.get("variances")
        coeffs = Coefficients(
            means=jnp.asarray(arrays["means"], dtype=dtype),
            variances=None if variances is None else jnp.asarray(variances, dtype=dtype),
        )
        return FixedEffectModel(
            model=model_class_for_task(task)(coeffs),
            feature_shard_id=meta["feature_shard_id"],
        )

    entity_ids = arrays["entity_ids"]
    ids = (
        tuple(int(e) for e in entity_ids)
        if meta["entity_ids_int"]
        else tuple(str(e) for e in entity_ids)
    )
    projector = None
    if "projector_matrix" in arrays:
        from photon_ml_tpu.data.projector import RandomProjector
        from photon_ml_tpu.normalization import NormalizationContext

        norm = None
        if "projector_norm_factors" in arrays or "projector_norm_shifts" in arrays:
            norm = NormalizationContext(
                factors=arrays.get("projector_norm_factors"),
                shifts=arrays.get("projector_norm_shifts"),
                intercept_index=meta.get("projector_norm_intercept_index"),
            )
        projector = RandomProjector(
            matrix=arrays["projector_matrix"],
            intercept_index=meta.get("projector_intercept_index"),
            normalization=norm,
        )
    variances = arrays.get("variances")
    return RandomEffectModel(
        re_type=meta["re_type"],
        feature_shard_id=meta["feature_shard_id"],
        task=task,
        entity_ids=ids,
        coeffs=jnp.asarray(arrays["coeffs"], dtype=dtype),
        proj_indices=jnp.asarray(arrays["proj_indices"], dtype=jnp.int32),
        variances=None if variances is None else jnp.asarray(variances, dtype=dtype),
        projector=projector,
    )


# ---------------------------------------------------------------- plumbing


def sha256_file(path: str) -> str:
    """Streaming SHA-256 of a file's bytes — the ONE content-fingerprint
    primitive every durable artifact in the store shares (checkpoint
    manifests/arrays here, corpus part files in continuous/manifest.py, and
    the content-addressed cold block pool in continuous/store.py, whose pool
    file NAMES are these digests)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


_sha256_file = sha256_file


def _write_models(directory: str, subdir: str, models: dict, manifest: dict,
                  checksums: dict) -> None:
    """One .npz per coordinate into <directory>/<subdir>; fills per-model meta
    into ``manifest`` and each file's SHA-256 into ``checksums`` (keyed by
    generation-relative path)."""
    for cid, model in models.items():
        meta, arrays = _model_to_arrays(model)
        manifest[cid] = meta
        rel = os.path.join(subdir, f"{cid}.npz") if subdir else f"{cid}.npz"
        path = os.path.join(directory, rel)
        action = faultpoint(FP_WRITE_ARRAYS)
        np.savez(path, **_encode_arrays(arrays))
        checksums[rel] = _sha256_file(path)
        if action == "corrupt":
            # simulated bit-rot: damage lands AFTER the checksum is recorded,
            # exactly the class restore's verification must catch
            corrupt_file(path)


def _read_models(directory: str, manifest: dict, dtype) -> dict:
    models = {}
    for cid, meta in manifest.items():
        arrays = _load_npz(os.path.join(directory, f"{cid}.npz"))
        models[cid] = _model_from_arrays(meta, arrays, dtype)
    return models


def _generations(root: str) -> list[tuple[int, str]]:
    """[(generation number, absolute path)] ascending; ignores staging/
    quarantined/legacy entries."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _GEN_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(root, name)))
    return sorted(out)


def _clean_stale_tmp(root: str) -> None:
    """Remove staging leftovers a crash mid-write leaked: ``gen-*.tmp`` dirs
    under the root and the legacy ``<root>.tmp`` sibling."""
    candidates = []
    if os.path.isdir(root):
        candidates += [
            os.path.join(root, n) for n in os.listdir(root) if n.endswith(_TMP_SUFFIX)
        ]
    legacy = root.rstrip(os.sep) + _TMP_SUFFIX
    if os.path.exists(legacy):
        candidates.append(legacy)
    for path in candidates:
        logger.info("removing stale checkpoint staging dir %s", path)
        shutil.rmtree(path, ignore_errors=True)


def _quarantine(path: str) -> None:
    """Move a failed-verification generation aside (never silently reuse it,
    never destroy the evidence)."""
    target = path + QUARANTINE_SUFFIX
    try:
        if os.path.exists(target):
            shutil.rmtree(target)
        os.rename(path, target)
        logger.warning("quarantined corrupt checkpoint generation: %s", target)
    except OSError:  # a failed quarantine must not block the rollback
        logger.warning("could not quarantine %s; ignoring it", path, exc_info=True)


# -------------------------------------------------- read-side generation API
# The serving hot-swap (serving/hotswap.py) is a READ-ONLY consumer of a
# training run's checkpoint directory: it polls for new generations and loads
# one specific generation after integrity verification. Unlike
# ``load_checkpoint`` it must never mutate the directory — quarantine and
# rollback are the training owner's recovery moves; a serving replica that
# renamed gen dirs would race the trainer (and every other replica).


def list_generations(directory: str) -> list[tuple[int, str]]:
    """Committed generations under a checkpoint root as ``[(number, path)]``
    ascending. Staging (``*.tmp``), quarantined (``*.corrupt``) and legacy
    entries are ignored; a missing root is an empty list, not an error."""
    return _generations(os.path.abspath(directory))


def load_generation(gen_dir: str, dtype=jnp.float32) -> dict:
    """Verify + load ONE specific generation directory (as returned by
    :func:`list_generations`): full SHA-256 integrity pass, then
    {completed_iterations, models, best_models, best_metric, best_metrics,
    incidents, generation, fingerprint}. ``dtype=None`` keeps every stored
    coefficient dtype (a bf16 deployment's tables load back as bf16,
    bit-exact); the default casts to float32 as before.

    Raises :class:`CheckpointCorruption` on any defect and touches nothing on
    disk — the caller decides whether to fall back to an older generation
    (the serving hot-swap rolls back to the generation it is already
    serving)."""
    return _verify_and_load_generation(os.path.abspath(gen_dir), dtype)


# ----------------------------------------------------- durable blacklist
# The serving fleet's canary verdict, made durable IN the generational store:
# when a generation fails deterministically (corrupt bytes, canary mismatch,
# warm-up crash), the rejecting process records a per-generation blacklist
# file under <root>/blacklist/. Every ReplicaSet / HotSwapManager reads the
# directory at bootstrap (and before each poll), so INDEPENDENT serving
# processes agree on rejected generations with no channel between them — one
# replica's canary spares the whole fleet, across restarts. Files are
# staged + atomically renamed with a SHA-256 sidecar (the store's integrity
# discipline); a damaged entry is ignored (the worst case is one redundant
# canary evaluation, never a wrong verdict adopted from bit-rot). Writes are
# best-effort: a read-only store degrades to in-memory blacklisting.

BLACKLIST_DIR = "blacklist"


def _blacklist_digest(generation: int, cause: str) -> str:
    return hashlib.sha256(f"{int(generation)}\x00{cause}".encode()).hexdigest()


def record_generation_blacklist(
    directory: str, generation: int, cause: str
) -> Optional[str]:
    """Durably record that ``generation`` under checkpoint root ``directory``
    was rejected deterministically. Returns the file path, or None when the
    store is unwritable (logged, never raised — a full disk must not take
    down serving).

    The integrity digest rides INSIDE the JSON, so one ``os.replace`` is the
    whole commit — a content/sidecar pair would have a torn window between
    its two renames that silently drops the verdict (the archive learned the
    same lesson in continuous/store.py)."""
    root = os.path.join(os.path.abspath(directory), BLACKLIST_DIR)
    final = os.path.join(root, f"{GEN_PREFIX}{int(generation):08d}.json")
    tmp = f"{final}{_TMP_SUFFIX}-{os.getpid()}"
    try:
        os.makedirs(root, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump(
                {
                    "generation": int(generation),
                    "cause": str(cause),
                    "sha256": _blacklist_digest(generation, str(cause)),
                },
                f,
            )
        os.replace(tmp, final)
        return final
    except OSError as e:
        logger.warning(
            "could not record blacklist verdict for generation %d under %s "
            "(%s); the verdict stays process-local", generation, directory, e,
        )
        try:
            os.remove(tmp)
        except OSError:
            pass
        return None


def _prune_blacklist(root: str) -> None:
    """Drop verdicts for generations older than the oldest RETAINED one:
    pruned generations can never become swap candidates again, so their
    verdict files would otherwise accumulate (and cost every poll's
    directory re-read) for the life of the store."""
    gens = _generations(root)
    if not gens:
        return
    oldest = gens[0][0]
    bl_root = os.path.join(root, BLACKLIST_DIR)
    if not os.path.isdir(bl_root):
        return
    for name in os.listdir(bl_root):
        m = re.match(rf"^{GEN_PREFIX}(\d{{8}})\.json$", name)
        if m and int(m.group(1)) < oldest:
            try:
                os.remove(os.path.join(bl_root, name))
            except OSError:
                pass


def load_generation_blacklist(directory: str) -> dict[int, str]:
    """{generation: cause} for every VERIFIED blacklist entry under the
    checkpoint root. Damaged or torn entries are skipped with a warning
    (treated as absent); a missing directory is an empty verdict set."""
    root = os.path.join(os.path.abspath(directory), BLACKLIST_DIR)
    out: dict[int, str] = {}
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        m = re.match(rf"^{GEN_PREFIX}(\d{{8}})\.json$", name)
        if not m:
            continue
        path = os.path.join(root, name)
        try:
            with open(path) as f:
                record = json.load(f)
            gen = int(record["generation"])
            cause = str(record.get("cause", ""))
            if record.get("sha256") != _blacklist_digest(gen, cause):
                raise ValueError("checksum mismatch")
            if gen != int(m.group(1)):
                raise ValueError(
                    f"generation {gen} does not match file name {name}"
                )
            out[gen] = cause
        except (OSError, ValueError, KeyError) as e:
            logger.warning(
                "ignoring damaged blacklist entry %s (%s)", path, e
            )
    return out


# ------------------------------------------------------------------ save / load


def save_checkpoint(
    directory: str,
    models: dict,
    completed_iterations: int,
    best_models: Optional[dict] = None,
    best_metric: Optional[float] = None,
    best_metrics: Optional[dict] = None,
    fingerprint: Optional[str] = None,
    incidents: Optional[list] = None,
    keep_generations: int = DEFAULT_KEEP_GENERATIONS,
    retry: Optional[Retry] = None,
    extra_state: Optional[dict] = None,
    aux_arrays: Optional[dict] = None,
) -> str:
    """Write a NEW checkpoint generation (staging dir + rename); returns its
    path. Keeps the newest ``keep_generations`` generations, pruning older
    ones (quarantined generations are left for inspection).

    ``fingerprint`` identifies the run configuration; ``load_checkpoint`` with
    a different fingerprint refuses the checkpoint, so a rerun with changed
    hyperparameters/data cannot silently reuse stale trained state.
    ``incidents`` (list of Incident or dicts) persists the run's survived-
    failure history into the manifest. Transient OSErrors retry with backoff;
    each attempt restages from scratch, so a failed attempt leaves nothing
    half-written.

    ``extra_state`` (JSON-serializable dict) rides inside the manifest —
    subsystem metadata such as the continuous-training corpus manifest and
    delta stats (photon_ml_tpu/continuous/). ``aux_arrays``
    ({name: {array_name: ndarray}}) persists non-model array artifacts (e.g.
    per-shard index-map name tables) as ``aux/<name>.npz`` under the same
    SHA-256 integrity regime as the model files; arrays must be
    pickle-free (numeric or unicode dtypes). Both round-trip through
    ``load_generation``/``load_checkpoint`` as the ``extra`` and ``aux``
    keys."""
    if keep_generations < 1:
        raise ValueError(f"keep_generations must be >= 1, got {keep_generations}")
    root = os.path.abspath(directory)
    incident_dicts = [
        i.to_dict() if isinstance(i, Incident) else dict(i) for i in (incidents or [])
    ]

    def _attempt() -> str:
        os.makedirs(root, exist_ok=True)
        _clean_stale_tmp(root)
        gens = _generations(root)
        gen_num = (gens[-1][0] + 1) if gens else 1
        final = os.path.join(root, f"{GEN_PREFIX}{gen_num:08d}")
        tmp = final + _TMP_SUFFIX
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)

        state = {
            "format": _FORMAT,
            "generation": gen_num,
            "completed_iterations": int(completed_iterations),
            "fingerprint": fingerprint,
            "best_metric": None if best_metric is None else float(best_metric),
            "best_metrics": (
                None
                if best_metrics is None
                else {k: float(v) for k, v in best_metrics.items()}
            ),
            "models": {},
            "best_models": None,
            "incidents": incident_dicts,
            "checksums": {},
            "extra": extra_state,
            "aux": sorted(aux_arrays) if aux_arrays else [],
        }
        _write_models(tmp, "", models, state["models"], state["checksums"])
        if best_models is not None:
            os.makedirs(os.path.join(tmp, BEST_DIR))
            state["best_models"] = {}
            _write_models(
                tmp, BEST_DIR, best_models, state["best_models"], state["checksums"]
            )
        if aux_arrays:
            os.makedirs(os.path.join(tmp, AUX_DIR))
            for name in sorted(aux_arrays):
                if "/" in name or os.sep in name or name.startswith("."):
                    raise ValueError(f"aux artifact name {name!r} must be a flat name")
                rel = os.path.join(AUX_DIR, f"{name}.npz")
                path = os.path.join(tmp, rel)
                action = faultpoint(FP_WRITE_ARRAYS)
                np.savez(path, **_encode_arrays(aux_arrays[name]))
                state["checksums"][rel] = _sha256_file(path)
                if action == "corrupt":
                    corrupt_file(path)

        action = faultpoint(FP_WRITE_MANIFEST)
        state_path = os.path.join(tmp, STATE_FILE)
        with open(state_path, "w") as f:
            json.dump(state, f)
        # the manifest's own integrity record: bit-rot inside syntactically
        # valid JSON is still detected at restore
        with open(os.path.join(tmp, STATE_SHA_FILE), "w") as f:
            f.write(_sha256_file(state_path) + "\n")
        if action == "corrupt":
            corrupt_file(state_path)

        faultpoint(FP_WRITE_COMMIT)
        os.rename(tmp, final)

        for _, old_path in _generations(root)[:-keep_generations]:
            shutil.rmtree(old_path, ignore_errors=True)
        _prune_blacklist(root)
        return final

    return (retry or _DEFAULT_RETRY).call(_attempt, description="checkpoint save")


def _verify_and_load_generation(gen_dir: str, dtype) -> dict:
    """Full integrity pass over one generation; raises CheckpointCorruption on
    ANY defect (missing file, checksum mismatch, unreadable manifest/arrays)."""
    state_path = os.path.join(gen_dir, STATE_FILE)
    sha_path = os.path.join(gen_dir, STATE_SHA_FILE)
    try:
        with open(sha_path) as f:
            expected = f.read().strip()
    except OSError as e:
        raise CheckpointCorruption(f"missing manifest checksum: {e}") from e
    actual = None
    try:
        actual = _sha256_file(state_path)
    except OSError as e:
        raise CheckpointCorruption(f"unreadable manifest: {e}") from e
    if actual != expected:
        raise CheckpointCorruption(
            f"manifest checksum mismatch in {gen_dir} "
            f"(expected {expected[:12]}…, got {actual[:12]}…)"
        )
    try:
        with open(state_path) as f:
            state = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruption(f"unparseable manifest: {e}") from e

    for rel, expected in state.get("checksums", {}).items():
        path = os.path.join(gen_dir, rel)
        try:
            actual = _sha256_file(path)
        except OSError as e:
            raise CheckpointCorruption(f"missing artifact {rel}: {e}") from e
        if actual != expected:
            raise CheckpointCorruption(
                f"artifact checksum mismatch: {rel} in {gen_dir}"
            )

    try:
        models = _read_models(gen_dir, state["models"], dtype)
        best_models = None
        if state.get("best_models") is not None:
            best_models = _read_models(
                os.path.join(gen_dir, BEST_DIR), state["best_models"], dtype
            )
        aux = {}
        for name in state.get("aux") or []:
            aux[name] = _load_npz(os.path.join(gen_dir, AUX_DIR, f"{name}.npz"))
    except Exception as e:  # torn .npz, bad metadata, dtype surprises ...
        raise CheckpointCorruption(f"unreadable model arrays: {e}") from e

    return {
        "completed_iterations": state["completed_iterations"],
        "best_metric": state["best_metric"],
        "best_metrics": state.get("best_metrics"),
        "models": models,
        "best_models": best_models,
        "incidents": list(state.get("incidents") or []),
        "generation": state.get("generation"),
        "fingerprint": state.get("fingerprint"),
        "extra": state.get("extra"),
        "aux": aux,
    }


def _load_legacy(directory: str, dtype) -> Optional[dict]:
    """Pre-generational layout: state.json directly in ``directory``. No
    checksums existed; a read failure quarantines the manifest so the next
    restore doesn't retry it (fresh-start fallback, never a raise)."""
    state_path = os.path.join(directory, STATE_FILE)
    if not os.path.exists(state_path):
        return None
    try:
        with open(state_path) as f:
            state = json.load(f)
        models = _read_models(directory, state["models"], dtype)
        best_models = None
        if state.get("best_models") is not None:
            best_models = _read_models(
                os.path.join(directory, BEST_DIR), state["best_models"], dtype
            )
    except Exception as e:
        logger.warning(
            "legacy checkpoint %s is unreadable (%s); quarantining it",
            directory, e,
        )
        try:
            os.rename(state_path, state_path + QUARANTINE_SUFFIX)
        except OSError:
            pass
        return None
    return {
        "completed_iterations": state["completed_iterations"],
        "best_metric": state["best_metric"],
        "best_metrics": state.get("best_metrics"),
        "models": models,
        "best_models": best_models,
        "incidents": list(state.get("incidents") or []),
        "generation": None,
        "fingerprint": state.get("fingerprint"),
    }


def _load_from_root(directory: str, dtype, sink: list) -> Optional[dict]:
    """Newest-valid-generation scan over one checkpoint root: verify newest
    first; quarantine + roll back on corruption; legacy layout as a last
    resort. Each rollback is recorded as a checkpoint-corruption incident in
    ``sink`` (and merged into the returned state's history when something
    loads — the sink outlives a restore that finds nothing valid). The WHOLE
    sink merges, not just this root's entries: when the main root was all
    corrupt and the .old fallback loads, its state must still carry the main
    root's quarantines (they happened during THIS restore)."""
    for gen_num, gen_dir in reversed(_generations(directory)):
        try:
            restored = _verify_and_load_generation(gen_dir, dtype)
        except CheckpointCorruption as e:
            logger.warning(
                "checkpoint generation %d failed verification (%s); "
                "rolling back to the previous generation", gen_num, e,
            )
            _quarantine(gen_dir)
            sink.append(
                Incident(
                    kind="checkpoint-corruption",
                    cause=str(e),
                    action=f"quarantined generation {gen_num}; rolled back",
                ).to_dict()
            )
            continue
        restored["incidents"] = restored["incidents"] + list(sink)
        return restored
    legacy = _load_legacy(directory, dtype)
    if legacy is not None:
        legacy["incidents"] = legacy["incidents"] + list(sink)
    return legacy


def load_checkpoint(
    directory: str,
    dtype=jnp.float32,
    fingerprint: Optional[str] = None,
    incident_sink: Optional[list] = None,
) -> Optional[dict]:
    """Restore {completed_iterations, models, best_models, best_metric,
    best_metrics, incidents, generation} from the newest generation that
    passes integrity verification, or None when no valid checkpoint exists.

    Never raises on damage: a torn/bit-rotted generation is quarantined and
    restore rolls back (the rollback appears in ``incidents``). Stale staging
    dirs from crashes mid-write are removed. A ``.old`` sibling left by the
    legacy overwrite dance is scanned as a fallback root. A saved
    ``fingerprint`` differing from the requested one rejects the checkpoint
    (that is a different RUN, not corruption — no rollback past it).

    ``incident_sink`` (a list) collects rollback incident dicts even when the
    restore ends in a fresh start (every generation corrupt): the caller can
    still record WHY there was nothing to resume from."""
    faultpoint(FP_RESTORE)
    directory = os.path.abspath(directory)
    _clean_stale_tmp(directory)
    sink = incident_sink if incident_sink is not None else []
    restored = _load_from_root(directory, dtype, sink)
    if restored is None:
        old = directory + ".old"
        if os.path.isdir(old):
            restored = _load_from_root(old, dtype, sink)
    if restored is None:
        return None
    if fingerprint is not None and restored.get("fingerprint") not in (None, fingerprint):
        return None
    restored.pop("fingerprint", None)
    return restored


class CoordinateDescentCheckpointer:
    """Save/restore hook handed to ``run_coordinate_descent``.

    ``interval`` saves every k-th completed iteration; the descent loop passes
    ``force=True`` on the final iteration so the completed state is always
    saved regardless of the interval. ``fingerprint`` (optional) ties the
    checkpoint to a run configuration: restore returns None when it differs.
    ``keep_generations`` bounds the rollback window (and the disk footprint).

    ``restore()`` never raises: any unexpected failure logs and falls back to
    a fresh start — a bad checkpoint must never be able to kill a run that
    could simply retrain.

    ``extra_state_provider`` (optional zero-arg callable returning a
    JSON-serializable dict or None) is polled at every save and rides the
    manifest's ``extra`` key — fingerprint-ADJACENT run state (e.g. the
    measured ``re_solver="auto"`` decisions) that a resume needs to replay
    bitwise but that must NOT invalidate the checkpoint the way a
    fingerprint mismatch does. ``restore()`` surfaces it back on the
    returned dict's ``"extra"`` key.
    """

    def __init__(
        self,
        directory: str,
        interval: int = 1,
        dtype=jnp.float32,
        fingerprint: Optional[str] = None,
        keep_generations: int = DEFAULT_KEEP_GENERATIONS,
        extra_state_provider=None,
    ):
        if interval < 1:
            raise ValueError(f"checkpoint interval must be >= 1, got {interval}")
        self.directory = directory
        self.interval = int(interval)
        self.dtype = dtype
        self.fingerprint = fingerprint
        self.keep_generations = int(keep_generations)
        self.extra_state_provider = extra_state_provider

    def maybe_save(
        self,
        completed_iterations: int,
        models: dict,
        best_models: Optional[dict],
        best_metric: Optional[float],
        best_metrics: Optional[dict] = None,
        force: bool = False,
        incidents: Optional[list] = None,
    ) -> bool:
        if not force and completed_iterations % self.interval != 0:
            return False
        extra = (
            self.extra_state_provider()
            if self.extra_state_provider is not None
            else None
        )
        save_checkpoint(
            self.directory,
            models,
            completed_iterations,
            best_models,
            best_metric,
            best_metrics,
            fingerprint=self.fingerprint,
            incidents=incidents,
            keep_generations=self.keep_generations,
            extra_state=extra,
        )
        return True

    def restore(self) -> Optional[dict]:
        """``self.restore_incidents`` afterwards holds any rollback incidents
        this restore produced — populated even when the result is None (all
        generations corrupt -> fresh start), so the run can still record why
        there was nothing to resume from."""
        self.restore_incidents: list = []
        try:
            return load_checkpoint(
                self.directory,
                dtype=self.dtype,
                fingerprint=self.fingerprint,
                incident_sink=self.restore_incidents,
            )
        except Exception:
            # the never-raise contract: unexpected damage (including errors
            # outside the per-generation verification) degrades to a fresh
            # start, not a crash loop. InjectedCrash (BaseException) still
            # propagates — a simulated process death is not recoverable.
            logger.exception(
                "checkpoint restore from %s failed; starting fresh", self.directory
            )
            return None

    def clear(self) -> None:
        # also drop the .old/.tmp siblings: load_checkpoint falls back to .old,
        # so leaving it would resurrect the state the caller tried to discard
        for path in (self.directory, self.directory + ".old", self.directory + _TMP_SUFFIX):
            if os.path.exists(path):
                shutil.rmtree(path)
