"""GAME model checkpoint I/O in the reference's on-disk layout.

Re-creates ModelProcessingUtils (photon-client data/avro/ModelProcessingUtils.scala:
59-625) without Spark/HDFS:

  <dir>/model-metadata.json
  <dir>/fixed-effect/<coordinate>/id-info
  <dir>/fixed-effect/<coordinate>/coefficients/part-00000.avro   (1 record)
  <dir>/random-effect/<coordinate>/id-info
  <dir>/random-effect/<coordinate>/coefficients/part-*.avro      (1 record / entity)

Coefficient records are BayesianLinearModelAvro (means + optional variances as
name-term-value lists), so checkpoints are byte-compatible with reference tooling.
Near-zero coefficients can be pruned at save (modelSparsityThreshold,
GameTrainingDriver.scala:165-168).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data import avro_io
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
from photon_ml_tpu.models.glm import (
    Coefficients,
    GeneralizedLinearModel,
    REFERENCE_CLASS_NAMES,
    task_for_reference_class,
)
from photon_ml_tpu.types import DELIMITER, TaskType

FIXED_EFFECT = "fixed-effect"
RANDOM_EFFECT = "random-effect"
ID_INFO = "id-info"
COEFFICIENTS = "coefficients"
METADATA_FILE = "model-metadata.json"


def _split_key(key: str) -> tuple[str, str]:
    if DELIMITER in key:
        name, term = key.split(DELIMITER, 1)
        return name, term
    return key, ""


def _coeffs_to_ntv(means, index_map: IndexMap, sparsity_threshold: float):
    out = []
    means = np.asarray(means)
    for j in np.flatnonzero(np.abs(means) > sparsity_threshold):
        name, term = _split_key(index_map.get_feature_name(int(j)) or str(int(j)))
        out.append({"name": name, "term": term, "value": float(means[j])})
    return out


def _ntv_to_coeffs(items, index_map: IndexMap) -> np.ndarray:
    vec = np.zeros(index_map.size)
    for it in items:
        j = index_map.get_index(feature_key(it['name'], it['term']))
        if j >= 0:
            vec[j] = it["value"]
    return vec


def _glm_record(
    model_id: str,
    means,
    variances,
    index_map: IndexMap,
    task: TaskType,
    sparsity_threshold: float,
) -> dict:
    rec = {
        "modelId": model_id,
        "modelClass": REFERENCE_CLASS_NAMES[TaskType(task)],
        "means": _coeffs_to_ntv(means, index_map, sparsity_threshold),
        "variances": None,
        "lossFunction": None,
    }
    if variances is not None:
        rec["variances"] = _coeffs_to_ntv(variances, index_map, 0.0)
    return rec


def save_glm_model(
    path: str,
    model: GeneralizedLinearModel,
    index_map: IndexMap,
    model_id: str = "",
    sparsity_threshold: float = 0.0,
) -> None:
    """Single GLM -> one BayesianLinearModelAvro container file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    variances = model.coefficients.variances
    rec = _glm_record(
        model_id, model.coefficients.means, variances, index_map, model.task, sparsity_threshold
    )
    avro_io.write_container(path, avro_io.BAYESIAN_LINEAR_MODEL_SCHEMA, [rec])


def load_glm_model(path: str, index_map: IndexMap, dtype=jnp.float32) -> GeneralizedLinearModel:
    recs = list(avro_io.read_container_dir(path))
    if len(recs) != 1:
        raise ValueError(f"{path}: expected 1 model record, found {len(recs)}")
    rec = recs[0]
    task = task_for_reference_class(rec.get("modelClass") or "") or TaskType.LINEAR_REGRESSION
    means = jnp.asarray(_ntv_to_coeffs(rec["means"], index_map), dtype=dtype)
    variances = rec.get("variances")
    var = jnp.asarray(_ntv_to_coeffs(variances, index_map), dtype=dtype) if variances else None
    return GeneralizedLinearModel(Coefficients(means, var), task)


def save_game_model(
    output_dir: str,
    game_model: GameModel,
    index_maps: dict[str, IndexMap],
    sparsity_threshold: float = 0.0,
    extra_metadata: Optional[dict] = None,
) -> None:
    os.makedirs(output_dir, exist_ok=True)
    n_re = sum(1 for _, m in game_model if isinstance(m, RandomEffectModel))
    model_type = "RANDOM_EFFECT" if n_re == len(game_model) else (
        "FIXED_EFFECT" if n_re == 0 else "GAME"
    )
    meta = {"modelType": model_type, "coordinates": game_model.coordinate_ids}
    if extra_metadata:
        meta.update(extra_metadata)
    with open(os.path.join(output_dir, METADATA_FILE), "w") as f:
        json.dump(meta, f, indent=2)

    for coord_id, model in game_model:
        index_map = index_maps[coord_id]
        if isinstance(model, FixedEffectModel):
            base = os.path.join(output_dir, FIXED_EFFECT, coord_id)
            os.makedirs(os.path.join(base, COEFFICIENTS), exist_ok=True)
            with open(os.path.join(base, ID_INFO), "w") as f:
                json.dump({"featureShardId": model.feature_shard_id}, f)
            glm = model.model
            rec = _glm_record(
                coord_id, glm.coefficients.means, glm.coefficients.variances,
                index_map, glm.task, sparsity_threshold,
            )
            avro_io.write_container(
                os.path.join(base, COEFFICIENTS, "part-00000.avro"),
                avro_io.BAYESIAN_LINEAR_MODEL_SCHEMA,
                [rec],
            )
        elif isinstance(model, RandomEffectModel):
            # random-projection models are stored in name space: back-project first
            # (the projected space is a runtime trick, not a storage format)
            model = model.to_original_space()
            base = os.path.join(output_dir, RANDOM_EFFECT, coord_id)
            os.makedirs(os.path.join(base, COEFFICIENTS), exist_ok=True)
            with open(os.path.join(base, ID_INFO), "w") as f:
                json.dump(
                    {"randomEffectType": model.re_type, "featureShardId": model.feature_shard_id},
                    f,
                )

            coeffs = np.asarray(model.coeffs)
            variances = None if model.variances is None else np.asarray(model.variances)
            proj = np.asarray(model.proj_indices)

            def entity_records():
                for row, entity_id in enumerate(model.entity_ids):
                    means, var_list = [], []
                    for k in range(proj.shape[1]):
                        j = int(proj[row, k])
                        # variances stay aligned with the surviving means (reference
                        # prunes both together at save)
                        if j < 0 or abs(coeffs[row, k]) <= sparsity_threshold:
                            continue
                        name, term = _split_key(index_map.get_feature_name(j) or str(j))
                        means.append({"name": name, "term": term, "value": float(coeffs[row, k])})
                        if variances is not None:
                            var_list.append(
                                {"name": name, "term": term, "value": float(variances[row, k])}
                            )
                    yield {
                        "modelId": str(entity_id),
                        "modelClass": REFERENCE_CLASS_NAMES[TaskType(model.task)],
                        "means": means,
                        "variances": var_list if variances is not None else None,
                        "lossFunction": None,
                    }

            avro_io.write_container(
                os.path.join(base, COEFFICIENTS, "part-00000.avro"),
                avro_io.BAYESIAN_LINEAR_MODEL_SCHEMA,
                entity_records(),
            )
        else:
            raise TypeError(f"Unknown model type for coordinate {coord_id}: {type(model)}")


def _read_id_info(path: str, *, random_effect: bool) -> dict:
    """Parse an ``id-info`` file in either on-disk dialect.

    This framework writes JSON; the reference's ModelProcessingUtils writes
    plain text lines (GameIntegTest fixtures: fixed effect = one line holding
    the feature shard id; random effect = randomEffectType then featureShardId,
    one per line — see saveModelToHDFS/loadGameModelFromHDFS in
    ModelProcessingUtils.scala). Both must load so reference-written model
    directories warm-start this framework directly.
    """
    with open(path) as f:
        text = f.read()
    try:
        info = json.loads(text)
        if isinstance(info, dict):
            return info
    except json.JSONDecodeError:
        pass
    lines = [ln.strip() for ln in text.splitlines() if ln.strip()]
    if random_effect:
        info = {}
        if lines:
            info["randomEffectType"] = lines[0]
        if len(lines) > 1:
            info["featureShardId"] = lines[1]
        return info
    return {"featureShardId": lines[0]} if lines else {}


def load_game_model(
    input_dir: str,
    index_maps: dict[str, IndexMap],
    dtype=jnp.float32,
) -> GameModel:
    """Load a GAME model saved by save_game_model or by the reference
    (ModelProcessingUtils.scala layout, including plain-text id-info files,
    multiple coefficient part files, and coefficient-less random-effect
    directories, which load as zero-entity models that score 0).

    Random-effect coordinates are rebuilt with per-entity index projections over the
    union of each entity's non-zero features.
    """
    models: dict[str, object] = {}

    fe_dir = os.path.join(input_dir, FIXED_EFFECT)
    if os.path.isdir(fe_dir):
        for coord_id in sorted(os.listdir(fe_dir)):
            base = os.path.join(fe_dir, coord_id)
            index_map = index_maps[coord_id]
            id_info = _read_id_info(os.path.join(base, ID_INFO), random_effect=False)
            glm = load_glm_model(os.path.join(base, COEFFICIENTS), index_map, dtype)
            models[coord_id] = FixedEffectModel(glm, id_info.get("featureShardId", "global"))

    re_dir = os.path.join(input_dir, RANDOM_EFFECT)
    if os.path.isdir(re_dir):
        for coord_id in sorted(os.listdir(re_dir)):
            base = os.path.join(re_dir, coord_id)
            index_map = index_maps[coord_id]
            id_info = _read_id_info(os.path.join(base, ID_INFO), random_effect=True)
            coeff_dir = os.path.join(base, COEFFICIENTS)
            recs = (
                list(avro_io.read_container_dir(coeff_dir))
                if os.path.isdir(coeff_dir)
                else []
            )
            entity_ids, rows, var_rows, proj_rows = [], [], [], []
            task = TaskType.LINEAR_REGRESSION
            max_k = 1
            parsed = []
            for rec in recs:
                task = task_for_reference_class(rec.get("modelClass") or "") or task
                cols = [
                    index_map.get_index(feature_key(m["name"], m["term"]))
                    for m in rec["means"]
                ]
                vals = [m["value"] for m in rec["means"]]
                keep = [(c, v) for c, v in zip(cols, vals) if c >= 0]
                var_by_col = {}
                for m in rec.get("variances") or []:
                    c = index_map.get_index(feature_key(m["name"], m["term"]))
                    if c >= 0:
                        var_by_col[c] = m["value"]
                parsed.append((rec["modelId"], keep, var_by_col))
                max_k = max(max_k, len(keep))
            for entity_id, keep, var_by_col in parsed:
                entity_ids.append(entity_id)
                coeff_row = np.zeros(max_k)
                proj_row = np.full(max_k, -1, dtype=np.int32)
                var_row = np.zeros(max_k)
                for k, (c, v) in enumerate(keep):
                    coeff_row[k] = v
                    proj_row[k] = c
                    var_row[k] = var_by_col.get(c, 0.0)
                rows.append(coeff_row)
                proj_rows.append(proj_row)
                var_rows.append(var_row)
            has_vars = any(v for _, _, v in parsed)
            models[coord_id] = RandomEffectModel(
                re_type=id_info.get("randomEffectType", coord_id),
                feature_shard_id=id_info.get("featureShardId", "global"),
                task=task,
                entity_ids=tuple(entity_ids),
                coeffs=jnp.asarray(np.stack(rows) if rows else np.zeros((0, 1)), dtype=dtype),
                proj_indices=jnp.asarray(
                    np.stack(proj_rows) if proj_rows else np.full((0, 1), -1, np.int32)
                ),
                variances=(
                    jnp.asarray(np.stack(var_rows), dtype=dtype) if has_vars and var_rows else None
                ),
            )

    # Preserve metadata coordinate order when available.
    meta_path = os.path.join(input_dir, METADATA_FILE)
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            order = json.load(f).get("coordinates", [])
        ordered = {c: models[c] for c in order if c in models}
        for c, m in models.items():
            if c not in ordered:
                ordered[c] = m
        models = ordered

    return GameModel(models=models)


def write_models_in_text(
    lambda_models,
    model_dir: str,
    index_map: IndexMap,
) -> None:
    """Legacy text model output (IOUtils.writeModelsInText:241-280): one part
    file per (regWeight, model), rows sorted by coefficient value DESCENDING,
    tab-separated ``name\\tterm\\tvalue\\tregWeight``."""
    os.makedirs(model_dir, exist_ok=True)
    for part, (reg_weight, model) in enumerate(lambda_models):
        means = np.asarray(model.coefficients.means)
        order = np.argsort(-means, kind="mergesort")
        lines = []
        for j in order:
            key = index_map.get_feature_name(int(j))
            if key is None:
                continue
            name, term = _split_key(key)
            lines.append(f"{name}\t{term}\t{float(means[j])}\t{float(reg_weight)}")
        with open(os.path.join(model_dir, f"part-{part:05d}.txt"), "w") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))


def read_models_from_text(model_dir: str, index_map: IndexMap):
    """Inverse of write_models_in_text: [(reg_weight, coefficient vector)]."""
    out = []
    for fname in sorted(os.listdir(model_dir)):
        if not fname.endswith(".txt"):
            continue
        vec = np.zeros(index_map.size)
        weight = None
        with open(os.path.join(model_dir, fname)) as f:
            for line in f:
                if not line.strip():
                    continue
                name, term, value, reg = line.rstrip("\n").split("\t")
                weight = float(reg)
                j = index_map.get_index(feature_key(name, term))
                if j >= 0:
                    vec[j] = float(value)
        if weight is not None:
            out.append((weight, vec))
    return out
