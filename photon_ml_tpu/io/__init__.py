from photon_ml_tpu.io.model_io import save_game_model, load_game_model, save_glm_model, load_glm_model

__all__ = ["save_game_model", "load_game_model", "save_glm_model", "load_glm_model"]
