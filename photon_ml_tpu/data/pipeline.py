"""Parallel streaming ingest pipeline: bounded thread-pooled block decode.

The reference amortized ingest across a Spark cluster; this single-controller
rebuild ingests on one host, where the sequential path leaves every core but
one idle for the whole ingest+prep phase. This module supplies the three
pieces the parallel path is built from:

- ``iter_file_blocks`` — the SEQUENTIAL block manifest: container framing is
  read file by file in listing order and every block's global row base is
  assigned before any decode work is scheduled. Determinism rests on this:
  whatever order workers finish in, a block's rows land at the row base the
  manifest gave it.
- ``map_ordered`` — a bounded, order-preserving thread-pool map: at most
  ``window`` blocks are in flight between the framing producer and the
  assembling consumer, so peak memory is O(window), not O(file set). The
  producer is generator-driven — a slow consumer stalls framing instead of
  letting raw payloads pile up. ``workers <= 1`` degenerates to a plain
  inline map (no pool, no reordering — the sequential path).
- ``BackgroundTask`` / ``start_xla_warmup`` — overlap for the work that
  FOLLOWS ingest: XLA backend init + a pilot compile (and, in callers,
  host->device transfers) run on a daemon thread while the main thread is
  busy with host-side ingest, so that latency hides behind I/O and decode
  instead of stacking after them.

The heavy per-block work this pipeline fans out — zlib inflate, the C++
``decode_block`` (a ctypes call), and numpy bulk ops — all release the GIL,
so a ThreadPoolExecutor gives real core overlap without pickling payloads
across processes.
"""

from __future__ import annotations

import atexit
import collections
import os
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, Optional, TypeVar

T = TypeVar("T")
R = TypeVar("R")

# Auto worker count is capped: ingest has a serial assembly tail (index-map
# application, csr construction), so returns diminish well before high core
# counts and an unbounded pool would just hold more payload windows in RAM.
DEFAULT_MAX_WORKERS = 8


def resolve_ingest_workers(workers: Optional[int]) -> int:
    """The ``ingest_workers`` contract shared by readers, CLI flags and the
    bench: None/0/"auto" -> min(cores, 8); 1 -> the sequential legacy path;
    N >= 2 -> N decode threads."""
    if workers is None or workers == 0 or workers == "auto":
        return max(1, min(os.cpu_count() or 1, DEFAULT_MAX_WORKERS))
    w = int(workers)
    if w < 1:
        raise ValueError(f"ingest_workers must be >= 1 (or None for auto), got {workers}")
    return w


def resolve_window(window: Optional[int], workers: int) -> int:
    """In-flight block budget: enough to keep ``workers`` busy across the
    consumer's assembly stalls, small enough to bound peak RSS at a handful
    of raw payloads."""
    if window is None:
        return max(4, 2 * workers)
    w = int(window)
    if w < 1:
        raise ValueError(f"ingest window must be >= 1, got {window}")
    return w


@dataclass
class RawBlock:
    """One container block as framed by the sequential manifest pass.

    ``payload`` is still compressed for deflate containers — inflate happens
    in the worker, off the producer thread. ``row_base``/``file_row`` are the
    block's first row in the global (concatenated, listing-order) sample axis
    and within its own file; both are fixed at framing time.
    """

    schema_json: Any
    codec: str
    payload: bytes
    n_records: int
    row_base: int
    file_path: str
    file_base: str
    file_row: int
    meta: Any = field(default=None)  # per-file metadata attached by callers


def iter_file_blocks(files: Iterable[str]) -> Iterator[RawBlock]:
    """The sequential block manifest: frame every container file in listing
    order and assign global row bases. Framing errors (bad magic, negative
    counts, sync-marker mismatch, truncation) raise here, on the caller's
    thread, exactly as they do on the sequential path."""
    from photon_ml_tpu.data import avro_io

    row_base = 0
    for file_path in files:
        file_base = os.path.basename(file_path)
        file_row = 0
        for schema_json, codec, payload, n_records in avro_io.iter_compressed_blocks(
            file_path
        ):
            yield RawBlock(
                schema_json=schema_json,
                codec=codec,
                payload=payload,
                n_records=n_records,
                row_base=row_base,
                file_path=file_path,
                file_base=file_base,
                file_row=file_row,
            )
            row_base += n_records
            file_row += n_records


def map_ordered(
    items: Iterable[T],
    fn: Callable[[T], R],
    workers: int,
    window: Optional[int] = None,
) -> Iterator[R]:
    """Map ``fn`` over ``items`` on a thread pool, yielding results in ITEM
    order with at most ``window`` items in flight.

    - ``workers <= 1``: plain inline map — no pool, the sequential path.
    - Results are yielded strictly in submission order regardless of worker
      completion order (the determinism contract).
    - The first worker exception propagates to the caller at the failing
      item's position, with unstarted work cancelled — the same exception
      type the sequential path would have raised at that item.
    - Producer pull is demand-driven: a consumer that stops iterating stalls
      the producer, so in-flight memory stays O(window) under any consumer.
    """
    workers = int(workers)
    if workers <= 1:
        for item in items:
            yield fn(item)
        return
    window = resolve_window(window, workers)
    pending: collections.deque = collections.deque()
    with ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix="photon-ingest"
    ) as pool:
        try:
            for item in items:
                if len(pending) >= window:
                    yield pending.popleft().result()
                pending.append(pool.submit(fn, item))
            while pending:
                yield pending.popleft().result()
        finally:
            # error or early consumer exit: drop unstarted work so pool
            # shutdown does not run the whole remaining manifest
            for fut in pending:
                fut.cancel()


class BackgroundTask:
    """A one-shot computation on a daemon thread, with fail-at-join semantics.

    Used to overlap post-ingest work (XLA warm-up compilation, host->device
    transfers) with host-side decode — and by the serving hot-swap
    (serving/hotswap.py) to pilot-compile a new model generation's engine
    while the live generation keeps serving. Start it, keep working,
    ``result()`` when the value is actually needed. Exceptions are captured
    and re-raised at ``result()`` — never swallowed, never crashing the
    spawning thread.

    Positional/keyword arguments after ``fn`` are passed through to it
    (``name`` is reserved for the thread name), so call sites don't need a
    closure for the common run-this-with-these-args case.
    """

    def __init__(self, fn: Callable[..., Any], *args: Any,
                 name: str = "photon-background", **kwargs: Any):
        self._value: Any = None
        self._exc: Optional[BaseException] = None
        self._finished = threading.Event()

        def _run():
            try:
                self._value = fn(*args, **kwargs)
            except BaseException as e:  # re-raised on the joining thread
                self._exc = e
            finally:
                self._finished.set()

        self._thread = threading.Thread(target=_run, name=name, daemon=True)
        self._thread.start()

    def done(self) -> bool:
        return self._finished.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._finished.wait(timeout):
            raise TimeoutError(f"background task {self._thread.name!r} still running")
        if self._exc is not None:
            raise self._exc
        return self._value


_warmup_lock = threading.Lock()
_warmup_task: Optional[BackgroundTask] = None


def start_xla_warmup() -> BackgroundTask:
    """Kick off XLA backend init + a pilot compile on a background thread.

    The first jitted program of a run pays backend/PJRT client creation and
    compiler-stack initialization on top of its own compile; started before
    ingest, that latency hides behind framing+decode instead of adding to
    time-to-first-update. The pilot is a tiny matmul-in-a-loop — enough to
    force device discovery, the lowering pipeline and the compile path; real
    programs still compile per shape, but against a warm stack.

    Idempotent per process: repeated calls return the same task. Callers may
    ignore the handle entirely (the thread is a daemon); joining via
    ``result()`` surfaces any backend failure.
    """
    global _warmup_task
    with _warmup_lock:
        if _warmup_task is not None:
            return _warmup_task

        def _warm():
            import jax
            import jax.numpy as jnp

            jax.devices()  # PJRT client + device discovery

            def pilot(a):
                def body(_, c):
                    return c + a @ a

                return jax.lax.fori_loop(0, 4, body, a).sum()

            out = jax.jit(pilot)(jnp.ones((8, 8), jnp.float32))
            # deliberate sync on a background thread: the task's contract is
            # "warm-up has COMPLETED when done() flips", and nothing on the
            # main thread waits on this
            out.block_until_ready()  # jaxlint: disable=HS001 warm-up runs on a daemon thread, off every hot path
            return True

        _warmup_task = BackgroundTask(_warm, name="photon-xla-warmup")
        # A daemon thread still inside XLA's C++ at interpreter teardown
        # aborts the whole process ("terminate called without an active
        # exception") — a fast CLI run can finish before the pilot compile
        # does. Draining the warm-up at exit (bounded; atexit runs before
        # thread teardown) costs nothing when the run outlived it.
        atexit.register(_warmup_task._finished.wait, 120.0)
        return _warmup_task
