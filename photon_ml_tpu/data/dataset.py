"""Labeled data containers.

``LabeledData`` is the device-side replacement for ``RDD[(UniqueSampleId, LabeledPoint)]``
(photon-lib data/LabeledPoint.scala:1-106): a struct-of-arrays pytree with labels,
offsets, weights and a design matrix. Padded rows carry weight 0 AND zeroed
features/labels/offsets, so every weighted reduction ignores them without masking.

``FixedEffectDataset`` mirrors photon-api data/FixedEffectDataset.scala:31-152 — one
global feature shard; "addScoresToOffsets" is an elementwise add over the global sample
axis (no joins: scores are dense arrays indexed by position).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.matrix import DesignMatrix, as_design_matrix

Array = jnp.ndarray


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LabeledData:
    """Batched labeled samples (label, features, offset, weight)."""

    X: DesignMatrix
    labels: Array  # [N]
    offsets: Array  # [N]
    weights: Array  # [N]

    @property
    def n(self) -> int:
        return self.labels.shape[0]

    @property
    def dim(self) -> int:
        return self.X.n_cols

    def margins(self, coef: Array) -> Array:
        """computeMargin: x.w + offset (LabeledPoint.scala:53-59)."""
        return self.X.matvec(coef) + self.offsets

    def with_offsets(self, offsets: Array) -> "LabeledData":
        return dataclasses.replace(self, offsets=offsets)

    def add_scores_to_offsets(self, scores: Array) -> "LabeledData":
        """FixedEffectDataset.addScoresToOffsets — elementwise, not a join."""
        return dataclasses.replace(self, offsets=self.offsets + scores)

    @staticmethod
    def build(X, labels, offsets=None, weights=None, dtype=None) -> "LabeledData":
        Xm = as_design_matrix(X, dtype=dtype)
        labels = jnp.asarray(labels, dtype=dtype)
        if not jnp.issubdtype(labels.dtype, jnp.floating):
            # Integer 0/1 labels are common; the solvers' while_loop carries require
            # a consistent float dtype, so coerce to the feature dtype.
            labels = labels.astype(Xm.dtype)
        n = labels.shape[0]
        if offsets is None:
            offsets = jnp.zeros(n, dtype=labels.dtype)
        else:
            offsets = jnp.asarray(offsets, dtype=labels.dtype)
        if weights is None:
            weights = jnp.ones(n, dtype=labels.dtype)
        else:
            weights = jnp.asarray(weights, dtype=labels.dtype)
        return LabeledData(X=Xm, labels=labels, offsets=offsets, weights=weights)


@dataclasses.dataclass
class FixedEffectDataset:
    """One global feature shard of the GAME dataset.

    Rows are positionally aligned with the global sample axis: coordinate scores are
    dense [N] arrays exchanged by position (replaces the reference's uniqueId joins).
    """

    data: LabeledData
    feature_shard_id: str = "global"
    # set by 2-D mesh placement (parallel/placement.py): coefficient vectors
    # and optimizer state live sharded over the model axis (feature-axis model
    # parallelism — per-device model memory ~ 1/n_model)
    coef_sharding: object = None

    @property
    def n(self) -> int:
        return self.data.n

    @property
    def dim(self) -> int:
        return self.data.dim

    def with_extra_offsets(self, scores: Array) -> "FixedEffectDataset":
        return dataclasses.replace(self, data=self.data.add_scores_to_offsets(scores))
