from photon_ml_tpu.data.matrix import DenseDesignMatrix, SparseDesignMatrix, DesignMatrix
from photon_ml_tpu.data.dataset import LabeledData, FixedEffectDataset

__all__ = [
    "DenseDesignMatrix",
    "SparseDesignMatrix",
    "DesignMatrix",
    "LabeledData",
    "FixedEffectDataset",
]
