"""Reader + writer for PalDB v1 stores — the reference's off-heap feature-index
format.

The reference builds feature index maps offline as partitioned PalDB
key-value stores (FeatureIndexingDriver.scala:41-320) and memory-maps them
per executor (PalDBIndexMap.scala:43-278, `com.linkedin.paldb:paldb:1.1.0`).
Each store holds BOTH directions — ``name\\x01term -> index`` and
``index -> name\\x01term`` — and a map spans ``partitionsNum`` files named
``paldb-partition-<namespace>-<i>.dat`` with global index = partition-local
index + cumulative offset (PalDBIndexMap.load:74-99, getIndex:145-153).

This module decodes that binary format natively (no JVM), so reference-built
index stores work directly as this framework's feature maps. Layout (reverse
engineered against the reference's committed stores, verified by the perfect
index<->name bijections in tests/test_reference_parity.py):

    writeUTF "PALDB_V1"; int64 timestamp;
    int32 keyCount, keyLengthCount, maxKeyLength;
    per distinct serialized-key length:
        int32 keyLength, keys, slots, slotSize, indexOffset; int64 dataOffset
    int64 globalIndexOffset, globalDataOffset
    index section: open-addressed slot arrays per key length —
        [serialized key | LEB128 data offset], offset 0 = empty slot
    data section: per-block regions, each led by a 0x00 sentinel;
        entry = [LEB128 length][serialized value]

Serialized values (PalDB's compact StorageSerialization):
    0x67 ('g') + LEB128 length + UTF-8 bytes        -> str
    0x05..0x0d                                      -> int 0..8
    0x0e + uint8                                    -> int 9..254 (one byte)
    0x10 + LEB128                                   -> int >= 255 (varint)

The WRITE side emits the same format so reference tooling can consume
repo-built index stores. Two details were pinned empirically against every
reference-committed store (103,520/103,520 slot placements and the full int
key range consistent — see tests/test_reference_parity.py):

  - slot placement: open addressing with linear probing from
    ``(murmur3_32(serialized_key, seed=42) & 0x7fffffff) % slots``
    (PalDB's HashUtils + StorageReader probe sequence);
  - table sizing: ``slots = round(keyCount / 0.75)`` per key-length block.

The int encodings are exact (not just decodable): a real-PalDB reader
serializes the QUERY key and compares bytes, so writing value 100 as
``0x10 0x64`` instead of ``0x0e 0x64`` would make its lookups miss.
"""

from __future__ import annotations

import os
import struct
from typing import Optional

from photon_ml_tpu.data.index_map import IndexMap

_MAGIC = b"PALDB_V1"


def _leb128(b: bytes, pos: int) -> tuple[int, int]:
    val = shift = 0
    while True:
        byte = b[pos]
        pos += 1
        val |= (byte & 0x7F) << shift
        shift += 7
        if not byte & 0x80:
            return val, pos


def _decode_value(b: bytes, pos: int):
    """One serialized value at ``pos`` (type-coded, see module docstring)."""
    code = b[pos]
    if code == 0x67:  # string
        ln, p = _leb128(b, pos + 1)
        return b[p : p + ln].decode("utf-8")
    if 0x05 <= code <= 0x0D:
        return code - 0x05
    if code == 0x0E:
        return b[pos + 1]
    if code == 0x10:
        val, _ = _leb128(b, pos + 1)
        return val
    raise ValueError(f"Unsupported PalDB serialization code 0x{code:02x}")


def read_paldb_store(path: str) -> dict:
    """Decode one ``.dat`` store into a plain dict (both directions:
    ``str -> int`` forward entries and ``int -> str`` reverse entries)."""
    with open(path, "rb") as f:
        b = f.read()
    (magic_len,) = struct.unpack(">H", b[:2])
    if b[2 : 2 + magic_len] != _MAGIC:
        raise ValueError(f"{path}: not a PalDB v1 store")
    off = 2 + magic_len + 8  # magic + timestamp

    def ri():
        nonlocal off
        (v,) = struct.unpack(">i", b[off : off + 4])
        off += 4
        return v

    def rl():
        nonlocal off
        (v,) = struct.unpack(">q", b[off : off + 8])
        off += 8
        return v

    key_count, n_lengths, _max_len = ri(), ri(), ri()
    blocks = []
    for _ in range(n_lengths):
        kl, _cnt, slots, slot_size = ri(), ri(), ri(), ri()
        index_off = ri()
        data_off = rl()
        blocks.append((kl, slots, slot_size, index_off, data_off))
    index_base, data_base = rl(), rl()

    out: dict = {}
    for kl, slots, slot_size, index_off, data_off in blocks:
        base = index_base + index_off
        for s in range(slots):
            slot = b[base + s * slot_size : base + (s + 1) * slot_size]
            offset, _ = _leb128(slot, kl)
            if offset == 0:  # empty slot
                continue
            key = _decode_value(slot, 0)
            pos = data_base + data_off + offset
            _entry_len, p = _leb128(b, pos)
            out[key] = _decode_value(b, p)
    if len(out) != key_count:
        raise ValueError(
            f"{path}: decoded {len(out)} keys, header declares {key_count}"
        )
    return out


def _encode_leb128(v: int) -> bytes:
    out = bytearray()
    while True:
        byte = v & 0x7F
        v >>= 7
        if v:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def _murmur3_32(data: bytes, seed: int = 42) -> int:
    """Murmur3 x86 32-bit, little-endian, seed 42 — PalDB's key hash (the
    seed and byte order were recovered by checking candidate hashes against
    the slot placements of every reference-committed store)."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed
    n = len(data)
    i = 0
    while i + 4 <= n:
        (k,) = struct.unpack_from("<I", data, i)
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
        i += 4
    k = 0
    tail = n & 3
    if tail >= 3:
        k ^= data[i + 2] << 16
    if tail >= 2:
        k ^= data[i + 1] << 8
    if tail >= 1:
        k ^= data[i]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def _serialize(value) -> bytes:
    """One key/value in PalDB's StorageSerialization (exact encodings — see
    module docstring)."""
    if isinstance(value, bool):
        raise TypeError("PalDB index stores hold str and int entries only")
    if isinstance(value, str):
        raw = value.encode("utf-8")
        return b"\x67" + _encode_leb128(len(raw)) + raw
    if isinstance(value, int):
        if value < 0:
            raise ValueError(f"negative int {value} not supported in index stores")
        if value <= 8:
            return bytes([0x05 + value])
        if value < 255:
            return bytes([0x0E, value])
        return b"\x10" + _encode_leb128(value)
    raise TypeError(f"unsupported PalDB entry type {type(value).__name__}")


def write_paldb_store(path: str, mapping: dict, timestamp_ms: int = 0) -> None:
    """Write one PalDB v1 ``.dat`` store readable by :func:`read_paldb_store`
    AND by the reference's PalDB 1.1.0 reader (PalDBIndexMap.scala:43-278).

    ``mapping`` holds both directions the way the reference stores do
    (``str -> int`` forward and ``int -> str`` reverse entries)."""
    pairs = [( _serialize(k), _serialize(v)) for k, v in mapping.items()]
    by_len: dict[int, list] = {}
    for kb, vb in sorted(pairs):  # deterministic layout
        by_len.setdefault(len(kb), []).append((kb, vb))

    blocks = []
    index_off = 0
    data_off = 0
    for kl in sorted(by_len):
        entries = by_len[kl]
        # data region: 0x00 sentinel so a real entry never sits at offset 0
        # (offset 0 marks an empty slot in the index)
        region = bytearray(b"\x00")
        offsets = []
        for _, vb in entries:
            offsets.append(len(region))
            region += _encode_leb128(len(vb)) + vb
        slots = max(1, int(len(entries) / 0.75 + 0.5))
        slot_size = kl + len(_encode_leb128(max(offsets)))
        table = bytearray(slots * slot_size)
        for (kb, _), off in zip(entries, offsets):
            s = (_murmur3_32(kb) & 0x7FFFFFFF) % slots
            while table[s * slot_size + kl]:  # occupied: offset byte non-zero
                s = (s + 1) % slots
            enc = kb + _encode_leb128(off)
            table[s * slot_size : s * slot_size + len(enc)] = enc
        blocks.append((kl, len(entries), slots, slot_size, index_off, data_off, table, region))
        index_off += len(table)
        data_off += len(region)

    header = bytearray()
    header += struct.pack(">H", len(_MAGIC)) + _MAGIC
    header += struct.pack(">q", timestamp_ms)
    max_kl = max(by_len) if by_len else 0
    header += struct.pack(">iii", len(pairs), len(blocks), max_kl)
    for kl, cnt, slots, slot_size, io_, do_, _, _ in blocks:
        header += struct.pack(">iiiii", kl, cnt, slots, slot_size, io_)
        header += struct.pack(">q", do_)
    index_base = len(header) + 16  # + the two int64s below
    data_base = index_base + index_off
    header += struct.pack(">qq", index_base, data_base)

    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(header)
        for *_, table, _ in blocks:
            f.write(table)
        for *_, region in blocks:
            f.write(region)
    os.replace(tmp, path)


def write_paldb_index_map(
    directory: str, namespace: str, names, num_partitions: int = 1
) -> None:
    """Write ``names`` (an IndexMap or any ordered feature-name sequence) as a
    partitioned PalDB index map under ``directory``.

    Partitions hold CONTIGUOUS chunks so that :func:`load_paldb_index_map`'s
    global-index rule (local index + cumulative offset,
    PalDBIndexMap.load:74-99) reproduces the input order exactly — the
    round-trip preserves every global feature index."""
    if num_partitions < 1:
        raise ValueError("num_partitions must be >= 1")
    names = list(names)
    os.makedirs(directory, exist_ok=True)
    base = len(names) // num_partitions
    extra = len(names) % num_partitions
    start = 0
    for p in range(num_partitions):
        size = base + (1 if p < extra else 0)
        chunk = names[start : start + size]
        start += size
        store: dict = {}
        for local, name in enumerate(chunk):
            store[name] = local
            store[local] = name
        write_paldb_store(
            os.path.join(directory, partition_filename(namespace, p)), store
        )


def partition_filename(namespace: str, partition: int) -> str:
    """PalDBIndexMap.partitionFilename (PalDBIndexMap.scala:218)."""
    return f"paldb-partition-{namespace}-{partition}.dat"


def discover_partitions(directory: str, namespace: str) -> int:
    """Count partition files for ``namespace`` under ``directory``.

    Globs every ``paldb-partition-<ns>-*.dat`` and requires the indices to be
    exactly 0..n-1: a missing middle partition must fail loudly, not silently
    truncate the index map (which would drop features and shrink the global
    index space under the trainer)."""
    prefix = f"paldb-partition-{namespace}-"
    indices = []
    for fname in os.listdir(directory) if os.path.isdir(directory) else []:
        if fname.startswith(prefix) and fname.endswith(".dat"):
            stem = fname[len(prefix) : -len(".dat")]
            if stem.isdigit():
                indices.append(int(stem))
    if not indices:
        return 0
    indices.sort()
    if indices != list(range(len(indices))):
        raise ValueError(
            f"{directory}: partition files for namespace {namespace!r} are not "
            f"dense 0..{len(indices) - 1} (found {indices}); refusing to load a "
            "truncated index map"
        )
    return len(indices)


def load_paldb_index_map(
    directory: str, namespace: str, num_partitions: Optional[int] = None
) -> IndexMap:
    """Load a partitioned reference-built PalDB index map as an IndexMap.

    Global index = partition-local index + cumulative offset, offsets being
    the running sum of per-partition feature counts (store size / 2, both
    directions live in one store) — PalDBIndexMap.load:74-99 semantics. The
    returned IndexMap preserves those exact global indices."""
    if num_partitions is None:
        num_partitions = discover_partitions(directory, namespace)
    if num_partitions <= 0:
        raise FileNotFoundError(
            f"No PalDB partitions for namespace {namespace!r} in {directory}"
        )
    names: list[str] = []
    for i in range(num_partitions):
        path = os.path.join(directory, partition_filename(namespace, i))
        store = read_paldb_store(path)
        part = {k: v for k, v in store.items() if isinstance(k, int)}
        if set(part) != set(range(len(part))):
            raise ValueError(
                f"{path}: reverse index entries are not dense 0..{len(part) - 1} "
                "(corrupt store or not a PalDBIndexMap store)"
            )
        # partition-local indices are dense 0..n-1; append in order so the
        # global position reproduces idx + offset
        names.extend(part[j] for j in range(len(part)))
    return IndexMap(names)
