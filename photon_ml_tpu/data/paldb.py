"""Reader for PalDB v1 stores — the reference's off-heap feature-index format.

The reference builds feature index maps offline as partitioned PalDB
key-value stores (FeatureIndexingDriver.scala:41-320) and memory-maps them
per executor (PalDBIndexMap.scala:43-278, `com.linkedin.paldb:paldb:1.1.0`).
Each store holds BOTH directions — ``name\\x01term -> index`` and
``index -> name\\x01term`` — and a map spans ``partitionsNum`` files named
``paldb-partition-<namespace>-<i>.dat`` with global index = partition-local
index + cumulative offset (PalDBIndexMap.load:74-99, getIndex:145-153).

This module decodes that binary format natively (no JVM), so reference-built
index stores work directly as this framework's feature maps. Layout (reverse
engineered against the reference's committed stores, verified by the perfect
index<->name bijections in tests/test_reference_parity.py):

    writeUTF "PALDB_V1"; int64 timestamp;
    int32 keyCount, keyLengthCount, maxKeyLength;
    per distinct serialized-key length:
        int32 keyLength, keys, slots, slotSize, indexOffset; int64 dataOffset
    int64 globalIndexOffset, globalDataOffset
    index section: open-addressed slot arrays per key length —
        [serialized key | LEB128 data offset], offset 0 = empty slot
    data section: per-block regions, each led by a 0x00 sentinel;
        entry = [LEB128 length][serialized value]

Serialized values (PalDB's compact StorageSerialization):
    0x67 ('g') + LEB128 length + UTF-8 bytes        -> str
    0x05..0x0d                                      -> int 0..8
    0x0e + uint8                                    -> int (one byte)
    0x10 + LEB128                                   -> int (varint)
"""

from __future__ import annotations

import os
import struct
from typing import Optional

from photon_ml_tpu.data.index_map import IndexMap

_MAGIC = b"PALDB_V1"


def _leb128(b: bytes, pos: int) -> tuple[int, int]:
    val = shift = 0
    while True:
        byte = b[pos]
        pos += 1
        val |= (byte & 0x7F) << shift
        shift += 7
        if not byte & 0x80:
            return val, pos


def _decode_value(b: bytes, pos: int):
    """One serialized value at ``pos`` (type-coded, see module docstring)."""
    code = b[pos]
    if code == 0x67:  # string
        ln, p = _leb128(b, pos + 1)
        return b[p : p + ln].decode("utf-8")
    if 0x05 <= code <= 0x0D:
        return code - 0x05
    if code == 0x0E:
        return b[pos + 1]
    if code == 0x10:
        val, _ = _leb128(b, pos + 1)
        return val
    raise ValueError(f"Unsupported PalDB serialization code 0x{code:02x}")


def read_paldb_store(path: str) -> dict:
    """Decode one ``.dat`` store into a plain dict (both directions:
    ``str -> int`` forward entries and ``int -> str`` reverse entries)."""
    with open(path, "rb") as f:
        b = f.read()
    (magic_len,) = struct.unpack(">H", b[:2])
    if b[2 : 2 + magic_len] != _MAGIC:
        raise ValueError(f"{path}: not a PalDB v1 store")
    off = 2 + magic_len + 8  # magic + timestamp

    def ri():
        nonlocal off
        (v,) = struct.unpack(">i", b[off : off + 4])
        off += 4
        return v

    def rl():
        nonlocal off
        (v,) = struct.unpack(">q", b[off : off + 8])
        off += 8
        return v

    key_count, n_lengths, _max_len = ri(), ri(), ri()
    blocks = []
    for _ in range(n_lengths):
        kl, _cnt, slots, slot_size = ri(), ri(), ri(), ri()
        index_off = ri()
        data_off = rl()
        blocks.append((kl, slots, slot_size, index_off, data_off))
    index_base, data_base = rl(), rl()

    out: dict = {}
    for kl, slots, slot_size, index_off, data_off in blocks:
        base = index_base + index_off
        for s in range(slots):
            slot = b[base + s * slot_size : base + (s + 1) * slot_size]
            offset, _ = _leb128(slot, kl)
            if offset == 0:  # empty slot
                continue
            key = _decode_value(slot, 0)
            pos = data_base + data_off + offset
            _entry_len, p = _leb128(b, pos)
            out[key] = _decode_value(b, p)
    if len(out) != key_count:
        raise ValueError(
            f"{path}: decoded {len(out)} keys, header declares {key_count}"
        )
    return out


def partition_filename(namespace: str, partition: int) -> str:
    """PalDBIndexMap.partitionFilename (PalDBIndexMap.scala:218)."""
    return f"paldb-partition-{namespace}-{partition}.dat"


def discover_partitions(directory: str, namespace: str) -> int:
    """Count partition files for ``namespace`` under ``directory``.

    Globs every ``paldb-partition-<ns>-*.dat`` and requires the indices to be
    exactly 0..n-1: a missing middle partition must fail loudly, not silently
    truncate the index map (which would drop features and shrink the global
    index space under the trainer)."""
    prefix = f"paldb-partition-{namespace}-"
    indices = []
    for fname in os.listdir(directory) if os.path.isdir(directory) else []:
        if fname.startswith(prefix) and fname.endswith(".dat"):
            stem = fname[len(prefix) : -len(".dat")]
            if stem.isdigit():
                indices.append(int(stem))
    if not indices:
        return 0
    indices.sort()
    if indices != list(range(len(indices))):
        raise ValueError(
            f"{directory}: partition files for namespace {namespace!r} are not "
            f"dense 0..{len(indices) - 1} (found {indices}); refusing to load a "
            "truncated index map"
        )
    return len(indices)


def load_paldb_index_map(
    directory: str, namespace: str, num_partitions: Optional[int] = None
) -> IndexMap:
    """Load a partitioned reference-built PalDB index map as an IndexMap.

    Global index = partition-local index + cumulative offset, offsets being
    the running sum of per-partition feature counts (store size / 2, both
    directions live in one store) — PalDBIndexMap.load:74-99 semantics. The
    returned IndexMap preserves those exact global indices."""
    if num_partitions is None:
        num_partitions = discover_partitions(directory, namespace)
    if num_partitions <= 0:
        raise FileNotFoundError(
            f"No PalDB partitions for namespace {namespace!r} in {directory}"
        )
    names: list[str] = []
    for i in range(num_partitions):
        path = os.path.join(directory, partition_filename(namespace, i))
        store = read_paldb_store(path)
        part = {k: v for k, v in store.items() if isinstance(k, int)}
        if set(part) != set(range(len(part))):
            raise ValueError(
                f"{path}: reverse index entries are not dense 0..{len(part) - 1} "
                "(corrupt store or not a PalDBIndexMap store)"
            )
        # partition-local indices are dense 0..n-1; append in order so the
        # global position reproduces idx + offset
        names.extend(part[j] for j in range(len(part)))
    return IndexMap(names)
