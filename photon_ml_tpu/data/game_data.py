"""GameInput: the host-side tabular input to GAME training/scoring.

Replaces the reference's DataFrame -> RDD[(UniqueSampleId, GameDatum)] conversion
(photon-api data/GameConverters.scala:28-173, data/GameDatum.scala:1-74). A GameDatum
held (response, offset, weight, feature-shard map, id tags) per row; GameInput holds
the same content as struct-of-arrays: per-shard feature matrices aligned on one
global sample axis, plus id columns for random-effect grouping and per-group
evaluation. The uniqueId join key disappears — position on the sample axis IS the id.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional

import numpy as np
import scipy.sparse as sp


@dataclasses.dataclass
class GameInput:
    """One table of samples for GAME training or scoring.

    features: feature_shard_id -> [N, D_shard] matrix (scipy sparse or ndarray)
    id_columns: id tag (e.g. "userId") -> [N] entity ids (used both for
        random-effect grouping and MultiEvaluator grouping)
    """

    features: Mapping[str, object]
    labels: Optional[np.ndarray] = None
    offsets: Optional[np.ndarray] = None
    weights: Optional[np.ndarray] = None
    id_columns: Mapping[str, np.ndarray] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        ns = {shard: m.shape[0] for shard, m in self.features.items()}
        if len(set(ns.values())) > 1:
            raise ValueError(f"Feature shards disagree on sample count: {ns}")
        n = self.n
        if self.labels is not None and len(self.labels) != n:
            raise ValueError(f"labels length {len(self.labels)} != {n}")
        if self.offsets is None:
            self.offsets = np.zeros(n)
        elif len(self.offsets) != n:
            raise ValueError(f"offsets length {len(self.offsets)} != {n}")
        if self.weights is None:
            self.weights = np.ones(n)
        elif len(self.weights) != n:
            raise ValueError(f"weights length {len(self.weights)} != {n}")
        for tag, col in self.id_columns.items():
            if len(col) != n:
                raise ValueError(f"id column {tag!r} length {len(col)} != {n}")

    @property
    def n(self) -> int:
        if not self.features:
            raise ValueError("GameInput needs at least one feature shard")
        return next(iter(self.features.values())).shape[0]

    @property
    def has_labels(self) -> bool:
        return self.labels is not None

    def shard(self, feature_shard_id: str):
        try:
            return self.features[feature_shard_id]
        except KeyError:
            raise KeyError(
                f"Unknown feature shard {feature_shard_id!r}; have {list(self.features)}"
            ) from None

    def ids(self, tag: str) -> np.ndarray:
        try:
            return self.id_columns[tag]
        except KeyError:
            raise KeyError(
                f"Unknown id column {tag!r}; have {list(self.id_columns)}"
            ) from None

    def select(self, idx: np.ndarray) -> "GameInput":
        """Row subset (bootstrap resamples, train/validation splits)."""
        feats = {
            s: (m[idx] if sp.issparse(m) else np.asarray(m)[idx])
            for s, m in self.features.items()
        }
        return GameInput(
            features=feats,
            labels=None if self.labels is None else np.asarray(self.labels)[idx],
            offsets=np.asarray(self.offsets)[idx],
            weights=np.asarray(self.weights)[idx],
            id_columns={t: np.asarray(c)[idx] for t, c in self.id_columns.items()},
        )


def as_csr(m) -> sp.csr_matrix:
    return m.tocsr() if sp.issparse(m) else sp.csr_matrix(np.asarray(m))


def build_fixed_effect_scoring_dataset(data: GameInput, feature_shard_id: str, dtype=None):
    """Label-free-tolerant FixedEffectDataset for validation / transform scoring
    (shared by GameEstimator.prepare_scoring_datasets and GameTransformer)."""
    from photon_ml_tpu.data.dataset import FixedEffectDataset, LabeledData

    labels = data.labels if data.has_labels else np.zeros(data.n)
    return FixedEffectDataset(
        LabeledData.build(
            data.shard(feature_shard_id),
            labels,
            offsets=data.offsets,
            weights=data.weights,
            dtype=dtype,
        ),
        feature_shard_id=feature_shard_id,
    )


def build_random_effect_scoring_dataset(
    data: GameInput, random_effect_type: str, feature_shard_id: str, dtype=None,
    projector=None,
):
    """Scoring-view-only RandomEffectDataset (no training buckets materialized).
    ``projector`` must be the SAME RandomProjector the model was trained under so
    projected-space coefficients line up."""
    from photon_ml_tpu.data.random_effect import build_random_effect_dataset

    kwargs = {} if dtype is None else {"dtype": dtype}
    return build_random_effect_dataset(
        as_csr(data.shard(feature_shard_id)),
        data.ids(random_effect_type),
        random_effect_type,
        feature_shard_id=feature_shard_id,
        scoring_only=True,
        projector=projector,
        **kwargs,
    )
