"""Off-heap (memory-mapped) feature index store.

Parity target: photon-api index/PalDBIndexMap.scala:43-278 +
PalDBIndexMapLoader.scala:111 + PalDBIndexMapBuilder.scala:98 — the reference
stores feature name<->index maps for billions of features in PalDB files,
partitioned by key hash, memory-mapped per executor so the map never lives on
the JVM heap.

This build's equivalent: one binary file per partition containing
  header | open-addressing hash table | reverse (index -> slot) table | key blob
memory-mapped via numpy. Lookups probe the hash table directly against the
mmap — nothing is materialized in RAM beyond touched pages, so a store with
hundreds of millions of keys costs only page cache. Forward (key -> index) is
O(1); reverse (index -> key) is a binary search over the partition's reverse
table. Global indices are contiguous ordinals over the sorted key set (unlike
the reference's local*P+partition interleave, which leaves gaps when hash
partitions are uneven — contiguous ids keep design-matrix widths == key count).

Partition file layout (little endian):
  [0:8)    magic "PHOFIDX1"
  [8:16)   n_keys (u64)
  [16:24)  table_slots (u64)  — open addressing, power of two, load <= 0.5
  [24:32)  blob_offset (u64)
  [32:a)   hash table: table_slots x (hash u64, key_off u64, key_len u32, index u64)
  [a:blob_offset)  reverse table: n_keys x (index u64, slot u64), sorted by index
  [blob_offset:)   key blob: concatenated utf-8 keys
"""

from __future__ import annotations

import os
from typing import Iterable, Optional

import numpy as np

MAGIC = b"PHOFIDX1"
_HEADER = 32
_SLOT_DTYPE = np.dtype(
    [("hash", "<u8"), ("key_off", "<u8"), ("key_len", "<u4"), ("index", "<u8")]
)
_REV_DTYPE = np.dtype([("index", "<u8"), ("slot", "<u8")])
_EMPTY = np.uint64(0xFFFFFFFFFFFFFFFF)


def _fnv1a(data: bytes) -> int:
    """64-bit FNV-1a — stable across processes (unlike Python's salted hash)."""
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def _fnv1a_batch(keys) -> np.ndarray:
    return np.fromiter((_fnv1a(k.encode()) for k in keys), dtype=np.uint64, count=len(keys))


class OffHeapIndexMapBuilder:
    """PalDBIndexMapBuilder equivalent: accumulates keys, partitions by hash,
    writes one store file per partition."""

    def __init__(self, output_dir: str, num_partitions: int = 1):
        if num_partitions < 1:
            raise ValueError("num_partitions must be >= 1")
        self.output_dir = output_dir
        self.num_partitions = num_partitions
        self._seen: set[str] = set()

    def put(self, key: str) -> "OffHeapIndexMapBuilder":
        self._seen.add(key)
        return self

    def put_all(self, keys: Iterable[str]) -> "OffHeapIndexMapBuilder":
        self._seen.update(keys)
        return self

    def build(self) -> "OffHeapIndexMap":
        os.makedirs(self.output_dir, exist_ok=True)
        keys = sorted(self._seen)  # deterministic ordinal assignment
        hashes = _fnv1a_batch(keys) if keys else np.zeros(0, dtype=np.uint64)
        parts = (
            (hashes % np.uint64(self.num_partitions)).astype(np.int64)
            if len(keys)
            else np.zeros(0, dtype=np.int64)
        )
        for p in range(self.num_partitions):
            idx = np.flatnonzero(parts == p)
            _write_partition(
                os.path.join(self.output_dir, f"part-{p:05d}.bin"),
                [keys[i] for i in idx],
                hashes[idx],
                idx.astype(np.uint64),  # contiguous global ordinals
            )
        with open(os.path.join(self.output_dir, "meta"), "w") as f:
            f.write(f"{self.num_partitions}\n{len(keys)}\n")
        return OffHeapIndexMap(self.output_dir)


def _write_partition(path: str, keys: list, hashes: np.ndarray, indices: np.ndarray) -> None:
    n = len(keys)
    slots = 16
    while slots < 2 * max(n, 1):
        slots *= 2
    table = np.zeros(slots, dtype=_SLOT_DTYPE)
    table["hash"][:] = _EMPTY
    slot_of = np.zeros(n, dtype=np.uint64)
    blob_parts: list[bytes] = []
    off = 0
    mask = slots - 1
    for i, key in enumerate(keys):
        data = key.encode()
        h = int(hashes[i])
        s = h & mask
        while table["hash"][s] != _EMPTY:
            s = (s + 1) & mask
        table["hash"][s] = h
        table["key_off"][s] = off
        table["key_len"][s] = len(data)
        table["index"][s] = indices[i]
        slot_of[i] = s
        blob_parts.append(data)
        off += len(data)
    rev = np.zeros(n, dtype=_REV_DTYPE)
    rev["index"] = indices
    rev["slot"] = slot_of
    rev = rev[np.argsort(rev["index"], kind="stable")]
    blob = b"".join(blob_parts)
    with open(path, "wb") as f:
        f.write(MAGIC)
        f.write(np.uint64(n).tobytes())
        f.write(np.uint64(slots).tobytes())
        f.write(np.uint64(_HEADER + table.nbytes + rev.nbytes).tobytes())
        f.write(table.tobytes())
        f.write(rev.tobytes())
        f.write(blob)


class _Partition:
    def __init__(self, path: str):
        raw = np.memmap(path, dtype=np.uint8, mode="r")
        if bytes(raw[:8]) != MAGIC:
            raise ValueError(f"{path}: not an off-heap index store")
        header = raw[8:_HEADER].view("<u8")
        self.n_keys = int(header[0])
        self.slots = int(header[1])
        blob_offset = int(header[2])
        table_end = _HEADER + self.slots * _SLOT_DTYPE.itemsize
        self.table = raw[_HEADER:table_end].view(_SLOT_DTYPE)
        self.rev = raw[table_end:blob_offset].view(_REV_DTYPE)
        self.blob = raw[blob_offset:]
        self.mask = self.slots - 1

    def _key_at_slot(self, s: int) -> str:
        off = int(self.table["key_off"][s])
        ln = int(self.table["key_len"][s])
        return bytes(self.blob[off : off + ln]).decode()

    def get(self, key: str, h: int) -> int:
        s = h & self.mask
        table = self.table
        while True:
            slot_hash = int(table["hash"][s])
            if slot_hash == int(_EMPTY):
                return -1
            if slot_hash == h and self._key_at_slot(s) == key:
                return int(table["index"][s])
            s = (s + 1) & self.mask

    def key_for_index(self, index: int) -> Optional[str]:
        pos = int(np.searchsorted(self.rev["index"], np.uint64(index)))
        if pos >= self.n_keys or int(self.rev["index"][pos]) != index:
            return None
        return self._key_at_slot(int(self.rev["slot"][pos]))


class OffHeapIndexMap:
    """Read side (PalDBIndexMap): mmap partitions, O(1) forward lookup, binary-
    search reverse lookup.

    Implements the same surface as data.index_map.IndexMap so shard configs,
    readers and model IO accept either implementation.
    """

    def __init__(self, directory: str):
        with open(os.path.join(directory, "meta")) as f:
            self.num_partitions = int(f.readline())
            self._size = int(f.readline())
        self.directory = directory
        self._parts = [
            _Partition(os.path.join(directory, f"part-{p:05d}.bin"))
            for p in range(self.num_partitions)
        ]

    @property
    def size(self) -> int:
        return self._size

    def __len__(self) -> int:
        return self._size

    @property
    def intercept_index(self) -> Optional[int]:
        from photon_ml_tpu.types import intercept_key

        idx = self.get_index(intercept_key())
        return idx if idx >= 0 else None

    def get_index(self, key: str) -> int:
        h = _fnv1a(key.encode())
        return self._parts[h % self.num_partitions].get(key, h)

    def get_indices(self, keys) -> np.ndarray:
        """Batch lookup (hashes vectorized; probes per key)."""
        hashes = _fnv1a_batch(keys)
        out = np.empty(len(keys), dtype=np.int64)
        for i, key in enumerate(keys):
            h = int(hashes[i])
            out[i] = self._parts[h % self.num_partitions].get(key, h)
        return out

    def get_feature_name(self, index: int) -> Optional[str]:
        if not (0 <= index < self._size):
            return None
        for part in self._parts:
            key = part.key_for_index(index)
            if key is not None:
                return key
        return None

    def __contains__(self, key: str) -> bool:
        return self.get_index(key) >= 0

    def keys(self):
        for index in range(self._size):
            yield self.get_feature_name(index)
