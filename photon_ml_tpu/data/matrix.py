"""Design matrices: the TPU-native replacement for Breeze sparse/dense feature vectors.

The reference streams per-sample Breeze vectors through aggregators
(ValueAndGradientAggregator.scala:137-169). On TPU the same computation is two ops:

  margins  = X @ eff_coef          (matvec   — MXU for dense, segment_sum for sparse)
  grad_vec = X.T @ (w * dz)        (rmatvec  — MXU / scatter-add)

Both layouts are jit-compatible pytrees with static shape metadata, so a whole
optimizer run compiles to one XLA program. The sparse layout is padded COO: TPUs want
static shapes, so nnz is padded to a bucket size with zero values (padding entries
point at row 0 / col 0 with value 0 and contribute nothing).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jnp.ndarray

# Column-reduction strategy for SparseDesignMatrix.rmatvec: "scatter" uses an
# unsorted scatter-add (fast on XLA:CPU), "sorted" a pre-sorted segment_sum
# (scatters serialize on TPU; the sorted segment reduction vectorizes).
# "auto" picks by backend at trace time — each backend compiles its own
# program anyway, so the choice is stable per process.
COL_REDUCE_MODE = "auto"  # "auto" | "sorted" | "scatter"

# Column-block width for SparseDesignMatrix.gram: the sparse Gram accumulates
# X^T D X one [N, GRAM_BLOCK_COLS] dense column slab at a time, so peak
# memory is O(nnz * block + N * block) instead of the O(N * D) full
# densification — the point of the sparse direct/IRLS path (Snap ML's
# sparse-aware kernel hierarchy, 1803.06333). The direct-solver regime is
# modest D (normal_equations.DIRECT_AUTO_K_MAX-ish), so one block is common.
GRAM_BLOCK_COLS = 256


def _use_sorted_col_reduce() -> bool:
    if COL_REDUCE_MODE == "sorted":
        return True
    if COL_REDUCE_MODE == "scatter":
        return False
    return jax.default_backend() not in ("cpu",)


def _mxu_dot(a: Array, b: Array, out_dtype) -> Array:
    """MXU-native mixed-precision product: when ``a`` is stored in bfloat16 the
    other operand is cast down so the MXU reads bf16 (half the HBM traffic of
    f32 — the usual bottleneck for GEMV-shaped GLM solves), while accumulation
    stays f32 via preferred_element_type. Full precision otherwise."""
    if a.dtype == jnp.bfloat16:
        acc = jnp.float32 if out_dtype in (jnp.bfloat16, jnp.float32) else out_dtype
        return jax.lax.dot(a, b.astype(jnp.bfloat16), preferred_element_type=acc).astype(
            out_dtype
        )
    return a @ b


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DenseDesignMatrix:
    """Dense [N, D] design matrix. matvec/rmatvec hit the MXU directly."""

    values: Array  # [N, D]

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def n_rows(self) -> int:
        return self.values.shape[0]

    @property
    def n_cols(self) -> int:
        return self.values.shape[1]

    def matvec(self, w: Array) -> Array:
        return _mxu_dot(self.values, w, w.dtype)

    def rmatvec(self, v: Array) -> Array:
        return _mxu_dot(self.values.T, v, v.dtype)

    def _sq(self, ref: Array) -> Array:
        # squares are computed at the reduction dtype: squaring in bf16 first
        # would double the rounding error of an already-rare (variance) path
        x = self.values
        return (x * x) if x.dtype != jnp.bfloat16 else (x.astype(ref.dtype) ** 2)

    def row_sq_dot(self, d: Array) -> Array:
        """sum_j x_ij^2 * d_j per row — Hessian-diagonal helper
        (HessianDiagonalAggregator semantics)."""
        return self._sq(d) @ d

    def rmatvec_sq(self, v: Array) -> Array:
        """sum_i x_ij^2 * v_i per column (Hessian diagonal principal term)."""
        return self._sq(v).T @ v

    def to_dense(self) -> Array:
        return self.values

    def take_rows(self, idx) -> "DenseDesignMatrix":
        """Host-side row subset (diagnostics / split helpers — not jit-traced)."""
        return DenseDesignMatrix(values=self.values[jnp.asarray(idx)])


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SparseDesignMatrix:
    """Padded-COO [N, D] design matrix for high-dimensional sparse features.

    rows/cols/vals are [nnz_padded]; padding entries have val == 0 so they are inert
    under segment_sum / scatter-add. Static n_rows/n_cols keep shapes compile-time.

    ``col_order``/``cols_sorted`` (optional, built by from_scipy) hold the
    column-sorting permutation: with them, rmatvec lowers to a SORTED
    segment_sum instead of an unsorted scatter-add — the scatter is the slow
    path on TPU (serialized updates), the sorted segment reduction vectorizes.
    The mesh-sharded constructor leaves them None: a global column sort would
    gather across the sharded nnz axis. ``rows_sorted`` marks row-major entry
    order (true for CSR-derived matrices) so matvec's segment_sum can also
    skip the unsorted path.
    """

    rows: Array  # [nnz] int32
    cols: Array  # [nnz] int32
    vals: Array  # [nnz] float
    n_rows: int = dataclasses.field(metadata=dict(static=True))
    n_cols: int = dataclasses.field(metadata=dict(static=True))
    col_order: Optional[Array] = None  # [nnz] int32 permutation sorting by column
    cols_sorted: Optional[Array] = None  # [nnz] int32 == cols[col_order]
    rows_sorted: bool = dataclasses.field(default=False, metadata=dict(static=True))

    @property
    def dtype(self):
        return self.vals.dtype

    def matvec(self, w: Array) -> Array:
        contrib = self.vals * jnp.take(w, self.cols, mode="clip")
        return jax.ops.segment_sum(
            contrib, self.rows, num_segments=self.n_rows,
            indices_are_sorted=self.rows_sorted,
        )

    def rmatvec(self, v: Array) -> Array:
        contrib = self.vals * jnp.take(v, self.rows, mode="clip")
        return self._col_reduce(contrib, v.dtype)

    def _col_reduce(self, contrib: Array, dtype) -> Array:
        if self.col_order is not None and _use_sorted_col_reduce():
            return jax.ops.segment_sum(
                jnp.take(contrib, self.col_order),
                self.cols_sorted,
                num_segments=self.n_cols,
                indices_are_sorted=True,
            )
        return jnp.zeros((self.n_cols,), dtype=dtype).at[self.cols].add(contrib)

    def row_sq_dot(self, d: Array) -> Array:
        contrib = self.vals * self.vals * jnp.take(d, self.cols, mode="clip")
        return jax.ops.segment_sum(
            contrib, self.rows, num_segments=self.n_rows,
            indices_are_sorted=self.rows_sorted,
        )

    def rmatvec_sq(self, v: Array) -> Array:
        contrib = self.vals * self.vals * jnp.take(v, self.rows, mode="clip")
        return self._col_reduce(contrib, v.dtype)

    def rmatmat(self, M: Array) -> Array:
        """X^T @ M for a dense [N, W] operand -> [D, W]: the multi-column form
        of rmatvec, sharing its column-reduction policy (sorted segment_sum
        when the layout carries col_order, scatter-add otherwise). The sparse
        Gram's building block."""
        contrib = self.vals[:, None] * jnp.take(M, self.rows, axis=0, mode="clip")
        if self.col_order is not None and _use_sorted_col_reduce():
            return jax.ops.segment_sum(
                jnp.take(contrib, self.col_order, axis=0),
                self.cols_sorted,
                num_segments=self.n_cols,
                indices_are_sorted=True,
            )
        return (
            jnp.zeros((self.n_cols, M.shape[1]), dtype=M.dtype)
            .at[self.cols]
            .add(contrib)
        )

    def densify_cols(self, start: int, width: int) -> Array:
        """Dense [N, width] slab of columns [start, start+width): out-of-block
        entries (and padding, val == 0) land masked at local column 0 with
        value 0, so the scatter stays shape-static and inert. ``start``/
        ``width`` are Python ints — the Gram loop unrolls at trace time."""
        local = self.cols - start
        in_block = (local >= 0) & (local < width)
        v = jnp.where(in_block, self.vals, jnp.zeros((), dtype=self.vals.dtype))
        out = jnp.zeros((self.n_rows, width), dtype=self.vals.dtype)
        return out.at[self.rows, jnp.where(in_block, local, 0)].add(v)

    def gram(self, d: Array) -> Array:
        """Weighted Gram matrix X^T diag(d) X -> [D, D] WITHOUT materializing
        the dense [N, D] design: accumulate one [N, GRAM_BLOCK_COLS] column
        slab at a time through rmatmat. O(nnz * D) work, O(nnz + N * block)
        peak memory — the sparse-aware Hessian for the direct/IRLS/NEWTON
        solvers (function/objective.hessian_matrix dispatches here)."""
        dt = jnp.result_type(self.vals.dtype, d.dtype)
        if self.n_cols == 0:
            return jnp.zeros((0, 0), dtype=dt)
        blocks = []
        for start in range(0, self.n_cols, GRAM_BLOCK_COLS):
            width = min(GRAM_BLOCK_COLS, self.n_cols - start)
            slab = self.densify_cols(start, width).astype(dt)
            blocks.append(self.rmatmat(d[:, None] * slab))
        return blocks[0] if len(blocks) == 1 else jnp.concatenate(blocks, axis=1)

    def to_dense(self) -> Array:
        out = jnp.zeros((self.n_rows, self.n_cols), dtype=self.vals.dtype)
        return out.at[self.rows, self.cols].add(self.vals)

    def take_rows(self, idx) -> "SparseDesignMatrix":
        """Host-side row subset (diagnostics / split helpers — not jit-traced).
        Output row k holds source row idx[k]'s entries; duplicate indices in
        ``idx`` duplicate the row (matching dense fancy indexing)."""
        idx = np.asarray(idx)
        rows = np.asarray(self.rows)
        cols = np.asarray(self.cols)
        vals = np.asarray(self.vals)
        real = vals != 0  # drop padding entries
        rows, cols, vals = rows[real], cols[real], vals[real]
        order = np.argsort(rows, kind="stable")
        sorted_rows = rows[order]
        starts = np.searchsorted(sorted_rows, idx, side="left")
        stops = np.searchsorted(sorted_rows, idx, side="right")
        counts = stops - starts
        total = int(counts.sum())
        # flatten [order[starts[k]:stops[k]] for k] without a Python loop
        out_rows = np.repeat(np.arange(len(idx), dtype=np.int32), counts)
        base = np.repeat(starts, counts)
        within = np.arange(total) - np.repeat(np.cumsum(counts) - counts, counts)
        sel = order[base + within]
        out_cols = cols[sel]
        col_order = cols_sorted = None
        if _use_sorted_col_reduce():
            co = np.argsort(out_cols, kind="stable").astype(np.int32)
            col_order = jnp.asarray(co)
            cols_sorted = jnp.asarray(out_cols[co])
        return SparseDesignMatrix(
            rows=jnp.asarray(out_rows),
            cols=jnp.asarray(out_cols),
            vals=jnp.asarray(vals[sel]),
            n_rows=int(len(idx)),
            n_cols=self.n_cols,
            col_order=col_order,
            cols_sorted=cols_sorted,
            rows_sorted=True,  # out_rows are emitted in nondecreasing order
        )

    @staticmethod
    def from_scipy(mat, dtype=jnp.float32, pad_nnz: int | None = None) -> "SparseDesignMatrix":
        coo = mat.tocoo()
        nnz = coo.nnz
        pad = pad_nnz if pad_nnz is not None else nnz
        if pad < nnz:
            raise ValueError(f"pad_nnz={pad} < nnz={nnz}")
        rows = np.zeros(pad, dtype=np.int32)
        cols = np.zeros(pad, dtype=np.int32)
        vals = np.zeros(pad, dtype=np.float64)
        rows[:nnz] = coo.row
        cols[:nnz] = coo.col
        vals[:nnz] = coo.data
        if nnz and pad > nnz:
            # pad with the LAST row id (vals stay 0, so still inert): row-0
            # padding would break the nondecreasing-rows invariant and silently
            # disable the sorted matvec fast path
            rows[nnz:] = rows[nnz - 1]
        # the sorted layout costs an O(nnz log nnz) host sort + two nnz-length
        # device arrays — only pay for it where the sorted path can run
        col_order = cols_sorted = None
        if _use_sorted_col_reduce():
            order = np.argsort(cols, kind="stable").astype(np.int32)
            col_order = jnp.asarray(order)
            cols_sorted = jnp.asarray(cols[order])
        return SparseDesignMatrix(
            rows=jnp.asarray(rows),
            cols=jnp.asarray(cols),
            vals=jnp.asarray(vals, dtype=dtype),
            n_rows=int(mat.shape[0]),
            n_cols=int(mat.shape[1]),
            col_order=col_order,
            cols_sorted=cols_sorted,
            rows_sorted=bool(np.all(np.diff(rows) >= 0)),
        )


DesignMatrix = Union[DenseDesignMatrix, SparseDesignMatrix]


def as_design_matrix_with_storage(X, storage_dtype, compute_dtype) -> "DesignMatrix":
    """as_design_matrix with an optional lower STORAGE dtype for dense inputs.

    Raw dense arrays cast at creation (only storage-dtype bytes are ever
    transferred/resident — the bf16 point); existing DenseDesignMatrix values
    are downcast; sparse inputs build once at the compute dtype (their values
    ride the elementwise VPU path, not the MXU)."""
    if storage_dtype is None:
        return as_design_matrix(X, dtype=compute_dtype)
    if isinstance(X, DenseDesignMatrix):
        return DenseDesignMatrix(values=X.values.astype(storage_dtype))
    if not isinstance(X, SparseDesignMatrix) and not hasattr(X, "tocoo"):
        return as_design_matrix(X, dtype=storage_dtype)  # raw dense array
    return as_design_matrix(X, dtype=compute_dtype)


def as_design_matrix(X, dtype=None) -> DesignMatrix:
    """Coerce numpy / jax arrays or scipy sparse matrices to a DesignMatrix."""
    if isinstance(X, (DenseDesignMatrix, SparseDesignMatrix)):
        return X
    if hasattr(X, "tocoo"):  # scipy sparse
        return SparseDesignMatrix.from_scipy(X, dtype=dtype or jnp.float32)
    arr = jnp.asarray(X, dtype=dtype) if dtype is not None else jnp.asarray(X)
    return DenseDesignMatrix(values=arr)
