"""Feature-space projectors for random-effect coordinates.

TPU-native re-design of photon-api projector/ (ProjectorType.scala:30,
ProjectionMatrix.scala:32-127, ProjectionMatrixBroadcast.scala,
IndexMapProjectorRDD.scala:36-274, IdentityProjector.scala):

- INDEX_MAP_PROJECTION — per-entity exact remap to the entity's observed feature
  set. Already the *native* representation of ``build_random_effect_dataset``
  (data/random_effect.py builds the [E, K] observed-column gather table); the
  projector here is just the dispatch marker.
- RANDOM_PROJECTION(dim) — one shared Gaussian Johnson–Lindenstrauss matrix for
  all entities. On TPU this becomes a single dense [d, k] matmul at ingest (an
  MXU-friendly op) instead of the reference's broadcast matrix multiplied inside
  every executor; the projected dataset then flows through the SAME bucketed
  builder, where every entity observes all k projected columns.
- IDENTITY_PROJECTION — no-op (entities keep global feature ids).

A RandomProjector optionally carries the coordinate's NormalizationContext: the
affine transform x' = (x-shift)*factor folds into the projection matrix
(IndexMapProjectorRDD.projectNormalizationRDD semantics), so inputs stay sparse
and training/scoring/export all see one consistent space. Models trained under
RANDOM_PROJECTION live in (normalized-)projected space; scoring uses the
projected per-sample view directly (margins are invariant), while model *export*
back-projects coefficients via ``P @ w`` and then un-does the normalization with
``NormalizationContext.model_to_original_space``
(RandomEffectModelInProjectedSpace.scala:151 semantics: models are projected back
for anything that needs name-space coefficients).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.normalization import NormalizationContext


class ProjectorType(str, enum.Enum):
    """projector/ProjectorType.scala:30 — INDEX_MAP / RANDOM / IDENTITY."""

    INDEX_MAP_PROJECTION = "INDEX_MAP_PROJECTION"
    RANDOM_PROJECTION = "RANDOM_PROJECTION"
    IDENTITY_PROJECTION = "IDENTITY_PROJECTION"


@dataclasses.dataclass(frozen=True)
class ProjectorConfig:
    projector_type: ProjectorType = ProjectorType.INDEX_MAP_PROJECTION
    projected_dim: Optional[int] = None  # required for RANDOM_PROJECTION
    seed: int = 0
    # intercept column of the shard, exempted from projection (pass-through);
    # falls back to the normalization context's intercept when unset
    intercept_index: Optional[int] = None

    def __post_init__(self):
        if (
            self.projector_type is ProjectorType.RANDOM_PROJECTION
            and not self.projected_dim
        ):
            raise ValueError("RANDOM_PROJECTION requires projected_dim > 0")


def build_gaussian_projection_matrix(
    original_dim: int, projected_dim: int, seed: int = 0
) -> np.ndarray:
    """[d, k] i.i.d. N(0, 1/k) Johnson–Lindenstrauss matrix
    (ProjectionMatrix.buildGaussianRandomProjectionMatrix:99-126 — Gaussian
    entries scaled so projected inner products are unbiased)."""
    rng = np.random.default_rng(seed)
    return rng.normal(size=(original_dim, projected_dim)) / np.sqrt(projected_dim)


@dataclasses.dataclass(frozen=True)
class RandomProjector:
    """Shared Gaussian projection for one random-effect coordinate.

    ``matrix`` maps original (non-intercept) features into projected space; the
    intercept column, when present, passes through untouched as the LAST
    projected column (the reference keeps the intercept out of the projection —
    ProjectionMatrixBroadcast builds the matrix over non-intercept features).

    ``normalization``, when set, is folded into every ``project_features`` call
    and un-done by ``project_coefficients_back`` — the single source of truth for
    the normalized-projected space the models live in.
    """

    matrix: np.ndarray  # [d, k]
    intercept_index: Optional[int] = None
    normalization: Optional[NormalizationContext] = None

    def __post_init__(self):
        norm = self.normalization
        if norm is not None and norm.is_identity:
            object.__setattr__(self, "normalization", None)

    @property
    def original_dim(self) -> int:
        return self.matrix.shape[0]

    @property
    def projected_dim(self) -> int:
        # +1 for the pass-through intercept slot
        return self.matrix.shape[1] + (1 if self.intercept_index is not None else 0)

    def _feature_mask(self) -> np.ndarray:
        mask = np.ones(self.original_dim, dtype=bool)
        if self.intercept_index is not None:
            mask[self.intercept_index] = False
        return mask

    def project_features(self, X: sp.spmatrix) -> sp.csr_matrix:
        """[n, d] sparse → [n, k(+1)] projected design matrix (CSR so it feeds
        straight into build_random_effect_dataset).

        Any carried normalization x' = (x-shift)*factor folds into the matmul:
        (x' @ P) = (x*factor) @ P - (shift*factor) @ P, so X stays sparse. The
        intercept column must carry factor 1 / shift 0 (NormalizationContext
        invariant) and passes through untouched.
        """
        X = X.tocsr()
        if X.shape[1] != self.original_dim:
            raise ValueError(
                f"X has {X.shape[1]} columns, projector expects {self.original_dim}"
            )
        mask = self._feature_mask()
        factors = None if self.normalization is None else self.normalization.factors
        shifts = None if self.normalization is None else self.normalization.shifts
        P = self.matrix[mask]
        if factors is not None:
            P = P * np.asarray(factors)[mask][:, None]
        body = np.asarray(X[:, mask] @ P)
        if shifts is not None:
            eff_shift = np.asarray(shifts)
            if factors is not None:
                eff_shift = eff_shift * np.asarray(factors)
            body = body - (eff_shift[mask] @ self.matrix[mask])[None, :]
        if self.intercept_index is not None:
            icept = np.asarray(X[:, [self.intercept_index]].todense())
            dense = np.concatenate([body, icept], axis=1)
        else:
            dense = body
        return sp.csr_matrix(dense)

    def project_coefficients_back(self, w_projected: np.ndarray) -> np.ndarray:
        """Projected-space coefficients → original name-space coefficients.

        [kp] → [d], or batched [E, kp] → [E, d]. Two steps: (1) P @ w lands in
        the (possibly normalized) original feature space — margin-invariant:
        x_proj · w = (x P) · w = x · (P w); (2) any carried normalization is
        un-done via model_to_original_space, so the result always scores raw
        features correctly.
        """
        w = np.atleast_2d(np.asarray(w_projected))  # [E, kp]
        if self.intercept_index is not None:
            body, icept = w[:, :-1], w[:, -1]
        else:
            body, icept = w, None
        mask = self._feature_mask()
        out = np.zeros((w.shape[0], self.original_dim), dtype=w.dtype)
        out[:, mask] = body @ self.matrix[mask].T
        if icept is not None:
            out[:, self.intercept_index] = icept
        if self.normalization is not None:
            # batched model_to_original_space: w_orig = factor*w;
            # w_orig[icept] -= w_orig . shift (normalization.py:96-104)
            norm = self.normalization
            if norm.factors is not None:
                out = out * np.asarray(norm.factors)[None, :]
            if norm.shifts is not None:
                out[:, norm.intercept_index] -= out @ np.asarray(norm.shifts)
        return out if np.ndim(w_projected) == 2 else out[0]


def make_projector(
    config: ProjectorConfig,
    original_dim: int,
    intercept_index: Optional[int] = None,
    normalization: Optional[NormalizationContext] = None,
) -> Optional[RandomProjector]:
    """ProjectorType dispatch: only RANDOM_PROJECTION materializes an object;
    INDEX_MAP is native to the dataset builder and IDENTITY is a no-op."""
    if config.projector_type is ProjectorType.RANDOM_PROJECTION:
        icept = config.intercept_index if config.intercept_index is not None else intercept_index
        if icept is None and normalization is not None:
            icept = normalization.intercept_index
        return RandomProjector(
            matrix=build_gaussian_projection_matrix(
                original_dim, int(config.projected_dim), config.seed
            ),
            intercept_index=icept,
            normalization=normalization,
        )
    return None
