"""Host-side data readers: Avro training records and LIBSVM text -> array batches.

Replaces the Spark ingest path (photon-client data/avro/AvroDataReader.scala:54-490,
io/deprecated/GLMSuite + LibSVMInputDataFormat). TPU-first: ingest happens once on
the host into columnar numpy (then device arrays); there is no lazy RDD layer.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data import avro_io
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.types import intercept_key


@dataclasses.dataclass
class RawDataset:
    """Columnar host dataset for one feature shard + response columns.

    ``X`` is scipy CSR (sparse ingest); id_columns carries entity-id strings per
    sample (the GameDatum idTagToValueMap, reference data/GameDatum.scala:1-74).
    """

    X: sp.csr_matrix
    labels: np.ndarray
    offsets: np.ndarray
    weights: np.ndarray
    uids: Optional[np.ndarray] = None
    id_columns: Optional[dict[str, np.ndarray]] = None

    @property
    def n(self) -> int:
        return self.labels.shape[0]

    @property
    def dim(self) -> int:
        return self.X.shape[1]


def _records_to_dataset(
    records,
    index_map: Optional[IndexMap],
    add_intercept: bool,
    id_tags: Sequence[str] = (),
) -> tuple[RawDataset, IndexMap]:
    labels, weights, offsets, uids = [], [], [], []
    rows, cols, vals = [], [], []
    id_cols: dict[str, list] = {tag: [] for tag in id_tags}
    all_keys: list[str] = []

    cached = list(records)
    if index_map is None:
        for rec in cached:
            for f in rec["features"]:
                all_keys.append(feature_key(f["name"], f["term"]))
        index_map = IndexMap.build(all_keys, add_intercept=add_intercept)

    icpt = index_map.intercept_index
    for i, rec in enumerate(cached):
        labels.append(rec.get("label", rec.get("response", 0.0)))
        w = rec.get("weight")
        weights.append(1.0 if w is None else w)
        o = rec.get("offset")
        offsets.append(0.0 if o is None else o)
        uids.append(rec.get("uid") or str(i))
        meta = rec.get("metadataMap") or {}
        for tag in id_tags:
            if tag not in meta:
                raise ValueError(f"Sample {i} missing id tag {tag!r} in metadataMap")
            id_cols[tag].append(meta[tag])
        has_explicit_intercept = False
        for f in rec["features"]:
            j = index_map.get_index(feature_key(f["name"], f["term"]))
            if j >= 0:
                if j == icpt:
                    has_explicit_intercept = True
                rows.append(i)
                cols.append(j)
                vals.append(f["value"])
        if icpt is not None and not has_explicit_intercept:
            rows.append(i)
            cols.append(icpt)
            vals.append(1.0)

    n = len(labels)
    X = sp.csr_matrix(
        (np.asarray(vals, dtype=np.float64), (rows, cols)), shape=(n, index_map.size)
    )
    ds = RawDataset(
        X=X,
        labels=np.asarray(labels, dtype=np.float64),
        offsets=np.asarray(offsets, dtype=np.float64),
        weights=np.asarray(weights, dtype=np.float64),
        uids=np.asarray(uids, dtype=object),
        id_columns={k: np.asarray(v, dtype=object) for k, v in id_cols.items()} or None,
    )
    return ds, index_map


def read_avro(
    path: str,
    index_map: Optional[IndexMap] = None,
    add_intercept: bool = True,
    id_tags: Sequence[str] = (),
) -> tuple[RawDataset, IndexMap]:
    """Read TrainingExampleAvro / ResponsePredictionAvro files or directories."""
    return _records_to_dataset(
        avro_io.read_container_dir(path), index_map, add_intercept, id_tags
    )


def write_training_avro(path: str, dataset_records) -> None:
    """Write TrainingExampleAvro records (AvroDataWriter equivalent)."""
    avro_io.write_container(path, avro_io.TRAINING_EXAMPLE_SCHEMA, dataset_records)


def read_libsvm(
    path: str,
    index_map: Optional[IndexMap] = None,
    add_intercept: bool = True,
) -> tuple[RawDataset, IndexMap]:
    """Read LIBSVM text (the a1a tutorial format, README.md:240-305).

    Feature j becomes key ("j", ""); labels <= 0 map to 0.0 (binary convention).
    """
    labels = []
    feats: list[list[tuple[str, float]]] = []
    with open(path) as f:
        for line in f:
            parts = line.strip().split()
            if not parts:
                continue
            y = float(parts[0])
            labels.append(1.0 if y > 0 else 0.0)
            row = []
            for tok in parts[1:]:
                idx, val = tok.split(":")
                row.append((feature_key(idx), float(val)))
            feats.append(row)

    if index_map is None:
        index_map = IndexMap.build(
            (k for row in feats for k, _ in row), add_intercept=add_intercept
        )
    icpt = index_map.intercept_index
    rows, cols, vals = [], [], []
    for i, row in enumerate(feats):
        has_explicit_intercept = False
        for k, v in row:
            j = index_map.get_index(k)
            if j >= 0:
                if j == icpt:
                    has_explicit_intercept = True
                rows.append(i)
                cols.append(j)
                vals.append(v)
        if icpt is not None and not has_explicit_intercept:
            rows.append(i)
            cols.append(icpt)
            vals.append(1.0)
    n = len(labels)
    X = sp.csr_matrix((np.asarray(vals), (rows, cols)), shape=(n, index_map.size))
    ds = RawDataset(
        X=X,
        labels=np.asarray(labels, dtype=np.float64),
        offsets=np.zeros(n),
        weights=np.ones(n),
        uids=np.asarray([str(i) for i in range(n)], dtype=object),
    )
    return ds, index_map
