"""Host-side data readers: Avro training records and LIBSVM text -> array batches.

Replaces the Spark ingest path (photon-client data/avro/AvroDataReader.scala:54-490,
io/deprecated/GLMSuite + LibSVMInputDataFormat). TPU-first: ingest happens once on
the host into columnar numpy (then device arrays); there is no lazy RDD layer.
"""

from __future__ import annotations

import dataclasses
import io
import os
from typing import Optional, Sequence

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.data import avro_io
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.types import DELIMITER, intercept_key


@dataclasses.dataclass
class RawDataset:
    """Columnar host dataset for one feature shard + response columns.

    ``X`` is scipy CSR (sparse ingest); id_columns carries entity-id strings per
    sample (the GameDatum idTagToValueMap, reference data/GameDatum.scala:1-74).
    """

    X: sp.csr_matrix
    labels: np.ndarray
    offsets: np.ndarray
    weights: np.ndarray
    uids: Optional[np.ndarray] = None
    id_columns: Optional[dict[str, np.ndarray]] = None

    @property
    def n(self) -> int:
        return self.labels.shape[0]

    @property
    def dim(self) -> int:
        return self.X.shape[1]


def _id_tag_value(rec: dict, tag: str, i: int, meta_field: str = "metadataMap") -> str:
    """Entity-id lookup order of the reference (GameConverters.scala:152-166):
    a top-level record field named ``tag`` wins, then ``metadataMap[tag]``;
    values are stringified (random-effect ids are strings by contract)."""
    v = rec.get(tag)
    if v is None:
        v = (rec.get(meta_field) or {}).get(tag)
    if v is None:
        raise ValueError(
            f"Sample {i}: cannot find id in either record field {tag!r} "
            f"or in metadataMap with key {tag!r}"
        )
    return str(v)


def _resolve_columns(columns) -> dict:
    """Accepts None, an InputColumnsNames, or a plain override dict and returns
    the concrete field-name map (reference data/InputColumnsNames.scala:106 —
    deployments rename response/offset/weight/uid/metadataMap record fields).
    Unknown override keys fail fast: a typo'd key would otherwise silently
    leave the default field name in place (e.g. every label read as 0.0)."""
    from photon_ml_tpu.types import InputColumnsNames

    if columns is None:
        return InputColumnsNames().all()
    if isinstance(columns, InputColumnsNames):
        return columns.all()
    overrides = dict(columns)
    known = InputColumnsNames().all().keys()
    unknown = set(overrides) - set(known)
    if unknown:
        raise ValueError(
            f"Unknown input column key(s) {sorted(unknown)}; expected a subset "
            f"of {sorted(known)}"
        )
    return InputColumnsNames(overrides).all()


def _label_of(rec: dict, response_f: str):
    """Label lookup shared by both read paths: "label" is
    TrainingExampleAvro's field, "response" ResponsePredictionAvro's; a
    RENAMED response column consults only its own name (AvroDataReader
    schema-inference precedence). Returns None when the record has neither."""
    lab = rec.get("label") if response_f == "response" else None
    return rec.get(response_f) if lab is None else lab


def _records_to_dataset(
    records,
    index_map: Optional[IndexMap],
    add_intercept: bool,
    id_tags: Sequence[str] = (),
    columns=None,
) -> tuple[RawDataset, IndexMap]:
    labels, weights, offsets, uids = [], [], [], []
    rows, cols, vals = [], [], []
    id_cols: dict[str, list] = {tag: [] for tag in id_tags}
    all_keys: list[str] = []
    cols_map = _resolve_columns(columns)
    response_f, offset_f = cols_map["response"], cols_map["offset"]
    weight_f, uid_f, meta_f = cols_map["weight"], cols_map["uid"], cols_map["metadataMap"]

    cached = list(records)
    if index_map is None:
        for rec in cached:
            for f in rec["features"]:
                all_keys.append(feature_key(f["name"], f["term"]))
        index_map = IndexMap.build(all_keys, add_intercept=add_intercept)

    icpt = index_map.intercept_index
    for i, rec in enumerate(cached):
        lab = _label_of(rec, response_f)
        labels.append(0.0 if lab is None else lab)
        w = rec.get(weight_f)
        weights.append(1.0 if w is None else w)
        o = rec.get(offset_f)
        offsets.append(0.0 if o is None else o)
        uids.append(rec.get(uid_f) or str(i))
        for tag in id_tags:
            id_cols[tag].append(_id_tag_value(rec, tag, i, meta_f))
        has_explicit_intercept = False
        for f in rec["features"]:
            j = index_map.get_index(feature_key(f["name"], f["term"]))
            if j >= 0:
                if j == icpt:
                    has_explicit_intercept = True
                rows.append(i)
                cols.append(j)
                vals.append(f["value"])
        if icpt is not None and not has_explicit_intercept:
            rows.append(i)
            cols.append(icpt)
            vals.append(1.0)

    n = len(labels)
    X = sp.csr_matrix(
        (np.asarray(vals, dtype=np.float64), (rows, cols)), shape=(n, index_map.size)
    )
    ds = RawDataset(
        X=X,
        labels=np.asarray(labels, dtype=np.float64),
        offsets=np.asarray(offsets, dtype=np.float64),
        weights=np.asarray(weights, dtype=np.float64),
        uids=np.asarray(uids, dtype=object),
        id_columns={k: np.asarray(v, dtype=object) for k, v in id_cols.items()} or None,
    )
    return ds, index_map


def read_avro(
    path: str,
    index_map: Optional[IndexMap] = None,
    add_intercept: bool = True,
    id_tags: Sequence[str] = (),
    columns=None,
) -> tuple[RawDataset, IndexMap]:
    """Read TrainingExampleAvro / ResponsePredictionAvro files or directories.

    ``columns`` renames the response/offset/weight/uid/metadataMap record
    fields (an InputColumnsNames or a plain override dict — the reference's
    input-columns-names driver parameter, InputColumnsNames.scala:106)."""
    return _records_to_dataset(
        avro_io.read_container_dir(path), index_map, add_intercept, id_tags,
        columns=columns,
    )


def write_training_avro(path: str, dataset_records) -> None:
    """Write TrainingExampleAvro records (AvroDataWriter equivalent)."""
    avro_io.write_container(path, avro_io.TRAINING_EXAMPLE_SCHEMA, dataset_records)


def read_merged_avro(
    path: str,
    shard_configs,
    index_maps: Optional[dict] = None,
    id_tags: Sequence[str] = (),
    use_native: bool = True,
    columns=None,
    ingest_workers: Optional[int] = None,
    ingest_window: Optional[int] = None,
):
    """Avro records -> one GameInput with per-SHARD feature matrices.

    The reference's AvroDataReader.readMerged (photon-client
    data/avro/AvroDataReader.scala:85-221): each feature SHARD is the union of
    one or more feature BAGS (record fields holding FeatureAvro arrays); when
    the same (name, term) appears in several bags of one sample, the first
    occurrence's VALUE wins; an intercept column is added when the shard config
    asks for one. Index positions come from the shard's IndexMap (sorted-key
    order when built here). Entity ids for random effects come from
    ``metadataMap`` (GameConverters' id-tag extraction); response/offset/weight
    from the standard TrainingExampleAvro fields.

    shard_configs: {shard_id: FeatureShardConfiguration}. index_maps: existing
    {shard_id: IndexMap} (e.g. from the feature-indexing driver); missing maps
    are built from the data (AvroDataReader builds index maps if absent).
    Returns (GameInput, {shard_id: IndexMap}, uids ndarray).

    ``ingest_workers`` selects the ingest engine: None/0 = auto (min(cores,
    8)), 1 = the sequential legacy path, N >= 2 = the parallel streaming
    pipeline (data/pipeline.py — framing+inflate+block decode fanned over N
    threads, bounded in-flight window ``ingest_window``, manifest-order
    assembly). Results are BITWISE identical across worker counts; the
    parallel paths additionally bound peak memory at O(window) raw payloads
    instead of materializing every decoded block.
    """
    from photon_ml_tpu.data import pipeline as _pipeline
    from photon_ml_tpu.data.game_data import GameInput

    workers = _pipeline.resolve_ingest_workers(ingest_workers)
    cols_map = _resolve_columns(columns)
    response_f, offset_f = cols_map["response"], cols_map["offset"]
    weight_f, uid_f, meta_f = cols_map["weight"], cols_map["uid"], cols_map["metadataMap"]
    if columns is not None and cols_map != _resolve_columns(None):
        # the C++ block decoder parses the standard TrainingExampleAvro field
        # names; renamed columns take the pure-Python record path
        use_native = False

    if use_native:
        native = (
            _read_merged_native_parallel(
                path, shard_configs, index_maps, id_tags, workers, ingest_window
            )
            if workers >= 2
            else _read_merged_native(path, shard_configs, index_maps, id_tags)
        )
        if native is not None:
            return native

    if workers >= 2:
        records, fallback_uids = _read_records_parallel(path, workers, ingest_window)
    else:
        records = []
        fallback_uids = []
        for file_path in avro_io.container_files(path):
            base = os.path.basename(file_path)
            for row, rec in enumerate(avro_io.read_container(file_path)):
                records.append(rec)
                # synthetic uids are FILE-anchored, not positional: a positional
                # fallback would depend on which slice of the part files a reader
                # saw (multi-process scoring splits them round-robin) and collide
                # across processes
                fallback_uids.append(f"{base}#{row}")
    n = len(records)
    index_maps = dict(index_maps or {})

    # build missing index maps: first-occurrence order over the shard's bags
    for shard_id, cfg in shard_configs.items():
        if shard_id in index_maps:
            continue
        keys: list[str] = []
        for rec in records:
            for bag in cfg.feature_bags:
                for f in rec.get(bag) or ():
                    keys.append(feature_key(f["name"], f["term"]))
        index_maps[shard_id] = IndexMap.build(keys, add_intercept=cfg.has_intercept)

    labels = np.zeros(n)
    offsets = np.zeros(n)
    weights = np.ones(n)
    uids = np.empty(n, dtype=object)
    has_labels = False
    id_cols: dict[str, list] = {tag: [] for tag in id_tags}
    shard_rows: dict[str, list] = {s: [] for s in shard_configs}
    shard_cols: dict[str, list] = {s: [] for s in shard_configs}
    shard_vals: dict[str, list] = {s: [] for s in shard_configs}

    for i, rec in enumerate(records):
        label = _label_of(rec, response_f)
        if label is not None:
            labels[i] = label
            has_labels = True
        if rec.get(offset_f) is not None:
            offsets[i] = rec[offset_f]
        if rec.get(weight_f) is not None:
            weights[i] = rec[weight_f]
        uids[i] = rec.get(uid_f) or fallback_uids[i]
        for tag in id_tags:
            id_cols[tag].append(_id_tag_value(rec, tag, i, meta_f))
        for shard_id, cfg in shard_configs.items():
            imap = index_maps[shard_id]
            icpt = imap.intercept_index
            seen: set[int] = set()
            for bag in cfg.feature_bags:
                for f in rec.get(bag) or ():
                    j = imap.get_index(feature_key(f["name"], f["term"]))
                    if j >= 0 and j not in seen:  # first occurrence wins
                        seen.add(j)
                        shard_rows[shard_id].append(i)
                        shard_cols[shard_id].append(j)
                        shard_vals[shard_id].append(f["value"])
            if icpt is not None and icpt not in seen:
                shard_rows[shard_id].append(i)
                shard_cols[shard_id].append(icpt)
                shard_vals[shard_id].append(1.0)

    features = {
        s: sp.csr_matrix(
            (np.asarray(shard_vals[s], dtype=np.float64), (shard_rows[s], shard_cols[s])),
            shape=(n, index_maps[s].size),
        )
        for s in shard_configs
    }
    game_input = GameInput(
        features=features,
        labels=labels if has_labels else None,
        offsets=offsets,
        weights=weights,
        id_columns={k: np.asarray(v, dtype=object) for k, v in id_cols.items()},
    )
    return game_input, index_maps, uids


def read_libsvm(
    path: str,
    index_map: Optional[IndexMap] = None,
    add_intercept: bool = True,
) -> tuple[RawDataset, IndexMap]:
    """Read LIBSVM text (the a1a tutorial format, README.md:240-305).

    Feature j becomes key ("j", ""); labels <= 0 map to 0.0 (binary convention).
    """
    labels = []
    feats: list[list[tuple[str, float]]] = []
    with open(path) as f:
        for line in f:
            parts = line.strip().split()
            if not parts:
                continue
            y = float(parts[0])
            labels.append(1.0 if y > 0 else 0.0)
            row = []
            for tok in parts[1:]:
                idx, val = tok.split(":")
                row.append((feature_key(idx), float(val)))
            feats.append(row)

    if index_map is None:
        index_map = IndexMap.build(
            (k for row in feats for k, _ in row), add_intercept=add_intercept
        )
    icpt = index_map.intercept_index
    rows, cols, vals = [], [], []
    for i, row in enumerate(feats):
        has_explicit_intercept = False
        for k, v in row:
            j = index_map.get_index(k)
            if j >= 0:
                if j == icpt:
                    has_explicit_intercept = True
                rows.append(i)
                cols.append(j)
                vals.append(v)
        if icpt is not None and not has_explicit_intercept:
            rows.append(i)
            cols.append(icpt)
            vals.append(1.0)
    n = len(labels)
    X = sp.csr_matrix((np.asarray(vals), (rows, cols)), shape=(n, index_map.size))
    ds = RawDataset(
        X=X,
        labels=np.asarray(labels, dtype=np.float64),
        offsets=np.zeros(n),
        weights=np.ones(n),
        uids=np.asarray([str(i) for i in range(n)], dtype=object),
    )
    return ds, index_map


def _read_merged_native(path, shard_configs, index_maps, id_tags):
    """Native columnar fast path for read_merged_avro: container framing +
    inflate in Python, record decoding in C++ (data/native_avro.py), shard
    assembly vectorized. Returns None when the decoder or schema is
    unsupported — callers fall back to the record-at-a-time Python path, which
    this function matches result-for-result (tests assert equality)."""
    from photon_ml_tpu.data import native_avro
    from photon_ml_tpu.data.game_data import GameInput

    if not native_avro.available():
        return None
    files = avro_io.container_files(path)

    # ---- pass 1: decode every block, keep columnar views -----------------------
    decoded = []  # (block, row_base, positions dict, bag positions dict, ...)
    n_total = 0
    for file_path in files:
        file_base = os.path.basename(file_path)
        file_row = 0
        for schema_json, payload, n_records in avro_io.iter_raw_blocks(file_path):
            fields = schema_json.get("fields", [])
            ftypes = native_avro.field_types_for_schema(fields)
            if ftypes is None:
                return None  # unsupported layout -> pure-Python path
            pos = {f["name"]: i for i, f in enumerate(fields)}
            label_pos = pos.get("label", pos.get("response"))
            if label_pos is None:
                return None
            # reference id lookup is record-field-first (GameConverters.scala:
            # 152-166); the columnar fast path only implements the common
            # metadataMap case — top-level id fields take the Python path
            if id_tags and (
                any(tag in pos for tag in id_tags) or "metadataMap" not in pos
            ):
                return None
            bag_pos = {
                bag: pos[bag]
                for cfg in shard_configs.values()
                for bag in cfg.feature_bags
                if bag in pos
            }
            try:
                block = native_avro.decode_block(payload, n_records, ftypes)
            except ValueError:
                return None  # malformed for the fast path; let Python report it
            decoded.append(
                (block, n_total, pos, bag_pos, ftypes, label_pos, file_base, file_row)
            )
            n_total += n_records
            file_row += n_records

    labels = np.zeros(n_total)
    offsets = np.zeros(n_total)
    weights = np.ones(n_total)
    uids = np.empty(n_total, dtype=object)
    has_labels = False
    id_cols: dict[str, list] = {tag: [None] * n_total for tag in id_tags}
    # per shard: entry arrays accumulated across blocks, in bag order per block
    ent_rows: dict[str, list] = {s: [] for s in shard_configs}
    ent_keys: dict[str, list] = {s: [] for s in shard_configs}
    ent_vals: dict[str, list] = {s: [] for s in shard_configs}

    DOUBLES = (native_avro.F_DOUBLE, native_avro.F_NULLABLE_DOUBLE)
    for block, base, pos, bag_pos, ftypes, label_pos, file_base, file_row in decoded:
        # nullable doubles decode nulls as NaN; match the Python path's
        # defaults (label 0, offset 0, weight 1) and its has_labels semantics
        # (true only when some label is present)
        lab = block.doubles(label_pos)
        if ftypes[label_pos] == native_avro.F_NULLABLE_DOUBLE:
            if np.any(~np.isnan(lab)):
                has_labels = True
            lab = np.where(np.isnan(lab), 0.0, lab)
        elif len(lab):
            has_labels = True
        labels[base : base + len(lab)] = lab
        if "offset" in pos and ftypes[pos["offset"]] in DOUBLES:
            off = block.doubles(pos["offset"])
            offsets[base : base + len(off)] = np.where(np.isnan(off), 0.0, off)
        if "weight" in pos and ftypes[pos["weight"]] in DOUBLES:
            w = block.doubles(pos["weight"])
            weights[base : base + len(w)] = np.where(np.isnan(w), 1.0, w)
        # synthetic uids are FILE-anchored (<part-file>#<row-in-file>), like
        # the Python path: a positional fallback would depend on which slice
        # of the part files this reader saw and collide across the processes
        # of a multi-process scoring run
        if "uid" in pos and ftypes[pos["uid"]] == native_avro.F_NULLABLE_STRING:
            offs, lens = block.strings(pos["uid"])
            vals = block.strings_at(offs, lens)
            for i, v in enumerate(vals):
                uids[base + i] = v if v else f"{file_base}#{file_row + i}"
        else:
            for i in range(block.count(label_pos)):
                uids[base + i] = f"{file_base}#{file_row + i}"
        if id_tags:
            rows, ko, kl, vo, vl = block.map_entries(pos["metadataMap"])
            keys = block.strings_at(ko, kl)
            vals = block.strings_at(vo, vl)
            for r, k, v in zip(rows.tolist(), keys, vals):
                if k in id_cols:
                    id_cols[k][base + r] = v
        for shard_id, cfg in shard_configs.items():
            for bag in cfg.feature_bags:
                if bag not in bag_pos:
                    continue
                rows, no, nl, to, tl, vals = block.features(bag_pos[bag])
                if not len(rows):
                    continue
                payload = block._payload
                keys = [
                    payload[o : o + l].decode() + DELIMITER + payload[o2 : o2 + l2].decode()
                    for o, l, o2, l2 in zip(
                        no.tolist(), nl.tolist(), to.tolist(), tl.tolist()
                    )
                ]
                ent_rows[shard_id].append(rows + base)
                ent_keys[shard_id].append(keys)
                ent_vals[shard_id].append(vals)

    for tag in id_tags:
        missing = [i for i, v in enumerate(id_cols[tag]) if v is None]
        if missing:
            raise ValueError(
                f"Sample {missing[0]}: cannot find id in either record field "
                f"{tag!r} or in metadataMap with key {tag!r}"
            )

    # ---- index maps (built from data when absent) ------------------------------
    index_maps = dict(index_maps or {})
    for shard_id, cfg in shard_configs.items():
        if shard_id not in index_maps:
            all_keys: list[str] = []
            for chunk in ent_keys[shard_id]:
                all_keys.extend(chunk)
            index_maps[shard_id] = IndexMap.build(all_keys, add_intercept=cfg.has_intercept)

    # ---- shard assembly: map keys -> cols, dedupe first occurrence, intercept --
    features = {}
    for shard_id, cfg in shard_configs.items():
        imap = index_maps[shard_id]
        if ent_rows[shard_id]:
            rows = np.concatenate(ent_rows[shard_id])
            vals = np.concatenate(ent_vals[shard_id])
            get_index = imap.get_index
            cols = np.fromiter(
                (get_index(k) for chunk in ent_keys[shard_id] for k in chunk),
                dtype=np.int64,
                count=len(rows),
            )
        else:
            rows = np.zeros(0, dtype=np.int64)
            cols = np.zeros(0, dtype=np.int64)
            vals = np.zeros(0, dtype=np.float64)
        features[shard_id] = _assemble_shard_matrix(imap, rows, cols, vals, n_total)

    for block, *_ in decoded:
        block.close()

    game_input = GameInput(
        features=features,
        labels=labels if has_labels else None,
        offsets=offsets,
        weights=weights,
        id_columns={k: np.asarray(v, dtype=object) for k, v in id_cols.items()},
    )
    return game_input, index_maps, uids


# --------------------------------------------------- parallel ingest pipeline
# The streaming counterpart of _read_merged_native (workers >= 2): container
# framing stays sequential (data/pipeline.iter_file_blocks assigns every
# block's global row base up front), inflate + native decode + per-block
# columnar extraction fan out over a bounded thread pool, and assembly
# consumes results in manifest order — so the output is BITWISE identical to
# the sequential path while peak memory holds O(window) raw payloads instead
# of every decoded block at once.


def _assemble_shard_matrix(imap, rows, cols, vals, n_total):
    """Unseen-key drop, first-occurrence dedupe, implicit intercept, csr —
    the shard-assembly tail shared by the sequential and parallel native
    paths (cols may contain -1 for keys outside the index map)."""
    keep = cols >= 0
    rows, cols, vals = rows[keep], cols[keep], vals[keep]
    # first occurrence wins for duplicate (row, col) — np.unique returns
    # the smallest input index per unique value
    _, first = np.unique(rows * np.int64(imap.size) + cols, return_index=True)
    rows, cols, vals = rows[first], cols[first], vals[first]
    icpt = imap.intercept_index
    if icpt is not None:
        has_icpt = np.zeros(n_total, dtype=bool)
        has_icpt[rows[cols == icpt]] = True
        add = np.flatnonzero(~has_icpt)
        rows = np.concatenate([rows, add])
        cols = np.concatenate([cols, np.full(len(add), icpt, dtype=np.int64)])
        vals = np.concatenate([vals, np.ones(len(add))])
    return sp.csr_matrix((vals, (rows, cols)), shape=(n_total, imap.size))


class _UnsupportedNativeLayout(Exception):
    """Schema outside the native decoder's supported set: the whole read
    falls back to the pure-Python record path (sequential-path behavior)."""


class _NativeBlockError(Exception):
    """Native decode rejected a block (malformed for the fast path): fall
    back to pure Python, which reports the corruption with the sequential
    path's own exception."""


@dataclasses.dataclass
class _NativeFileMeta:
    """Per-file schema resolution, computed ONCE on the framing thread (the
    container schema is constant across a file's blocks)."""

    ftypes: list
    pos: dict
    label_pos: int
    bag_pos: dict


def _native_file_meta(schema_json, shard_configs, id_tags) -> _NativeFileMeta:
    """The sequential path's per-block schema checks, hoisted per file;
    raises _UnsupportedNativeLayout where the sequential path returns None."""
    from photon_ml_tpu.data import native_avro

    fields = schema_json.get("fields", [])
    ftypes = native_avro.field_types_for_schema(fields)
    if ftypes is None:
        raise _UnsupportedNativeLayout("unsupported field layout")
    pos = {f["name"]: i for i, f in enumerate(fields)}
    label_pos = pos.get("label", pos.get("response"))
    if label_pos is None:
        raise _UnsupportedNativeLayout("no label/response field")
    # reference id lookup is record-field-first (GameConverters.scala:
    # 152-166); the columnar fast path only implements the common
    # metadataMap case — top-level id fields take the Python path
    if id_tags and (any(tag in pos for tag in id_tags) or "metadataMap" not in pos):
        raise _UnsupportedNativeLayout("id tags need the pure-Python id lookup")
    bag_pos = {
        bag: pos[bag]
        for cfg in shard_configs.values()
        for bag in cfg.feature_bags
        if bag in pos
    }
    return _NativeFileMeta(ftypes=ftypes, pos=pos, label_pos=label_pos, bag_pos=bag_pos)


@dataclasses.dataclass
class _BlockColumns:
    """One block's extracted columns — everything assembly needs, with the
    raw payload and the native handle already released."""

    row_base: int
    n: int
    labels: np.ndarray
    block_has_labels: bool
    offsets: Optional[np.ndarray]
    weights: Optional[np.ndarray]
    uids: list
    # (global rows, tag str objects, value str objects), entry order preserved
    id_entries: Optional[tuple]
    # shard -> [(global rows, unique keys, inverse, values), ...] in bag order
    shard_entries: dict


def _decode_native_block(blk, shard_configs, id_tags) -> _BlockColumns:
    """Worker: inflate + native decode + columnar extraction for one block.
    All heavy steps (zlib, the ctypes decode, numpy bulk ops) release the
    GIL; the DecodedBlock is closed before returning, so a result never pins
    its payload."""
    from photon_ml_tpu.data import native_avro

    meta: _NativeFileMeta = blk.meta
    payload = avro_io.inflate_block(blk.payload, blk.codec)
    try:
        block = native_avro.decode_block(payload, blk.n_records, meta.ftypes)
    except ValueError as e:
        raise _NativeBlockError(str(e)) from e
    try:
        return _extract_block_columns(block, payload, blk, meta, shard_configs, id_tags)
    finally:
        block.close()


def _extract_block_columns(block, payload, blk, meta, shard_configs, id_tags):
    from photon_ml_tpu.data import native_avro

    DOUBLES = (native_avro.F_DOUBLE, native_avro.F_NULLABLE_DOUBLE)
    pos, ftypes, label_pos = meta.pos, meta.ftypes, meta.label_pos

    # nullable doubles decode nulls as NaN; match the Python path's defaults
    # (label 0, offset 0, weight 1) and its has_labels semantics
    lab = block.doubles(label_pos)
    block_has_labels = False
    if ftypes[label_pos] == native_avro.F_NULLABLE_DOUBLE:
        if np.any(~np.isnan(lab)):
            block_has_labels = True
        lab = np.where(np.isnan(lab), 0.0, lab)
    elif len(lab):
        block_has_labels = True
    offsets = weights = None
    if "offset" in pos and ftypes[pos["offset"]] in DOUBLES:
        off = block.doubles(pos["offset"])
        offsets = np.where(np.isnan(off), 0.0, off)
    if "weight" in pos and ftypes[pos["weight"]] in DOUBLES:
        w = block.doubles(pos["weight"])
        weights = np.where(np.isnan(w), 1.0, w)

    # synthetic uids stay FILE-anchored (<part-file>#<row-in-file>) exactly
    # like the sequential paths
    file_base, file_row = blk.file_base, blk.file_row
    if "uid" in pos and ftypes[pos["uid"]] == native_avro.F_NULLABLE_STRING:
        offs, lens = block.strings(pos["uid"])
        vals = block.strings_at(offs, lens)
        uids = [v if v else f"{file_base}#{file_row + i}" for i, v in enumerate(vals)]
    else:
        uids = [f"{file_base}#{file_row + i}" for i in range(block.count(label_pos))]

    id_entries = None
    if id_tags:
        map_field = pos["metadataMap"]
        rows, _ko, _kl, _vo, _vl = block.map_entries(map_field)
        if len(rows):
            uniq_keys, key_inv = block.dedup_keys(
                map_field, native_avro.DEDUP_MAP_KEYS
            )
            tag_set = set(id_tags)
            is_tag = np.array([k in tag_set for k in uniq_keys], dtype=bool)
            sel = np.flatnonzero(is_tag[key_inv])
            if len(sel):
                uniq_vals, val_inv = block.dedup_keys(
                    map_field, native_avro.DEDUP_MAP_VALUES
                )
                id_entries = (
                    rows[sel] + blk.row_base,
                    np.array(uniq_keys, dtype=object)[key_inv[sel]],
                    np.array(uniq_vals, dtype=object)[val_inv[sel]],
                )

    shard_entries = {s: [] for s in shard_configs}
    for shard_id, cfg in shard_configs.items():
        for bag in cfg.feature_bags:
            if bag not in meta.bag_pos:
                continue
            rows, _no, _nl, _to, _tl, vals = block.features(meta.bag_pos[bag])
            if not len(rows):
                continue
            uniq_keys, inverse = block.dedup_keys(
                meta.bag_pos[bag], native_avro.DEDUP_FEATURE_KEYS
            )
            shard_entries[shard_id].append(
                (rows + blk.row_base, uniq_keys, inverse, vals)
            )

    return _BlockColumns(
        row_base=blk.row_base,
        n=blk.n_records,
        labels=lab,
        block_has_labels=block_has_labels,
        offsets=offsets,
        weights=weights,
        uids=uids,
        id_entries=id_entries,
        shard_entries=shard_entries,
    )


def _read_merged_native_parallel(
    path, shard_configs, index_maps, id_tags, workers: int, window: Optional[int]
):
    """Streaming parallel counterpart of _read_merged_native. Returns None
    when the decoder or schema is unsupported (callers fall back to the pure-
    Python path, exactly like the sequential fast path)."""
    from photon_ml_tpu.data import native_avro, pipeline
    from photon_ml_tpu.data.game_data import GameInput

    if not native_avro.available():
        return None
    files = avro_io.container_files(path)

    def tasks():
        current, meta = None, None
        for blk in pipeline.iter_file_blocks(files):
            if blk.file_path != current:
                current = blk.file_path
                meta = _native_file_meta(blk.schema_json, shard_configs, id_tags)
            blk.meta = meta
            yield blk

    # streaming accumulators: per-block columns land here in MANIFEST order
    # while workers decode later blocks (index-map application and triplet
    # accumulation overlap decode by construction)
    n_total = 0
    has_labels = False
    label_parts: list = []  # (row_base, array)
    offset_parts: list = []
    weight_parts: list = []
    uid_parts: list = []
    id_parts: list = []
    ent_rows: dict = {s: [] for s in shard_configs}
    ent_keys: dict = {s: [] for s in shard_configs}  # (unique keys, inverse)
    ent_vals: dict = {s: [] for s in shard_configs}

    try:
        for col in pipeline.map_ordered(
            tasks(),
            lambda b: _decode_native_block(b, shard_configs, id_tags),
            workers,
            window,
        ):
            n_total = col.row_base + col.n
            has_labels = has_labels or col.block_has_labels
            label_parts.append((col.row_base, col.labels))
            if col.offsets is not None:
                offset_parts.append((col.row_base, col.offsets))
            if col.weights is not None:
                weight_parts.append((col.row_base, col.weights))
            uid_parts.append((col.row_base, col.uids))
            if col.id_entries is not None:
                id_parts.append(col.id_entries)
            for shard_id, entries in col.shard_entries.items():
                for rows, uniq, inverse, vals in entries:
                    ent_rows[shard_id].append(rows)
                    ent_keys[shard_id].append((uniq, inverse))
                    ent_vals[shard_id].append(vals)
    except (_UnsupportedNativeLayout, _NativeBlockError):
        return None  # pure-Python path handles (or reports) it

    labels = np.zeros(n_total)
    offsets = np.zeros(n_total)
    weights = np.ones(n_total)
    uids = np.empty(n_total, dtype=object)
    for base, arr in label_parts:
        labels[base : base + len(arr)] = arr
    for base, arr in offset_parts:
        offsets[base : base + len(arr)] = arr
    for base, arr in weight_parts:
        weights[base : base + len(arr)] = arr
    for base, lst in uid_parts:
        uids[base : base + len(lst)] = lst

    id_cols = {tag: np.full(n_total, None, dtype=object) for tag in id_tags}
    for rows, tags, vals in id_parts:
        for tag in id_tags:
            m = tags == tag
            # fancy assignment applies entries in order -> last wins per row,
            # matching the sequential entry walk
            id_cols[tag][rows[m]] = vals[m]
    for tag in id_tags:
        missing = np.flatnonzero(np.equal(id_cols[tag], None))
        if len(missing):
            raise ValueError(
                f"Sample {missing[0]}: cannot find id in either record field "
                f"{tag!r} or in metadataMap with key {tag!r}"
            )

    # ---- index maps (built from data when absent) ------------------------------
    index_maps = dict(index_maps or {})
    for shard_id, cfg in shard_configs.items():
        if shard_id not in index_maps:
            all_keys: set = set()
            for uniq, _inverse in ent_keys[shard_id]:
                all_keys.update(uniq)
            index_maps[shard_id] = IndexMap.build(all_keys, add_intercept=cfg.has_intercept)

    # ---- shard assembly: per-block vocab -> cols, then the shared tail ---------
    features = {}
    for shard_id, cfg in shard_configs.items():
        imap = index_maps[shard_id]
        if ent_rows[shard_id]:
            rows = np.concatenate(ent_rows[shard_id])
            vals = np.concatenate(ent_vals[shard_id])
            get_index = imap.get_index
            cols = np.concatenate([
                np.fromiter(
                    (get_index(k) for k in uniq), dtype=np.int64, count=len(uniq)
                )[inverse]
                for uniq, inverse in ent_keys[shard_id]
            ])
        else:
            rows = np.zeros(0, dtype=np.int64)
            cols = np.zeros(0, dtype=np.int64)
            vals = np.zeros(0, dtype=np.float64)
        features[shard_id] = _assemble_shard_matrix(imap, rows, cols, vals, n_total)

    game_input = GameInput(
        features=features,
        labels=labels if has_labels else None,
        offsets=offsets,
        weights=weights,
        id_columns={k: np.asarray(v, dtype=object) for k, v in id_cols.items()},
    )
    return game_input, index_maps, uids


def _read_records_parallel(path, workers: int, window: Optional[int]):
    """Pure-Python record decode through the block pipeline: framing and
    inflate overlap record decoding (the per-record walk itself is Python and
    gains no parallel speedup, but behavior and results match the sequential
    loop record for record). Returns (records, fallback_uids)."""
    from photon_ml_tpu.data import pipeline

    files = avro_io.container_files(path)

    def tasks():
        schemas: dict = {}
        for blk in pipeline.iter_file_blocks(files):
            schema = schemas.get(blk.file_path)
            if schema is None:
                schema = schemas[blk.file_path] = avro_io.Schema(blk.schema_json)
            blk.meta = schema  # read-only after construction: thread-safe
            yield blk

    def decode(blk):
        payload = avro_io.inflate_block(blk.payload, blk.codec)
        buf = io.BytesIO(payload)
        root = blk.meta.root
        recs = [avro_io.decode(buf, root) for _ in range(blk.n_records)]
        return blk.file_base, blk.file_row, recs

    records: list = []
    fallback_uids: list = []
    for file_base, file_row, recs in pipeline.map_ordered(
        tasks(), decode, workers, window
    ):
        records.extend(recs)
        fallback_uids.extend(
            f"{file_base}#{file_row + i}" for i in range(len(recs))
        )
    return records, fallback_uids
