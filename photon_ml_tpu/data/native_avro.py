"""ctypes bridge to the native Avro block decoder (photon_ml_tpu/native/avro_block_decoder.cpp).

Container framing (magic, metadata, codec, sync markers) and zlib inflate stay
in Python — both already run at C speed — while the per-record varint walk,
which dominates pure-Python ingest, runs native. The shared object is compiled
on demand with g++ and cached next to the source; when no compiler is
available every entry point degrades to ``available() == False`` and callers
fall back to the pure-Python decoder in data/avro_io.py.

Supported record layouts: every field must be one of
  double | ["null","double"] | ["null","string"] |
  array<FeatureAvro{name,term,value}> | ["null", map<string>]
which covers TrainingExampleAvro, ResponsePredictionAvro and custom multi-bag
training schemas. Schemas outside this set simply use the Python path.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

F_DOUBLE = 0
F_NULLABLE_DOUBLE = 1
F_NULLABLE_STRING = 2
F_FEATURE_ARRAY = 3
F_NULLABLE_MAP_STRING = 4

# photon_avro_dedup `which` selectors (DecodedBlock.dedup_keys)
DEDUP_FEATURE_KEYS = 0  # name + '\x01' + term, the feature_key() composition
DEDUP_MAP_KEYS = 1
DEDUP_MAP_VALUES = 2

_SOURCE = os.path.join(os.path.dirname(__file__), "..", "native", "avro_block_decoder.cpp")
# Build cache lives under the user cache dir, NOT the package tree: with a
# pip-installed (possibly read-only) site-packages, writing next to the source
# would raise OSError and break ingest instead of degrading to the Python
# decoder.
_CACHE_DIR = os.path.join(
    os.environ.get("XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")),
    "photon_ml_tpu",
    "native_build",
)

_lib = None
_lib_error: Optional[str] = None
_lock = threading.Lock()


def _build_library() -> Optional[str]:
    source = os.path.abspath(_SOURCE)
    if not os.path.exists(source):
        return None
    try:
        with open(source, "rb") as f:
            src_bytes = f.read()
        # The cache is shared across installs (user cache dir), so the .so is
        # keyed by source CONTENT, not mtime — pip-installed trees often carry
        # archive mtimes that would make a stale cross-version .so look fresh.
        digest = hashlib.sha256(src_bytes).hexdigest()[:16]
        os.makedirs(_CACHE_DIR, exist_ok=True)
        so_path = os.path.join(_CACHE_DIR, f"libphoton_avro-{digest}.so")
        if os.path.exists(so_path):
            return so_path
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, source]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)
    except (OSError, subprocess.SubprocessError):
        # Unwritable cache dir, missing compiler, or failed build: the pure-
        # Python decoder handles every input, just slower.
        return None
    return so_path


def _load():
    global _lib, _lib_error
    with _lock:
        if _lib is not None or _lib_error is not None:
            return _lib
        so_path = _build_library()
        if so_path is None:
            _lib_error = "native decoder unavailable (no source or compiler)"
            return None
        try:
            lib = _bind(ctypes.CDLL(so_path))
        except (OSError, AttributeError):
            # Stale/incompatible cached .so (wrong arch/ABI, corrupt, or an old
            # build missing symbols): drop it and rebuild from source once,
            # degrading to the pure-Python decoder if that fails too.
            try:
                os.remove(so_path)
            except OSError:
                pass
            so_path = _build_library()
            try:
                lib = _bind(ctypes.CDLL(so_path)) if so_path else None
            except (OSError, AttributeError):
                lib = None
            if lib is None:
                _lib_error = "native decoder .so failed to load; using Python path"
                return None
        _lib = lib
        return _lib


def _bind(lib):
    lib.photon_avro_decode.restype = ctypes.c_void_p
    lib.photon_avro_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    lib.photon_avro_error.restype = ctypes.c_char_p
    lib.photon_avro_error.argtypes = [ctypes.c_void_p]
    lib.photon_avro_count.restype = ctypes.c_int64
    lib.photon_avro_count.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    i64p = np.ctypeslib.ndpointer(dtype=np.int64, flags="C_CONTIGUOUS")
    f64p = np.ctypeslib.ndpointer(dtype=np.float64, flags="C_CONTIGUOUS")
    lib.photon_avro_doubles.argtypes = [ctypes.c_void_p, ctypes.c_int32, f64p]
    lib.photon_avro_strings.argtypes = [ctypes.c_void_p, ctypes.c_int32, i64p, i64p]
    lib.photon_avro_features.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i64p, i64p, i64p, i64p, i64p, f64p,
    ]
    lib.photon_avro_map.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, i64p, i64p, i64p, i64p, i64p,
    ]
    i32p = np.ctypeslib.ndpointer(dtype=np.int32, flags="C_CONTIGUOUS")
    lib.photon_avro_dedup.restype = ctypes.c_int64
    lib.photon_avro_dedup.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32, i32p,
    ]
    lib.photon_avro_dedup_vocab_len.restype = ctypes.c_int64
    lib.photon_avro_dedup_vocab_len.argtypes = [ctypes.c_void_p]
    lib.photon_avro_free.argtypes = [ctypes.c_void_p]
    u8p = np.ctypeslib.ndpointer(dtype=np.uint8, flags="C_CONTIGUOUS")
    lib.photon_avro_dedup_vocab.argtypes = [ctypes.c_void_p, u8p, i64p]
    lib.photon_encode_scores.restype = ctypes.c_int64
    lib.photon_encode_scores.argtypes = [
        u8p, i64p, f64p, ctypes.c_int32, ctypes.c_char_p, ctypes.c_int64,
        f64p, f64p, ctypes.c_int64, u8p, ctypes.c_int64,
    ]
    return lib


def available() -> bool:
    return _load() is not None


def field_types_for_schema(fields: list) -> Optional[list[int]]:
    """Map an Avro record schema's fields to decoder field types; None when any
    field falls outside the supported set (callers then use the Python path)."""
    out = []
    for f in fields:
        t = f.get("type")
        if t == "double":
            out.append(F_DOUBLE)
        elif t == ["null", "double"]:
            out.append(F_NULLABLE_DOUBLE)
        elif t == ["null", "string"]:
            out.append(F_NULLABLE_STRING)
        elif (
            isinstance(t, dict)
            and t.get("type") == "array"
            and _is_feature_record(t.get("items"))
        ):
            out.append(F_FEATURE_ARRAY)
        elif (
            isinstance(t, list)
            and len(t) == 2
            and t[0] == "null"
            and isinstance(t[1], dict)
            and t[1].get("type") == "map"
            and t[1].get("values") == "string"
        ):
            out.append(F_NULLABLE_MAP_STRING)
        else:
            return None
    return out


def _is_feature_record(items) -> bool:
    if isinstance(items, str):  # named-type reference, e.g. "FeatureAvro"
        return items.rsplit(".", 1)[-1] == "FeatureAvro"
    if not isinstance(items, dict) or items.get("type") != "record":
        return False
    names = [f.get("name") for f in items.get("fields", ())]
    types = [f.get("type") for f in items.get("fields", ())]
    # value must be exactly "double": the native decoder reads 8 fixed bytes per
    # value, so a float/nullable value schema must take the pure-Python path.
    return names == ["name", "term", "value"] and types == ["string", "string", "double"]


class DecodedBlock:
    """Columnar view over one decoded block. String columns come back as
    (offsets, lengths) into ``payload``; ``strings_at`` materializes them.

    Thread model: each block owns an independent native handle, so DIFFERENT
    blocks may be decoded and read concurrently (the parallel ingest pipeline
    does exactly that — the ctypes calls release the GIL). One block instance
    is not a shared object: confine it to the thread that decoded it. After
    ``close()`` every accessor raises instead of dereferencing a freed handle.
    """

    def __init__(self, payload: bytes, handle: int, lib, n_fields: int):
        self._payload = payload
        self._view = np.frombuffer(payload, dtype=np.uint8)
        self._handle = handle
        self._lib = lib
        self._n_fields = n_fields

    def _live_handle(self) -> int:
        handle = self._handle
        if not handle:
            raise RuntimeError("DecodedBlock is closed (native buffers freed)")
        return handle

    def count(self, field: int) -> int:
        return int(self._lib.photon_avro_count(self._live_handle(), field))

    def doubles(self, field: int) -> np.ndarray:
        handle = self._live_handle()
        n = self.count(field)
        out = np.empty(n, dtype=np.float64)
        self._lib.photon_avro_doubles(handle, field, out)
        return out

    def strings(self, field: int) -> tuple[np.ndarray, np.ndarray]:
        handle = self._live_handle()
        n = self.count(field)
        offs = np.empty(n, dtype=np.int64)
        lens = np.empty(n, dtype=np.int64)
        self._lib.photon_avro_strings(handle, field, offs, lens)
        return offs, lens

    def features(self, field: int):
        """(rows, name_offs, name_lens, term_offs, term_lens, values)."""
        handle = self._live_handle()
        n = self.count(field)
        rows = np.empty(n, dtype=np.int64)
        no = np.empty(n, dtype=np.int64)
        nl = np.empty(n, dtype=np.int64)
        to = np.empty(n, dtype=np.int64)
        tl = np.empty(n, dtype=np.int64)
        vals = np.empty(n, dtype=np.float64)
        self._lib.photon_avro_features(handle, field, rows, no, nl, to, tl, vals)
        return rows, no, nl, to, tl, vals

    def map_entries(self, field: int):
        """(rows, key_offs, key_lens, val_offs, val_lens)."""
        handle = self._live_handle()
        n = self.count(field)
        rows = np.empty(n, dtype=np.int64)
        ko = np.empty(n, dtype=np.int64)
        kl = np.empty(n, dtype=np.int64)
        vo = np.empty(n, dtype=np.int64)
        vl = np.empty(n, dtype=np.int64)
        self._lib.photon_avro_map(handle, field, rows, ko, kl, vo, vl)
        return rows, ko, kl, vo, vl

    def dedup_keys(self, field: int, which: int) -> tuple[list, np.ndarray]:
        """(vocabulary list[str], per-entry int32 vocabulary ids) for one
        string-keyed column — the ingest pipeline's per-block key dedupe, run
        natively (no GIL) so only the tiny VOCABULARY pays Python-level
        decode. ``which``: DEDUP_FEATURE_KEYS composes name+DELIMITER+term
        per FeatureAvro entry; DEDUP_MAP_KEYS / DEDUP_MAP_VALUES intern one
        side of a map column's entries. Vocabulary order is first occurrence
        (deterministic; consumers treat it as unordered)."""
        handle = self._live_handle()
        n = self.count(field)
        ids = np.empty(n, dtype=np.int32)
        n_vocab = self._lib.photon_avro_dedup(
            handle, self._payload, field, which, ids
        )
        if n_vocab < 0:
            raise ValueError(f"dedup unsupported for field {field} (which={which})")
        nbytes = self._lib.photon_avro_dedup_vocab_len(handle)
        buf = np.empty(max(int(nbytes), 1), dtype=np.uint8)
        offs = np.empty(int(n_vocab) + 1, dtype=np.int64)
        self._lib.photon_avro_dedup_vocab(handle, buf, offs)
        raw = buf.tobytes()
        vocab = [
            raw[offs[i] : offs[i + 1]].decode() for i in range(int(n_vocab))
        ]
        return vocab, ids

    def string_at(self, off: int, length: int) -> str:
        if off < 0:
            return ""
        return self._payload[off : off + length].decode()

    def strings_at(self, offs: np.ndarray, lens: np.ndarray) -> list:
        payload = self._payload
        return [
            payload[o : o + l].decode() if o >= 0 else None
            for o, l in zip(offs.tolist(), lens.tolist())
        ]

    def close(self) -> None:
        # swap-then-free: idempotent, and safe against a close()/__del__ pair
        # racing under the GIL (only one observer sees the live handle)
        handle, self._handle = self._handle, 0
        if handle:
            self._lib.photon_avro_free(handle)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        self.close()


def decode_block(payload: bytes, n_records: int, field_types: list[int]) -> DecodedBlock:
    """Decode one decompressed Avro block; raises ValueError on malformed data."""
    lib = _load()
    if lib is None:
        raise RuntimeError(_lib_error or "native decoder unavailable")
    ftypes = (ctypes.c_int32 * len(field_types))(*field_types)
    handle = lib.photon_avro_decode(
        payload, len(payload), n_records, ftypes, len(field_types)
    )
    if not handle:
        raise MemoryError("native avro decoder allocation failed")
    err = lib.photon_avro_error(handle)
    if err:
        msg = err.decode()
        lib.photon_avro_free(handle)
        raise ValueError(f"native avro decode failed: {msg}")
    return DecodedBlock(payload, handle, lib, len(field_types))


def encode_scores(uids, labels, model_id: str, scores, weights):
    """Encode ScoringResultAvro record payloads natively (one block's bytes).

    ``uids`` is a sequence of strings; ``labels`` is None or a float array.
    Returns bytes, or None when the native library is unavailable (caller
    falls back to the pure-Python record encoder)."""
    lib = _load()
    if lib is None:
        return None
    n = len(scores)
    uid_bytes = [str(u).encode() for u in uids]
    if len(uid_bytes) != n:
        raise ValueError(f"{len(uid_bytes)} uids for {n} scores")
    offsets = np.zeros(n + 1, dtype=np.int64)
    for i, b in enumerate(uid_bytes):
        offsets[i + 1] = offsets[i] + len(b)
    uid_buf = np.frombuffer(b"".join(uid_bytes), dtype=np.uint8) if n else np.zeros(0, np.uint8)
    uid_buf = np.ascontiguousarray(uid_buf)
    has_labels = labels is not None
    labels_arr = np.ascontiguousarray(
        np.asarray(labels, dtype=np.float64) if has_labels else np.zeros(n)
    )
    scores_arr = np.ascontiguousarray(np.asarray(scores, dtype=np.float64))
    weights_arr = np.ascontiguousarray(np.asarray(weights, dtype=np.float64))
    mid = str(model_id).encode()
    # per record: uid varint+bytes, unions (<=5 varints ~5B), modelId, 2 doubles
    cap = int(offsets[-1]) + n * (40 + len(mid)) + 64
    out = np.zeros(cap, dtype=np.uint8)
    written = lib.photon_encode_scores(
        uid_buf, offsets, labels_arr, 1 if has_labels else 0,
        mid, len(mid), scores_arr, weights_arr, n, out, cap,
    )
    if written < 0:
        return None
    return out[:written].tobytes()
