"""Random-effect datasets: ragged per-entity data on a dense SPMD machine.

Re-designs photon-api data/RandomEffectDataset.scala:46-508 + LocalDataset.scala:35-251
+ RandomEffectDatasetPartitioner for TPU. The reference keeps RDD[(REId, LocalDataset)]
and solves per entity inside mapValues; here:

- host ingest groups samples by entity ONCE (replacing the groupBy shuffle),
  with the reference's semantics: deterministic reservoir-sampling cap on active
  data with weight rescale count/cap (generateActiveData:293-342,
  groupDataByKeyAndSample:358-420), lower-bound filtering (:433-478 neighborhood),
  per-entity Pearson-correlation feature selection
  (LocalDataset.filterFeaturesByPearsonCorrelationScore:110-138),
  per-entity index-map projection (projector/IndexMapProjectorRDD.scala:36-274);
- entities are BUCKETED by (padded sample count, padded feature count) into dense
  [E_b, S, K] blocks so a vmap-ed optimizer solves a whole bucket as one XLA
  program; padding rows carry weight 0 (inert by construction);
- samples beyond the active cap become passive data (score-only), exactly the
  reference's active/passive split;
- a per-sample gathered view over the FULL dataset supports O(1) scoring and the
  coordinate-descent score exchange without joins.

The partitioner disappears: bucket leading axes are sharded over the device mesh
(parallel/), which replaces the greedy bin-packing of
RandomEffectDatasetPartitioner.scala:1-171.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.types import intercept_key

Array = jnp.ndarray


def _entity_seed(entity_id: str, base_seed: int) -> int:
    """Deterministic per-entity seed (the reference uses byteswap64-mixed keys so
    reservoir sampling is reproducible on recomputation, RandomEffectDataset.scala:
    394-402; a stable hash gives the same property)."""
    h = hashlib.blake2b(f"{base_seed}:{entity_id}".encode(), digest_size=8)
    return int.from_bytes(h.digest(), "little")


def _next_pow2(n: int, minimum: int) -> int:
    p = minimum
    while p < n:
        p *= 2
    return p


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EntityBucket:
    """One padded block of entities with similar shapes.

    X is [E, S, K] in each entity's local (projected) space; sample_ids are global
    sample-axis positions (-1 padding) used to gather offsets/partial scores and to
    scatter this coordinate's scores back.
    """

    entity_rows: Array  # [E] int32 — row into the dataset-wide entity table
    X: Array  # [E, S, K]
    labels: Array  # [E, S]
    weights: Array  # [E, S] (0 = padding)
    sample_ids: Array  # [E, S] int32 (-1 padding)

    @property
    def n_entities(self) -> int:
        return self.X.shape[0]

    @property
    def shape(self) -> tuple[int, int]:
        return self.X.shape[1], self.X.shape[2]


@dataclasses.dataclass
class RandomEffectDataset:
    """All per-entity training blocks + the per-sample scoring view for one
    (random-effect type, feature shard) coordinate."""

    re_type: str
    feature_shard_id: str
    entity_ids: tuple  # entities WITH active data (training targets), row order
    buckets: list[EntityBucket]
    # dataset-wide per-entity projection table, [E, K_max] global col ids (-1 pad)
    proj_indices: Array
    # per-sample scoring view over the FULL sample axis:
    sample_entity_rows: Array  # [N] int32, -1 = entity has no model
    sample_local_cols: Array  # [N, nnz] int32 into the entity's K axis, -1 pad
    sample_vals: Array  # [N, nnz]
    n_samples: int
    # passive-sample bookkeeping (reference passiveData): ids not in active blocks
    n_active_samples: int = 0
    n_passive_samples: int = 0
    # RandomProjector when the dataset lives in a shared projected space
    # (projector/ProjectionMatrixBroadcast semantics); None for index-map/identity
    projector: Optional[object] = None
    # set by parallel.placement: NamedSharding for the coefficient tables
    # (entity axis sharded over the mesh) and their padded row count (next
    # multiple of the mesh size >= n_entities; device_put requires divisibility).
    # None on the host backend. Rows >= n_entities are always-zero padding.
    coeffs_sharding: Optional[object] = None
    coeffs_rows: Optional[int] = None

    @property
    def n_entities(self) -> int:
        return len(self.entity_ids)

    @property
    def max_k(self) -> int:
        return self.proj_indices.shape[1]

    def scoring_view(self, model=None):
        """(entity_rows [N], local_cols [N, nnz], vals [N, nnz]) for
        RandomEffectModel.score_dataset."""
        return self.sample_entity_rows, self.sample_local_cols, self.sample_vals


def _resolve_merge_fraction(bucket_merge_fraction: Optional[float]) -> float:
    """Resolve the auto (None) bucket-merge policy by backend.

    Consolidating rare bucket shapes trades padded FLOPs for fewer sequential
    solver programs per pass. On an accelerator the programs are pure dispatch
    latency, so the trade wins; on CPU the extra padded FLOPs are real compute
    on a latency-cheap backend and consolidation measured ~25% slower on the
    flagship bench (186k -> 141k samples/s). Auto therefore consolidates only
    when the default JAX backend is not the CPU. Pass an explicit fraction
    (0 disables) to override per-dataset.
    """
    if bucket_merge_fraction is not None:
        return bucket_merge_fraction
    env = os.environ.get("PHOTON_BUCKET_MERGE", "").strip()
    if env:
        # experimentation override (e.g. bench sweeps: 0 = off, 1.0 = merge
        # every sub-threshold shape class, still under the padding budget)
        try:
            return float(env)
        except ValueError:
            raise ValueError(
                f"PHOTON_BUCKET_MERGE must be a number, got {env!r}"
            ) from None
    return 0.05 if jax.default_backend() != "cpu" else 0.0


def _consolidate_buckets(
    bucket_members: dict, n_ent: int, merge_fraction: float
) -> dict:
    """Merge rare bucket shape classes into nearby larger shapes.

    Every bucket is a separate sequential vmapped-solver program per
    coordinate-descent pass — on TPU that is pure latency, so shape classes
    holding fewer than ``merge_fraction`` of the entities are folded into the
    partner bucket that wastes the fewest padded cells. Padding is inert by
    construction (weight-0 rows; zero columns keep their coefficients at 0
    under L2), so only shapes change, never results. A merge is only taken
    when its added padding stays below the current total cell count, which
    blocks pathological merges (e.g. one huge entity inflating everyone's
    sample axis).
    """
    if merge_fraction <= 0 or len(bucket_members) <= 1:
        return bucket_members
    merged = dict(bucket_members)
    # Cumulative padding growth is capped against the PRE-consolidation total
    # (a per-step budget would ratchet: each merge inflates the base the next
    # merge is judged against). At 1.0x the padded cell count can at most
    # double — a deliberate memory-for-latency trade: every removed bucket is
    # one fewer sequential solver program per coordinate-descent pass, and the
    # blocks are small relative to HBM.
    budget = 1.0 * sum(len(m) * s * k for (s, k), m in merged.items())
    added_total = 0.0
    skip: set = set()  # shapes whose every merge exceeds the budget
    while True:
        candidates = sorted(
            (len(m), key) for key, m in merged.items() if key not in skip
        )
        progressed = False
        for cnt, (s1, k1) in candidates:
            if cnt >= merge_fraction * n_ent:
                break  # candidates are sorted: nothing rarer remains
            m1 = merged[(s1, k1)]
            best = None
            for (s2, k2), m2 in merged.items():
                if (s2, k2) == (s1, k1):
                    continue
                S, K = max(s1, s2), max(k1, k2)
                added = (
                    (len(m1) + len(m2)) * S * K
                    - len(m1) * s1 * k1
                    - len(m2) * s2 * k2
                )
                if added_total + added <= budget and (best is None or added < best[0]):
                    best = (added, (s2, k2))
            if best is None:
                skip.add((s1, k1))  # unmergeable; keep trying the others
                continue
            added, (s2, k2) = best
            m2 = merged.pop((s2, k2))
            merged.pop((s1, k1))
            key = (max(s1, s2), max(k1, k2))
            combined = np.sort(np.concatenate([m1, m2]))
            if key in merged:
                combined = np.sort(np.concatenate([merged[key], combined]))
            merged[key] = combined
            added_total += added
            skip.clear()  # a merge changes the partner landscape
            progressed = True
            break  # re-sort candidates against the new bucket set
        if not progressed:
            break
    return merged


def build_random_effect_dataset(
    X: sp.spmatrix,
    entity_ids_per_sample: Sequence,
    re_type: str,
    feature_shard_id: str = "global",
    *,
    active_data_upper_bound: Optional[int] = None,
    active_data_lower_bound: int = 1,
    features_max: Optional[int] = None,
    labels: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    intercept_index: Optional[int] = None,
    normalization: Optional[NormalizationContext] = None,
    seed: int = 0,
    dtype=jnp.float32,
    min_samples_pad: int = 8,
    min_features_pad: int = 4,
    bucket_merge_fraction: Optional[float] = None,
    scoring_only: bool = False,
    projector: Optional[object] = None,
    entity_order: Optional[Sequence] = None,
    exclude_entities: Optional[set] = None,
) -> RandomEffectDataset:
    """Host-side construction of the bucketed random-effect dataset.

    - ``active_data_upper_bound``: reservoir cap; kept samples get weight * n/cap
      (RandomEffectDataset.scala:358-420). Overflow samples become passive.
    - ``active_data_lower_bound``: entities with fewer active samples train no model
      (their samples score 0), reference lower-bound filtering.
    - ``features_max``: per-entity Pearson feature selection cap (needs ``labels``).
    - ``normalization``: applied to the materialized blocks (x' = (x-shift)*factor);
      models are converted back to original space after the solve, so scoring and
      model export always live in the original space.
    - ``scoring_only``: skip training-bucket materialization entirely (validation /
      transform datasets only need the per-sample scoring view); caps, lower-bound
      filtering and Pearson selection don't apply to scoring data.
    - ``projector``: a data.projector.RandomProjector. Features — and the
      projector's OWN carried normalization — are folded into the shared
      projected space up-front; the dataset then lives entirely in that space
      (every entity observes the same k(+1) projected columns), matching
      RandomEffectCoordinateInProjectedSpace. Pass normalization via the
      projector (make_projector(..., normalization=...)), not this function's
      ``normalization`` argument, so scoring datasets (which never see the
      training normalization) stay consistent.
    - ``entity_order``: STABLE entity-row growth for incremental training
      (continuous/): entities appearing in this sequence keep its relative
      order (row i of the previous generation's table stays row i as long as
      the entity still trains), unseen entities append at the tail in sorted
      order — so a previous generation's coefficient table aligns with the
      grown dataset by construction. Default (None) keeps the historical
      fully sorted order.
    - ``exclude_entities``: entity-row SHRINK for continuous training's
      eviction (continuous/compaction.py): listed entities get no training
      bucket and no model row — their samples' scoring-view entity row is -1,
      i.e. they score exactly like entities that never had a model (the
      serving engine's missing-entity contract, now on the training side too).
    """
    if projector is not None:
        if normalization is not None and projector.normalization is None:
            raise ValueError(
                "normalization must be carried BY the projector "
                "(make_projector(..., normalization=...)) so training and scoring "
                "datasets agree on the projected space"
            )
        X = projector.project_features(X)
        normalization = None
        intercept_index = (
            projector.projected_dim - 1 if projector.intercept_index is not None else None
        )
    if scoring_only:
        active_data_upper_bound = None
        active_data_lower_bound = 1
        features_max = None
    elif labels is None:
        raise ValueError(
            "labels are required to build training buckets; pass scoring_only=True "
            "for validation/transform datasets that only need the scoring view"
        )
    X = X.tocsr()
    n, d = X.shape
    base_weights = np.ones(n) if weights is None else np.asarray(weights, dtype=np.float64)
    ent = np.asarray(entity_ids_per_sample)
    if len(ent) != n:
        raise ValueError("entity ids and sample count mismatch")

    # ---- group samples by entity (the one-time 'shuffle') -----------------------
    order = np.argsort(ent, kind="mergesort")
    sorted_ent = ent[order]
    boundaries = np.flatnonzero(sorted_ent[1:] != sorted_ent[:-1]) + 1
    if n:
        starts = np.concatenate([[0], boundaries])
        stops = np.concatenate([boundaries, [n]])
    else:  # empty input (e.g. an empty validation split): no groups at all
        starts = stops = boundaries

    active_rows: dict = {}
    weights_scale: dict = {}
    passive_count = 0
    for a, b in zip(starts, stops):
        e_id = sorted_ent[a]
        rows = order[a:b]
        count = len(rows)
        if active_data_upper_bound is not None and count > active_data_upper_bound:
            rng = np.random.default_rng(_entity_seed(str(e_id), seed))
            keys = rng.random(count)
            keep = rows[np.argsort(keys, kind="mergesort")[: active_data_upper_bound]]
            active_rows[e_id] = np.sort(keep)
            weights_scale[e_id] = count / active_data_upper_bound
            passive_count += count - active_data_upper_bound
        else:
            active_rows[e_id] = np.sort(rows)
            weights_scale[e_id] = 1.0

    # lower-bound filter: entities below the threshold train no model
    entities = [e for e, rows in active_rows.items() if len(rows) >= active_data_lower_bound]
    if exclude_entities:
        entities = [e for e in entities if e not in exclude_entities]
    if entity_order is not None:
        # stable growth: known entities keep the caller's row order, unseen
        # ones append sorted at the tail (continuous-training alignment)
        present = set(entities)
        known = [e for e in entity_order if e in present]
        known_set = set(known)
        entities = known + sorted(e for e in entities if e not in known_set)
    else:
        entities.sort()
    row_of_entity = {e: i for i, e in enumerate(entities)}
    n_ent = len(entities)
    labels_arr = None if labels is None else np.asarray(labels, dtype=np.float64)

    # Flat active-sample machinery shared by the (vectorized) observed-column
    # computation and the bucket fill: one concatenated row list replaces the
    # per-entity scipy CSR slicing that dominated build time at 100k+ entities.
    lens = np.asarray([len(active_rows[e]) for e in entities], dtype=np.int64)
    act_concat = (
        np.concatenate([active_rows[e] for e in entities])
        if n_ent
        else np.zeros(0, dtype=np.int64)
    )
    ent_row_per_act = np.repeat(np.arange(n_ent, dtype=np.int64), lens)
    act_starts = np.concatenate([[0], np.cumsum(lens)[:-1]]) if n_ent else lens
    s_local_per_act = np.arange(len(act_concat)) - np.repeat(act_starts, lens)
    # active nnz: global nnz positions of every active sample's entries
    counts_all = np.diff(X.indptr)
    c_act = counts_all[act_concat]
    total_act_nnz = int(c_act.sum())
    nnz_cum = np.concatenate([[0], np.cumsum(c_act)[:-1]]) if len(c_act) else c_act
    act_nnz_idx = (
        np.repeat(X.indptr[act_concat], c_act)
        + (np.arange(total_act_nnz) - np.repeat(nnz_cum, c_act))
    ).astype(np.int64)
    ent_of_act_nnz = np.repeat(ent_row_per_act, c_act)
    s_local_of_act_nnz = np.repeat(s_local_per_act, c_act)

    # ---- per-entity projection (+ optional Pearson selection) -------------------
    # col_of[i]: sorted global col ids observed in entity i's ACTIVE rows.
    if n_ent == 0:
        col_of = []
    elif features_max is None:
        keys = ent_of_act_nnz * d + X.indices[act_nnz_idx].astype(np.int64)
        uniq_keys = np.unique(keys)
        ent_of_obs = uniq_keys // d
        obs_counts = np.bincount(ent_of_obs, minlength=n_ent)
        col_of = np.split(
            (uniq_keys % d).astype(np.int32), np.cumsum(obs_counts)[:-1]
        )
    else:
        # Pearson feature selection needs per-entity column/label statistics —
        # the per-entity loop stays on this opt-in path only.
        col_of = []
        for e in entities:
            rows = active_rows[e]
            sub = X[rows]  # csr [s, d]
            observed = np.unique(sub.indices) if sub.nnz else np.array([], dtype=np.int32)
            if len(observed) > features_max:
                if labels_arr is None:
                    raise ValueError("features_max (Pearson selection) requires labels")
                scores = _pearson_scores(sub, observed, labels_arr[rows])
                keep_order = np.argsort(-scores, kind="mergesort")
                kept = set(observed[keep_order[:features_max]].tolist())
                if intercept_index is not None:
                    kept.add(intercept_index)
                observed = np.asarray(sorted(kept), dtype=observed.dtype)
            col_of.append(observed.astype(np.int32))

    # ---- global nnz -> entity-local column mapping ------------------------------
    # local col = position of the global col in the entity's projection row.
    # Vectorized over all nnz: a dense [E, D] lookup when it fits, else per-entity
    # dict fallback (huge-D regimes). Used by BOTH the bucket fill (through
    # act_nnz_idx) and the per-sample scoring view.
    # map each sample's entity to its row id (vectorized: entities is sorted)
    s_ent_rows = np.full(n, -1, dtype=np.int32)
    uniq = np.asarray(entities)
    if len(uniq):
        # entity_order may leave `uniq` unsorted: search through a sorter so
        # the lookup stays vectorized either way (identity when sorted)
        sorter = np.argsort(uniq, kind="mergesort")
        pos = np.searchsorted(uniq, ent, sorter=sorter)
        rows = sorter[np.clip(pos, 0, len(uniq) - 1)]
        hit = uniq[rows] == ent
        s_ent_rows = np.where(hit, rows, -1).astype(np.int32)

    local = np.full(X.nnz, -1, dtype=np.int32)
    if n and X.nnz:
        rows_per_nnz = np.repeat(np.arange(n), counts_all)
        slot_per_nnz = np.arange(X.nnz) - np.repeat(X.indptr[:-1], counts_all)
        ent_per_nnz = s_ent_rows[rows_per_nnz]
        valid = ent_per_nnz >= 0
        if n_ent * d <= 50_000_000:
            lookup = np.full((max(n_ent, 1), d), -1, dtype=np.int32)
            for i, cols in enumerate(col_of):
                lookup[i, cols] = np.arange(len(cols), dtype=np.int32)
            local[valid] = lookup[ent_per_nnz[valid], X.indices[valid]]
        else:
            local_of = [{int(c): k for k, c in enumerate(cols)} for cols in col_of]
            idx_valid = np.flatnonzero(valid)
            for t in idx_valid:
                local[t] = local_of[ent_per_nnz[t]].get(int(X.indices[t]), -1)

    # ---- bucketing by (padded sample count, padded feature count) ---------------
    norm_factors = None if normalization is None or normalization.factors is None else np.asarray(normalization.factors)
    norm_shifts = None if normalization is None or normalization.shifts is None else np.asarray(normalization.shifts)

    k_counts = np.asarray([len(c) for c in col_of], dtype=np.int64)
    bucket_members: dict[tuple[int, int], np.ndarray] = {}
    if n_ent:
        s_pads = np.asarray([_next_pow2(int(c), min_samples_pad) for c in lens])
        k_pads = np.asarray(
            [_next_pow2(max(int(k), 1), min_features_pad) for k in k_counts]
        )
        pad_keys = s_pads * (2 ** 32) + k_pads
        for key in np.unique(pad_keys):
            members = np.flatnonzero(pad_keys == key)
            bucket_members[(int(key >> 32), int(key & (2 ** 32 - 1)))] = members
        if not scoring_only:  # scoring datasets discard the buckets entirely
            bucket_members = _consolidate_buckets(
                bucket_members, n_ent, _resolve_merge_fraction(bucket_merge_fraction)
            )

    # Dataset-wide projection table is as wide as the widest PADDED bucket so that
    # bucket slices coeffs_global[:, :K_bucket] always fit.
    max_k_all = max((k for _, k in bucket_members), default=min_features_pad)
    proj_table = np.full((n_ent, max_k_all), -1, dtype=np.int32)
    for i, cols in enumerate(col_of):
        proj_table[i, : len(cols)] = cols

    buckets: list[EntityBucket] = []
    if scoring_only:
        bucket_members = {}
    scale_arr = np.asarray([weights_scale[e] for e in entities], dtype=np.float64)
    local_of_act_nnz = local[act_nnz_idx] if total_act_nnz else local[:0]

    # One stable sort groups the flat sample/nnz arrays by bucket, so each
    # bucket gets a contiguous slice instead of re-scanning everything
    # (O(total_nnz) overall, not O(buckets x total_nnz)).
    sorted_keys = sorted(bucket_members.items())
    n_buckets = len(sorted_keys)
    bucket_id = np.full(max(n_ent, 1), -1, dtype=np.int64)
    e_local_all = np.zeros(max(n_ent, 1), dtype=np.int64)
    for b, (_, members) in enumerate(sorted_keys):
        bucket_id[members] = b
        e_local_all[members] = np.arange(len(members))
    act_order = np.argsort(bucket_id[ent_row_per_act], kind="stable") if n_ent else ent_row_per_act
    act_bounds = np.searchsorted(
        bucket_id[ent_row_per_act][act_order], np.arange(n_buckets + 1)
    )
    nnz_bucket = bucket_id[ent_of_act_nnz] if total_act_nnz else ent_of_act_nnz
    nnz_valid_local = local_of_act_nnz >= 0
    nnz_order = np.argsort(np.where(nnz_valid_local, nnz_bucket, -1), kind="stable")
    nnz_bounds = np.searchsorted(
        np.where(nnz_valid_local, nnz_bucket, -1)[nnz_order], np.arange(n_buckets + 1)
    )
    for b, ((s_pad, k_pad), members) in enumerate(sorted_keys):
        eb = len(members)
        Xb = np.zeros((eb, s_pad, k_pad), dtype=np.float64)
        yb = np.zeros((eb, s_pad), dtype=np.float64)
        wb = np.zeros((eb, s_pad), dtype=np.float64)
        sb = np.full((eb, s_pad), -1, dtype=np.int32)
        # sample-level fills (contiguous bucket slice)
        ai = act_order[act_bounds[b] : act_bounds[b + 1]]
        el_s, sl_s, rows_s = e_local_all[ent_row_per_act[ai]], s_local_per_act[ai], act_concat[ai]
        if labels_arr is not None:
            yb[el_s, sl_s] = labels_arr[rows_s]
        wb[el_s, sl_s] = base_weights[rows_s] * scale_arr[ent_row_per_act[ai]]
        sb[el_s, sl_s] = rows_s
        # nnz-level X fill (duplicate (row, col) entries sum, as toarray does;
        # bincount over raveled indices = vectorized scatter-add)
        ni = nnz_order[nnz_bounds[b] : nnz_bounds[b + 1]]
        gv = X.data[act_nnz_idx[ni]].astype(np.float64)
        gc = X.indices[act_nnz_idx[ni]]
        if norm_factors is not None:
            gv = gv * norm_factors[gc]
        flat = np.ravel_multi_index(
            (e_local_all[ent_of_act_nnz[ni]], s_local_of_act_nnz[ni], local_of_act_nnz[ni]),
            Xb.shape,
        )
        Xb += np.bincount(flat, weights=gv, minlength=Xb.size).reshape(Xb.shape)
        if norm_shifts is not None:
            # x' = (x - shift) * factor = x*factor - shift*factor: the shift term
            # applies to every VALID (sample, observed-col) cell, zeros included.
            base = np.zeros((eb, k_pad))
            for bi, i in enumerate(members):
                cols = col_of[i]
                sh = -norm_shifts[cols]
                if norm_factors is not None:
                    sh = sh * norm_factors[cols]
                base[bi, : len(cols)] = sh
            row_valid = np.arange(s_pad)[None, :] < lens[members][:, None]
            Xb += base[:, None, :] * row_valid[:, :, None]
        buckets.append(
            EntityBucket(
                entity_rows=jnp.asarray(members.astype(np.int32)),
                X=jnp.asarray(Xb, dtype=dtype),
                labels=jnp.asarray(yb, dtype=dtype),
                weights=jnp.asarray(wb, dtype=dtype),
                sample_ids=jnp.asarray(sb),
            )
        )

    # ---- per-sample scoring view over the FULL sample axis ----------------------
    nnz_max = max(int(counts_all.max()) if n else 1, 1)
    s_cols = np.full((n, nnz_max), -1, dtype=np.int32)
    s_vals = np.zeros((n, nnz_max), dtype=np.float64)
    if n and X.nnz:
        keep = local >= 0
        s_cols[rows_per_nnz[keep], slot_per_nnz[keep]] = local[keep]
        s_vals[rows_per_nnz[keep], slot_per_nnz[keep]] = X.data[keep]

    n_active = sum(len(active_rows[e]) for e in entities)
    return RandomEffectDataset(
        re_type=re_type,
        feature_shard_id=feature_shard_id,
        entity_ids=tuple(entities),
        buckets=buckets,
        proj_indices=jnp.asarray(proj_table),
        sample_entity_rows=jnp.asarray(s_ent_rows),
        sample_local_cols=jnp.asarray(s_cols),
        sample_vals=jnp.asarray(s_vals, dtype=dtype),
        n_samples=n,
        n_active_samples=n_active,
        n_passive_samples=passive_count,
        projector=projector,
    )


def _pearson_scores(sub: sp.csr_matrix, observed: np.ndarray, y: np.ndarray) -> np.ndarray:
    """|Pearson correlation| of each observed column with the label
    (LocalDataset.computePearsonCorrelationScore semantics; constant columns,
    e.g. the intercept, get score ~1 so they are always kept — reference gives the
    intercept a pass-through score)."""
    dense = np.asarray(sub[:, observed].todense(), dtype=np.float64)
    s = len(y)
    if s <= 1:
        return np.ones(len(observed))
    xm = dense - dense.mean(axis=0, keepdims=True)
    ym = y - y.mean()
    denom = np.sqrt((xm**2).sum(axis=0) * (ym**2).sum())
    num = xm.T @ ym
    corr = np.where(denom > 0, np.abs(num / np.where(denom > 0, denom, 1.0)), 1.0)
    return corr
