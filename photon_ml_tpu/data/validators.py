"""Row-level sanity validation of training data.

Parity target: photon-client data/DataValidators.scala:1-405 — per-task validator
stacks (finite labels/offsets/weights/features for every task; binary labels for
logistic; non-negative labels for Poisson) run in VALIDATE_FULL (every row) or
VALIDATE_SAMPLE (a fraction) mode, raising on any violation. Vectorized here:
each check is one numpy reduction over the columnar batch instead of a per-row
closure.
"""

from __future__ import annotations

import enum
from typing import Optional

import numpy as np
import scipy.sparse as sp

from photon_ml_tpu.types import TaskType


class DataValidationType(str, enum.Enum):
    """DataValidationType.scala:22."""

    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    VALIDATE_DISABLED = "VALIDATE_DISABLED"


SAMPLE_FRACTION = 0.10  # reference samples a fraction of rows in SAMPLE mode


def _finite(a: np.ndarray) -> np.ndarray:
    return np.isfinite(np.asarray(a, dtype=np.float64))


def _sample_idx(n: int, mode: DataValidationType, seed: int = 0) -> Optional[np.ndarray]:
    if mode == DataValidationType.VALIDATE_FULL:
        return None  # all rows
    rng = np.random.default_rng(seed)
    k = max(1, int(n * SAMPLE_FRACTION))
    return rng.choice(n, size=k, replace=False)


def sanity_check_data(
    task: TaskType,
    labels: np.ndarray,
    offsets: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    feature_shards: Optional[dict] = None,
    validation_type: DataValidationType = DataValidationType.VALIDATE_FULL,
    seed: int = 0,
) -> None:
    """Raise ValueError listing every failed check
    (DataValidators.sanityCheckDataFrameForTraining semantics: all validators run,
    failures are collected, one error raised)."""
    validation_type = DataValidationType(validation_type)
    if validation_type == DataValidationType.VALIDATE_DISABLED:
        return
    task = TaskType(task)
    labels = np.asarray(labels, dtype=np.float64)
    n = len(labels)
    idx = _sample_idx(n, validation_type, seed)

    def view(a):
        a = np.asarray(a, dtype=np.float64)
        return a if idx is None else a[idx]

    failures: list[str] = []
    lab = view(labels)
    if not _finite(lab).all():
        failures.append("Data contains row(s) with non-finite label")
    if task.is_classification:  # logistic + smoothed hinge both need binary labels
        if not np.isin(lab[np.isfinite(lab)], (0.0, 1.0)).all():
            failures.append("Data contains row(s) with non-binary label")
    if task == TaskType.POISSON_REGRESSION:
        if (lab[np.isfinite(lab)] < 0).any():
            failures.append("Data contains row(s) with negative label")
    if offsets is not None and not _finite(view(offsets)).all():
        failures.append("Data contains row(s) with non-finite offset")
    if weights is not None:
        w = view(weights)
        if not _finite(w).all() or (w <= 0).any():
            failures.append("Data contains row(s) with non-finite or non-positive weight")
    for shard, X in (feature_shards or {}).items():
        if sp.issparse(X):
            data = X.tocsr()[idx].data if idx is not None else X.data
            ok = np.isfinite(data).all()
        else:
            ok = np.isfinite(view_matrix(X, idx)).all()
        if not ok:
            failures.append(f"Data contains row(s) with non-finite feature(s) in shard {shard!r}")
    if failures:
        raise ValueError("Data validation failed:\n  " + "\n  ".join(failures))


def view_matrix(X, idx):
    X = np.asarray(X, dtype=np.float64)
    return X if idx is None else X[idx]
