"""Feature index maps: (name, term) <-> dense column index.

Replaces the reference's IndexMap stack (photon-api index/DefaultIndexMap.scala:98,
PalDBIndexMap.scala:43-278). The reference needs off-heap PalDB stores because JVM
heaps choke on billions of feature names; here the map lives host-side only (device
code sees dense column ids), stored as a sorted name array + offsets in an .npz —
O(1) array lookup by id, binary search / dict by name. Feature hashing is available
as an alternative for extreme cardinalities.
"""

from __future__ import annotations

import os
from typing import Iterable, Optional

import numpy as np

from photon_ml_tpu.types import DELIMITER, intercept_key


def feature_key(name: str, term: str = "") -> str:
    """Canonical feature key: name + \\x01 + term (reference Constants / AvroUtils)."""
    return f"{name}{DELIMITER}{term}"


class IndexMap:
    """Bidirectional (feature key <-> index) map for one feature shard."""

    def __init__(self, names: list[str], add_intercept: bool = False):
        if add_intercept and intercept_key() not in names:
            names = list(names) + [intercept_key()]
        self._names = list(names)
        self._index = {n: i for i, n in enumerate(self._names)}
        if len(self._index) != len(self._names):
            raise ValueError("Duplicate feature keys in index map")

    @property
    def size(self) -> int:
        return len(self._names)

    @property
    def intercept_index(self) -> Optional[int]:
        return self._index.get(intercept_key())

    def get_index(self, key: str) -> int:
        """-1 for unseen features (reference IndexMap.NULL_KEY semantics)."""
        return self._index.get(key, -1)

    def get_feature_name(self, index: int) -> Optional[str]:
        return self._names[index] if 0 <= index < len(self._names) else None

    def __contains__(self, key: str) -> bool:
        return key in self._index

    def __len__(self) -> int:
        return self.size

    def keys(self):
        return list(self._names)

    # -- construction ----------------------------------------------------------

    @staticmethod
    def build(feature_keys: Iterable[str], add_intercept: bool = True) -> "IndexMap":
        """Build from observed keys, sorted for determinism (FeatureIndexingDriver
        semantics: distinct (name, term) per shard -> stable indices)."""
        distinct = sorted(set(feature_keys))
        return IndexMap(distinct, add_intercept=add_intercept)

    # -- growth ----------------------------------------------------------------

    def extend(self, feature_keys: Iterable[str]) -> "IndexMap":
        """Grown copy for incremental ingest (continuous/): every existing
        (key -> index) pair is FROZEN — previously assigned indices never move,
        so coefficient tables and persisted matrices indexed by this map stay
        aligned across growth by construction. Unseen keys append at the tail
        in sorted order (deterministic regardless of observation order).
        Returns ``self`` unchanged when nothing is new."""
        unseen = sorted(set(feature_keys) - set(self._index))
        if not unseen:
            return self
        return IndexMap(self._names + unseen)

    # -- persistence -----------------------------------------------------------

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez_compressed(path, names=np.array(self._names, dtype=object))

    @staticmethod
    def load(path: str) -> "IndexMap":
        with np.load(path if path.endswith(".npz") else path + ".npz", allow_pickle=True) as z:
            return IndexMap([str(n) for n in z["names"]])
