"""Device-resident working set for random-effect tables larger than device memory.

At ads/recsys scale the reference's random-effect tables do not fit in
accelerator memory, and every other path in this repo assumes fully
addressable ``[E, K]`` tables on device. This module supplies the missing
tier of the memory hierarchy (the Snap ML shape, arxiv 1803.06333: disk ->
host RAM -> accelerator, with importance-based selection of what occupies
the fast tier):

- **Host tier (authoritative).** :class:`WorkingSet` owns the full
  coefficient/variance tables and every entity bucket's design blocks as
  host numpy arrays. Commits are staged per pass and swapped atomically, so
  streamed device state is NEVER the only copy of a committed row — a crash
  mid-stream loses at most the in-flight pass (the chaos sweep in
  tests/test_working_set.py proves bitwise recovery through the
  ``workingset.*`` fault points below).
- **Device tier (the working set).** A row budget (``working_set_rows``)
  bounds what lives on device: RESIDENT chunks — the hottest entities,
  whose design blocks stay device-cached and whose coefficient rows stay
  device-resident across coordinate-descent passes — plus at most two
  in-flight STREAMED chunks (double buffering). Everything is accounted in
  whole chunks, so the budget check is exact:
  ``resident_rows + 2 * max_chunk_lanes <= budget_rows``.
- **Chunk scheduler.** A bucket that fits in one chunk keeps its EXACT
  entity count as the lane count — the streamed solve then runs the same
  batch shape the all-resident program gives that bucket, which is what
  carries the bitwise coefficient contract (XLA's batch-1 lowering of the
  vmapped LBFGS solve differs from batch-n by an ulp; batches >= 2 are
  probe-confirmed lane-count-stable). Buckets larger than the cap stream
  pow2-capped chunks (one lane count per bucket), so the program family is
  CLOSED: steady-state chunk rotation compiles nothing
  (``no_retrace``-gated). Padding lanes duplicate the chunk's first real
  lane (the delta path's twin-solve trick) and carry ``sample_ids = -1``
  so their score scatter drops. Coefficients and scores are bitwise-equal
  to the all-resident path; FULL variances are tolerance-bounded when a
  bucket is split (the Hessian build ``A.T @ (A*d)`` is a batched GEMM
  whose lowering is batch-count-sensitive at the last bit — see
  solver_cache.re_chunk_update_program).
- **Admission/eviction policy.** :func:`select_resident_chunks` ranks
  chunks by the max priority of their lanes — priority defaults to data
  mass (per-entity active sample count) and is overridden by the
  ``random_effect_gradient_norms`` screen and/or recency when the caller
  supplies them (continuous/active_set.py feeds both). The admission
  quantum is one chunk: residency changes rebuild device caches, never
  host state (hot rows are mirrored to the host tier every pass).
- **Overlap.** Host slicing + H2D of chunk i+1's DESIGN blocks (the large
  ``C x S x K`` transfers) runs on a
  :class:`~photon_ml_tpu.data.pipeline.BackgroundTask` while chunk i's
  solve executes — the PR 5 discipline. The small table-row transfers stay
  on the training thread, ordered harvest(i-1) -> stage-init(i) -> solve(i),
  so at most TWO chunk tables are ever live and the admission bound above is
  the true peak (chunk solves are already serialized by the score-partial
  chain, so this ordering costs no solve overlap). ``stall_seconds`` vs
  ``h2d_seconds`` quantify how much copy latency the solves actually hid
  (the bench's overlap-efficiency metric).

``peak_device_table_bytes`` is MEASURED from the live buffers this module
holds (resident rows + staged inits + pending outputs), sampled at every
chunk boundary — not modeled from the schedule. ``backend_peak_bytes``
additionally reports the backend allocator's peak where the platform
exposes ``memory_stats()`` (TPU/GPU; the CPU backend returns None).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.pipeline import BackgroundTask
from photon_ml_tpu.data.random_effect import RandomEffectDataset, _next_pow2
from photon_ml_tpu.resilience import faultpoint, register_fault_point

# Chaos-sweep fault points (tests/test_chaos.py allowlist + the dedicated
# sweep in tests/test_working_set.py): admission/eviction churn, per-chunk
# H2D staging, and the host scatter commit.
FP_ADMIT = register_fault_point("workingset.admit")
FP_EVICT = register_fault_point("workingset.evict")
FP_H2D = register_fault_point("workingset.h2d")
FP_SCATTER = register_fault_point("workingset.scatter")

# Smallest streamed lane count — the dataset builder's min entity pad, so
# chunk shapes stay inside the pow2 family the solver cache already compiles.
MIN_CHUNK_LANES = 8


def _prev_pow2(n: int, minimum: int) -> int:
    """Largest power of two <= max(n, minimum), floored at ``minimum``."""
    p = minimum
    while p * 2 <= n:
        p *= 2
    return p


def backend_peak_bytes() -> Optional[int]:
    """Peak bytes in use reported by the live backend allocator, maxed over
    local devices; None when the platform exposes no memory stats (the CPU
    backend). This is the honest-measurement primitive the benches report
    alongside the live-buffer accounting — never a modeled byte count."""
    peak = None
    for device in jax.local_devices():
        stats = getattr(device, "memory_stats", lambda: None)()
        if not stats:
            continue
        value = stats.get("peak_bytes_in_use")
        if value is not None:
            peak = value if peak is None else max(peak, value)
    return peak


def select_resident_chunks(
    chunk_priorities: np.ndarray,
    chunk_lanes: np.ndarray,
    hot_budget: int,
) -> np.ndarray:
    """Greedy chunk-granular admission: admit chunks hottest-first while the
    admitted lane count stays within ``hot_budget``. Ties break on chunk id
    (deterministic). Returns a bool mask over chunks."""
    admitted = np.zeros(len(chunk_priorities), dtype=bool)
    if hot_budget <= 0:
        return admitted
    order = np.lexsort((np.arange(len(chunk_priorities)), -chunk_priorities))
    used = 0
    for c in order:
        lanes = int(chunk_lanes[c])
        if used + lanes <= hot_budget:
            admitted[c] = True
            used += lanes
    return admitted


class _DeferredStage:
    """BackgroundTask-shaped handle that runs the stage call synchronously at
    ``result()`` time — the ``overlap=False`` schedule, where every H2D copy
    sits on the training thread's critical path."""

    def __init__(self, fn, chunk):
        self._fn = fn
        self._chunk = chunk

    def result(self, timeout=None):
        return self._fn(self._chunk)


@dataclasses.dataclass
class StreamChunk:
    """One schedulable unit: a pow2-lane slice of one bucket's entities."""

    bucket: int  # index into the dataset's bucket list
    rows: np.ndarray  # [C] int64 entity rows (padding duplicates lane 0)
    lanes: np.ndarray  # [C] int64 lane index into the bucket arrays
    real: np.ndarray  # [C] bool — False on pow2 padding lanes
    sid: np.ndarray  # [C, S] int32 sample ids; -1 on every padding lane
    priority: float  # max lane priority (admission rank)
    hot: bool = False
    # hot-tier device caches (built at admission, dropped at eviction):
    data_dev: Optional[tuple] = None  # (X, y, w, sid) device arrays
    l2_dev: Optional[object] = None
    norm_dev: Optional[tuple] = None
    # device-resident coefficient rows carried ACROSS passes (hot only);
    # None forces a re-seed from the committed host rows (first pass,
    # post-eviction readmission, or a rejected pass)
    init_dev: Optional[object] = None


class WorkingSet:
    """Host-pinned table owner + chunk scheduler + streaming pass driver.

    The coordinate (algorithm/coordinate.py) owns program resolution and the
    divergence-guard/commit decision; this class owns the tiers: which rows
    are resident, what streams when, and the authoritative host tables."""

    def __init__(
        self,
        dataset: RandomEffectDataset,
        budget_rows: int,
        dtype,
        *,
        variance_on: bool,
        l2_host: np.ndarray,
        norm_host: tuple,
        priorities=None,
        overlap: bool = True,
    ):
        E, K_all = dataset.n_entities, dataset.max_k
        self.n_entities = E
        self.k_all = K_all
        self.budget_rows = int(budget_rows)
        self.dtype = np.dtype(dtype)
        self.variance_on = bool(variance_on)
        # False serializes staging onto the training thread (stage -> solve
        # -> stage ...): the bench's unoverlapped denominator for the
        # double-buffering speedup gate. Staging is pure data movement, so
        # the toggle cannot change a single output bit.
        self.overlap = bool(overlap)
        # --- host (pinned, authoritative) tier -------------------------------
        self.host_coeffs = np.zeros((E, K_all), dtype=self.dtype)
        self.host_vars = (
            np.zeros((E, K_all), dtype=self.dtype) if variance_on else None
        )
        # one D2H per bucket moves the design blocks to the host tier; the
        # caller re-points dataset.buckets at these so the device copies free
        self.host_buckets = [jax.device_get(b) for b in dataset.buckets]
        self.l2_host = np.asarray(l2_host)
        self.norm_host = tuple(
            None
            if tbl is None
            else tuple(None if a is None else np.asarray(a) for a in tbl)
            for tbl in norm_host
        )
        # non-finite coefficients in the table tail (columns a bucket never
        # rewrites) poison the all-resident guard forever; mirror that here
        self._tail_ok = True
        # --- staging (in-flight pass) ----------------------------------------
        self._staging_coeffs: Optional[np.ndarray] = None
        self._staging_vars: Optional[np.ndarray] = None
        # --- stats -----------------------------------------------------------
        self.peak_device_table_bytes = 0
        self.h2d_seconds = 0.0
        self.stall_seconds = 0.0
        self.h2d_bytes = 0
        self.passes = 0
        self.chunks: list[StreamChunk] = []
        self.max_chunk_lanes = 0
        self._build_schedule(priorities)

    # ------------------------------------------------------------------ policy
    def _default_priorities(self) -> np.ndarray:
        """Data mass: per-entity active sample counts (free — the host tier
        already holds every bucket's sample ids)."""
        mass = np.zeros(self.n_entities, dtype=np.float64)
        for hb in self.host_buckets:
            rows = np.asarray(hb.entity_rows, dtype=np.int64)
            counts = (np.asarray(hb.sample_ids) >= 0).sum(axis=1)
            valid = rows < self.n_entities
            mass[rows[valid]] = counts[valid]
        return mass

    def _resolve_priorities(self, priorities) -> np.ndarray:
        if priorities is None:
            return self._default_priorities()
        arr = np.asarray(priorities, dtype=np.float64).reshape(-1)
        if arr.shape[0] != self.n_entities:
            raise ValueError(
                f"working-set priorities cover {arr.shape[0]} entities, "
                f"dataset has {self.n_entities}"
            )
        return arr

    @staticmethod
    def schedule_feasible(budget_rows: int, n_buckets: int) -> bool:
        """Can a double-buffered stream run inside ``budget_rows`` at all?
        The minimal schedule needs two in-flight chunks of the smallest pow2
        lane count. Callers demote (with a logged fallback) when this fails."""
        return n_buckets == 0 or budget_rows >= 2 * MIN_CHUNK_LANES

    # --------------------------------------------------------------- scheduler
    def _build_schedule(self, priorities) -> None:
        prio = self._resolve_priorities(priorities)
        chunks: list[StreamChunk] = []
        # one chunk lane count per bucket: pow2, capped so two in-flight
        # streamed chunks leave the budget's resident share intact
        cap = _prev_pow2(max(self.budget_rows // 4, MIN_CHUNK_LANES), MIN_CHUNK_LANES)
        for b, hb in enumerate(self.host_buckets):
            rows_b = np.asarray(hb.entity_rows, dtype=np.int64)
            real_rows = np.flatnonzero(rows_b < self.n_entities)
            if not len(real_rows):
                continue
            # a bucket that fits in ONE chunk keeps its exact entity count:
            # the streamed solve then runs the same batch shape the
            # all-resident program gives this bucket, which is what carries
            # the bitwise contract — XLA's batch-1 lowering of the vmapped
            # LBFGS solve differs from batch-n by an ulp, so padding a
            # 1-entity bucket to MIN_CHUNK_LANES would break parity. Split
            # buckets stream pow2-capped chunks (batch >= 2 lane-count
            # stability is probe-confirmed, tests/test_working_set.py).
            if len(real_rows) <= cap:
                c_lanes = len(real_rows)
            else:
                c_lanes = cap
            # hottest lanes first, row order breaking ties (deterministic) —
            # chunk 0 of each bucket holds the bucket's hottest entities
            order = real_rows[
                np.lexsort((real_rows, -prio[rows_b[real_rows]]))
            ]
            sid_b = np.asarray(hb.sample_ids)
            for start in range(0, len(order), c_lanes):
                sel = order[start : start + c_lanes]
                pad = c_lanes - len(sel)
                lanes = np.concatenate([sel, np.full(pad, sel[0])]) if pad else sel
                real = np.zeros(c_lanes, dtype=bool)
                real[: len(sel)] = True
                sid = sid_b[lanes].astype(np.int32)
                sid[~real] = -1  # padding lanes never score
                chunks.append(
                    StreamChunk(
                        bucket=b,
                        rows=rows_b[lanes],
                        lanes=lanes,
                        real=real,
                        sid=sid,
                        priority=float(prio[rows_b[sel]].max()),
                    )
                )
        self.max_chunk_lanes = max((len(c.rows) for c in chunks), default=0)
        hot_budget = self.budget_rows - 2 * self.max_chunk_lanes
        admitted = select_resident_chunks(
            np.asarray([c.priority for c in chunks]),
            np.asarray([len(c.rows) for c in chunks]),
            hot_budget,
        )
        for c, hot in zip(chunks, admitted):
            c.hot = bool(hot)
        # streamed (cold) chunks run first, hottest-resident last: the tail of
        # the pipeline is the cheap device-cached work, so the final D2H
        # harvests overlap it instead of trailing the pass
        chunks.sort(key=lambda c: (c.hot, -c.priority))
        self.chunks = chunks
        self._warm_hot_tier()

    def _warm_hot_tier(self) -> None:
        """Upload admitted chunks' design blocks once (the device cache that
        makes them resident). Fires ``workingset.admit`` per admission."""
        for chunk in self.chunks:
            if not chunk.hot or chunk.data_dev is not None:
                continue
            faultpoint(FP_ADMIT)
            hb = self.host_buckets[chunk.bucket]
            chunk.data_dev = (
                jnp.asarray(np.ascontiguousarray(np.asarray(hb.X)[chunk.lanes])),
                jnp.asarray(np.ascontiguousarray(np.asarray(hb.labels)[chunk.lanes])),
                jnp.asarray(np.ascontiguousarray(np.asarray(hb.weights)[chunk.lanes])),
                jnp.asarray(chunk.sid),
            )
            chunk.l2_dev = jnp.asarray(self._l2_rows(chunk))
            chunk.norm_dev = self._norm_rows(chunk, device=True)

    def reselect(self, priorities) -> None:
        """Admission/eviction churn between passes: re-rank with fresh
        priorities (recency / gradient-norm screen) and rebuild the schedule.
        Hot rows were mirrored to the host tier at every commit, so eviction
        only drops device caches — no state moves."""
        for chunk in self.chunks:
            if chunk.hot:
                faultpoint(FP_EVICT)
            chunk.data_dev = chunk.l2_dev = chunk.norm_dev = None
            chunk.init_dev = None
        self._build_schedule(priorities)

    # ----------------------------------------------------------------- seeding
    def owns(self, coeffs) -> bool:
        return coeffs is self.host_coeffs

    def seed_tables(self, coeffs: np.ndarray, variances=None) -> None:
        """Adopt a foreign warm start (checkpoint restore, external model)
        into the host tier; hot device rows are invalidated so the next pass
        re-seeds from these values."""
        arr = np.asarray(coeffs, dtype=self.dtype)
        if arr.shape != self.host_coeffs.shape:
            fresh = np.zeros_like(self.host_coeffs)
            fresh[: arr.shape[0], : arr.shape[1]] = arr[
                : fresh.shape[0], : fresh.shape[1]
            ]
            arr = fresh
        self.host_coeffs = np.array(arr, copy=True)
        if self.host_vars is not None:
            if variances is None:
                self.host_vars = np.zeros_like(self.host_vars)
            else:
                v = np.asarray(variances, dtype=self.dtype)
                if v.shape != self.host_vars.shape:
                    fresh = np.zeros_like(self.host_vars)
                    fresh[: v.shape[0], : v.shape[1]] = v[
                        : fresh.shape[0], : fresh.shape[1]
                    ]
                    v = fresh
                self.host_vars = np.array(v, copy=True)
        for chunk in self.chunks:
            chunk.init_dev = None
        self._check_tail()

    def _check_tail(self) -> None:
        """The all-resident guard checks the WHOLE table, including columns
        beyond each bucket's K that no update ever rewrites; a non-finite
        seed there must poison the streamed guard the same way."""
        ok = True
        for hb in self.host_buckets:
            K = np.asarray(hb.X).shape[2]
            if K >= self.k_all:
                continue
            rows = np.asarray(hb.entity_rows, dtype=np.int64)
            rows = rows[rows < self.n_entities]
            if not np.isfinite(self.host_coeffs[rows, K:]).all():
                ok = False
        self._tail_ok = ok

    @property
    def tail_ok(self) -> bool:
        return self._tail_ok

    # ---------------------------------------------------------------- staging
    def _l2_rows(self, chunk: StreamChunk) -> np.ndarray:
        idx = np.minimum(chunk.rows, len(self.l2_host) - 1)
        return np.ascontiguousarray(self.l2_host[idx]).astype(self.dtype)

    def _norm_rows(self, chunk: StreamChunk, device: bool = False):
        tbl = self.norm_host[chunk.bucket]
        if tbl is None:
            return None
        rows = tuple(
            None if a is None else np.ascontiguousarray(a[chunk.lanes])
            for a in tbl
        )
        if device:
            return tuple(None if a is None else jnp.asarray(a) for a in rows)
        return rows

    def _stage(self, chunk: StreamChunk) -> tuple[dict, float, int]:
        """Slice + H2D a chunk's DESIGN blocks (X/y/w/l2/norm); runs on the
        prefetch thread. Deliberately excludes the coefficient init rows:
        table rows are the budgeted resource, and staging them here would put
        a third in-flight chunk table on device (the prefetched init, the
        solving chunk's init and its output) — the init H2D is tiny (C x K vs
        the C x S x K blocks) and stays on the training thread instead
        (:meth:`_stage_init`), so at most TWO chunk tables are ever live."""
        t0 = time.perf_counter()
        faultpoint(FP_H2D)
        hb = self.host_buckets[chunk.bucket]
        moved = 0
        if chunk.hot and chunk.data_dev is not None:
            data, l2, norm = chunk.data_dev, chunk.l2_dev, chunk.norm_dev
        else:
            data = (
                jnp.asarray(np.ascontiguousarray(np.asarray(hb.X)[chunk.lanes])),
                jnp.asarray(np.ascontiguousarray(np.asarray(hb.labels)[chunk.lanes])),
                jnp.asarray(np.ascontiguousarray(np.asarray(hb.weights)[chunk.lanes])),
                jnp.asarray(chunk.sid),
            )
            l2 = jnp.asarray(self._l2_rows(chunk))
            norm = self._norm_rows(chunk, device=True)
            moved += sum(int(a.nbytes) for a in data) + int(l2.nbytes)
            if norm is not None:
                moved += sum(int(a.nbytes) for a in norm if a is not None)
        staged = {"data": data, "l2": l2, "norm": norm}
        return staged, time.perf_counter() - t0, moved

    def _stage_init(self, chunk: StreamChunk):
        """H2D one chunk's coefficient init rows on the training thread —
        AFTER the previous chunk's harvest freed its output, so the table
        tier holds at most two in-flight chunk tables (this init + the
        solve's output). Hot chunks reuse their device-resident rows."""
        if chunk.init_dev is not None:
            return chunk.init_dev
        t0 = time.perf_counter()
        hb = self.host_buckets[chunk.bucket]
        K = np.asarray(hb.X).shape[2]
        # jnp.array(copy=True), NOT jnp.asarray: this buffer is DONATED to
        # the chunk program (arg 0), and asarray may zero-copy alias the
        # host temp — donating an aliased buffer lets XLA scribble its
        # output into memory numpy can recycle mid-execution.
        init = jnp.array(self.host_coeffs[chunk.rows, :K], copy=True)
        self.h2d_seconds += time.perf_counter() - t0
        self.h2d_bytes += int(init.nbytes)
        return init

    # ------------------------------------------------------------- pass driver
    def _prefetch(self, chunk: StreamChunk):
        """Next chunk's staging handle: a :class:`BackgroundTask` when double
        buffering (H2D hides behind the current solve), or a deferred call
        that runs on the training thread at ``result()`` time when
        ``overlap=False`` — staging then serializes stage(i) -> solve(i) ->
        stage(i+1), and the whole copy lands in ``stall_seconds``."""
        if self.overlap:
            return BackgroundTask(self._stage, chunk, name="photon-ws-h2d")
        return _DeferredStage(self._stage, chunk)

    def stream_pass(self, solve_chunk: Callable, score_partial):
        """Drive one coordinate-descent pass over the chunk schedule.

        ``solve_chunk(chunk, staged, score_partial)`` dispatches the caller's
        jitted chunk program and returns ``(w_out, var_out, score_partial,
        ok, reasons, iters)``. Returns ``(score, ok_device_flag,
        reasons_parts, iters_parts, real_masks)``; the caller decides the
        commit with :meth:`commit_pass`."""
        if not self.chunks:
            raise RuntimeError("working set has no chunks to stream")
        self._staging_coeffs = np.array(self.host_coeffs, copy=True)
        self._staging_vars = (
            None if self.host_vars is None else np.array(self.host_vars, copy=True)
        )
        ok_dev = None
        reasons_parts: list = []
        iters_parts: list = []
        masks: list = []
        pending = None  # (chunk, w_out, var_out) awaiting D2H + host scatter
        prefetch = self._prefetch(self.chunks[0])
        for i, chunk in enumerate(self.chunks):
            t0 = time.perf_counter()
            # bounded join: a wedged H2D thread surfaces as a TimeoutError on
            # the training thread instead of hanging the pass forever (and
            # interpreter teardown never aborts an unbounded wait mid-dispatch)
            staged, h2d_s, moved = prefetch.result(timeout=600.0)
            self.stall_seconds += time.perf_counter() - t0
            self.h2d_seconds += h2d_s
            self.h2d_bytes += moved
            if i + 1 < len(self.chunks):
                prefetch = self._prefetch(self.chunks[i + 1])
            if pending is not None:
                # harvest BEFORE staging this chunk's init: the previous
                # output's D2H frees its rows first, so the table tier never
                # holds more than two in-flight chunk tables — the bound the
                # admission check (resident + 2 * max_chunk_lanes <= budget)
                # promises. Its solve was dispatched a full prefetch ago, so
                # this read rarely stalls.
                self._harvest(*pending)
                pending = None
            staged["init"] = self._stage_init(chunk)
            w_out, var_out, score_partial, ok, reasons, iters = solve_chunk(
                chunk, staged, score_partial
            )
            ok_dev = ok if ok_dev is None else jnp.logical_and(ok_dev, ok)
            if chunk.hot:
                # the resident tier's cross-pass warm start; a rejected pass
                # clears it back to the committed host rows (commit_pass)
                chunk.init_dev = w_out
            self._note_table_bytes(staged["init"], w_out, var_out)
            pending = (chunk, w_out, var_out)
            reasons_parts.append(reasons)
            iters_parts.append(iters)
            masks.append(chunk.real)
        self._harvest(*pending)
        self.passes += 1
        return (
            score_partial,
            ok_dev,
            tuple(reasons_parts),
            tuple(iters_parts),
            tuple(masks),
        )

    def _harvest(self, chunk: StreamChunk, w_out, var_out) -> None:
        """D2H one chunk's solved rows and scatter them into the staging
        tables (blocks on that chunk's solve — by construction the chunk
        AFTER it is already dispatched)."""
        faultpoint(FP_SCATTER)
        K = w_out.shape[1]
        real = chunk.real
        rows = chunk.rows[real]
        self._staging_coeffs[rows, :K] = np.asarray(jax.device_get(w_out))[real]
        if var_out is not None and self._staging_vars is not None:
            self._staging_vars[rows, :K] = np.asarray(jax.device_get(var_out))[real]

    def commit_pass(self, ok: bool) -> None:
        """Atomic host-tier commit: swap the staged tables in on a healthy
        pass; on a divergence reject, discard them and drop the hot tier's
        device rows so the next pass warm-starts from the committed values
        (the all-resident donated-``where`` reject, replayed host-side)."""
        if self._staging_coeffs is None:
            raise RuntimeError("commit_pass without a streamed pass in flight")
        if ok:
            self.host_coeffs = self._staging_coeffs
            if self._staging_vars is not None:
                self.host_vars = self._staging_vars
        else:
            for chunk in self.chunks:
                chunk.init_dev = None
        self._staging_coeffs = None
        self._staging_vars = None

    # ------------------------------------------------------------- scoring
    def score_streamed(self, score_program, coeffs: np.ndarray, n_samples: int,
                       view_cols, view_vals):
        """Chunked scoring for an arbitrary host table (the descent loop's
        initial score): each chunk's full-width rows go up as a C-row lane
        table through the same view kernel the all-resident score uses."""
        arr = np.asarray(coeffs, dtype=self.dtype)
        score = jnp.zeros((n_samples,), dtype=self.dtype)
        for chunk in self.chunks:
            w_rows = jnp.asarray(np.ascontiguousarray(arr[chunk.rows]))
            score = score_program(
                score, w_rows, jnp.asarray(chunk.sid), view_cols, view_vals
            )
        return score

    # ---------------------------------------------------------------- metrics
    def _note_table_bytes(self, init, w_out, var_out) -> None:
        """Sample the live table-tier buffers (measured, not modeled): the
        resident rows and the in-flight chunk's init + outputs. The previous
        chunk was harvested (and its rows freed) before this chunk's init
        staged, so these ARE the only live chunk tables."""
        live = 0
        for chunk in self.chunks:
            if chunk.init_dev is not None:
                live += int(chunk.init_dev.nbytes)
        for a in (init, w_out, var_out):
            if a is not None:
                live += int(a.nbytes)
        self.peak_device_table_bytes = max(self.peak_device_table_bytes, live)

    @property
    def budget_bytes(self) -> int:
        tables = 2 if self.variance_on else 1
        return self.budget_rows * self.k_all * self.dtype.itemsize * tables

    def overlap_efficiency(self) -> float:
        """Fraction of H2D staging time hidden behind solves: 1.0 = every
        copy fully overlapped, 0.0 = fully serialized H2D -> solve."""
        if self.h2d_seconds <= 0.0:
            return 1.0
        return max(0.0, 1.0 - self.stall_seconds / self.h2d_seconds)

    def stats(self) -> dict:
        hot_rows = sum(len(c.rows) for c in self.chunks if c.hot)
        return {
            "budget_rows": self.budget_rows,
            "budget_bytes": self.budget_bytes,
            "resident_rows": hot_rows,
            "n_chunks": len(self.chunks),
            "n_resident_chunks": sum(1 for c in self.chunks if c.hot),
            "max_chunk_lanes": self.max_chunk_lanes,
            "passes": self.passes,
            "peak_device_table_bytes": self.peak_device_table_bytes,
            "h2d_seconds": self.h2d_seconds,
            "stall_seconds": self.stall_seconds,
            "h2d_bytes": self.h2d_bytes,
            "overlap": self.overlap,
            "overlap_efficiency": self.overlap_efficiency(),
        }
