"""Pure-Python Avro object-container codec + the Photon data contracts.

The reference ships 8 Avro schemas (photon-avro-schemas/src/main/avro/, compiled to
Java) and reads/writes them through Spark + avro-mapred (photon-client
data/avro/AvroDataReader.scala, AvroUtils.scala, ModelProcessingUtils.scala). This
environment has no avro library, so this module implements the Avro 1.x binary
encoding and object-container file format directly (spec: zigzag varints, IEEE
doubles, block-structured arrays/maps, union index prefix, 'Obj\\x01' container with
deflate/null codecs) — giving byte-compatible data and model files so models can be
exchanged with the reference.

Schemas below are re-declared from the reference's .avsc contracts
(photon-avro-schemas/src/main/avro/*.avsc; see SURVEY.md §2.5).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Iterable, Iterator

MAGIC = b"Obj\x01"
SYNC_SIZE = 16
DEFAULT_SYNC = b"\x8a\x14\x1b\x90photon-tpu!!"  # 16 bytes, arbitrary but fixed
assert len(DEFAULT_SYNC) == SYNC_SIZE


# --------------------------------------------------------------------- encoding


def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(buf: io.BytesIO, n: int) -> None:
    n = _zigzag_encode(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def read_long(buf) -> int:
    shift = 0
    acc = 0
    while True:
        byte = buf.read(1)
        if not byte:
            raise EOFError("unexpected EOF in varint")
        b = byte[0]
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            return _zigzag_decode(acc)
        shift += 7


def write_bytes(buf, data: bytes) -> None:
    write_long(buf, len(data))
    buf.write(data)


def _read_exact(buf, n: int) -> bytes:
    data = buf.read(n)
    if len(data) != n:
        raise EOFError(f"truncated Avro data: wanted {n} bytes, got {len(data)}")
    return data


def read_bytes(buf) -> bytes:
    n = read_long(buf)
    if n < 0:
        raise ValueError(f"negative Avro byte-length {n} (corrupt stream)")
    return _read_exact(buf, n)


# --------------------------------------------------------------------- schema


class Schema:
    """Parsed Avro schema with a named-type registry (handles schema references)."""

    def __init__(self, schema_json):
        self.names: dict[str, Any] = {}
        self.root = self._resolve(schema_json)

    def _resolve(self, s):
        if isinstance(s, str):
            if s in ("null", "boolean", "int", "long", "float", "double", "bytes", "string"):
                return s
            for key in (s, f"com.linkedin.photon.avro.generated.{s}"):
                if key in self.names:
                    return self.names[key]
            raise ValueError(f"Unknown Avro type reference: {s}")
        if isinstance(s, list):  # union
            return ["union"] + [self._resolve(x) for x in s]
        if isinstance(s, dict):
            t = s["type"]
            if t == "record":
                namespace = s.get("namespace", "")
                fullname = f"{namespace}.{s['name']}" if namespace else s["name"]
                rec = {"type": "record", "name": s["name"], "fullname": fullname, "fields": []}
                self.names[fullname] = rec
                self.names[s["name"]] = rec
                for f in s["fields"]:
                    rec["fields"].append(
                        {"name": f["name"], "type": self._resolve(f["type"]), "default": f.get("default")}
                    )
                return rec
            if t == "array":
                return {"type": "array", "items": self._resolve(s["items"])}
            if t == "map":
                return {"type": "map", "values": self._resolve(s["values"])}
            if t in ("null", "boolean", "int", "long", "float", "double", "bytes", "string"):
                return t
            raise ValueError(f"Unsupported Avro type: {t}")
        raise ValueError(f"Bad schema node: {s!r}")


def _union_branch_index(branches, value):
    """Pick the union branch for a Python value (null/record/primitive heuristics)."""
    for i, b in enumerate(branches):
        if b == "null" and value is None:
            return i
    for i, b in enumerate(branches):
        if b == "null":
            continue
        if isinstance(b, dict) and b["type"] == "record" and isinstance(value, dict):
            return i
        if isinstance(b, dict) and b["type"] == "array" and isinstance(value, (list, tuple)):
            return i
        if isinstance(b, dict) and b["type"] == "map" and isinstance(value, dict):
            return i
        if b == "string" and isinstance(value, str):
            return i
        if b in ("double", "float") and isinstance(value, (int, float)):
            return i
        if b in ("int", "long") and isinstance(value, int):
            return i
        if b == "boolean" and isinstance(value, bool):
            return i
        if b == "bytes" and isinstance(value, bytes):
            return i
    raise ValueError(f"No union branch for {value!r} among {branches}")


def encode(buf, schema, value) -> None:
    if isinstance(schema, str):
        if schema == "null":
            return
        if schema == "boolean":
            buf.write(b"\x01" if value else b"\x00")
        elif schema in ("int", "long"):
            write_long(buf, int(value))
        elif schema == "float":
            buf.write(struct.pack("<f", float(value)))
        elif schema == "double":
            buf.write(struct.pack("<d", float(value)))
        elif schema == "string":
            write_bytes(buf, value.encode("utf-8"))
        elif schema == "bytes":
            write_bytes(buf, value)
        else:
            raise ValueError(schema)
        return
    if isinstance(schema, list):  # union
        branches = schema[1:]
        idx = _union_branch_index(branches, value)
        write_long(buf, idx)
        encode(buf, branches[idx], value)
        return
    t = schema["type"]
    if t == "record":
        for f in schema["fields"]:
            fv = value.get(f["name"], f.get("default"))
            encode(buf, f["type"], fv)
    elif t == "array":
        if value:
            write_long(buf, len(value))
            for item in value:
                encode(buf, schema["items"], item)
        write_long(buf, 0)
    elif t == "map":
        if value:
            write_long(buf, len(value))
            for k, v in value.items():
                write_bytes(buf, k.encode("utf-8"))
                encode(buf, schema["values"], v)
        write_long(buf, 0)
    else:
        raise ValueError(t)


def decode(buf, schema):
    if isinstance(schema, str):
        if schema == "null":
            return None
        if schema == "boolean":
            return _read_exact(buf, 1) == b"\x01"
        if schema in ("int", "long"):
            return read_long(buf)
        if schema == "float":
            return struct.unpack("<f", _read_exact(buf, 4))[0]
        if schema == "double":
            return struct.unpack("<d", _read_exact(buf, 8))[0]
        if schema == "string":
            return read_bytes(buf).decode("utf-8")
        if schema == "bytes":
            return read_bytes(buf)
        raise ValueError(schema)
    if isinstance(schema, list):
        idx = read_long(buf)
        return decode(buf, schema[1 + idx])
    t = schema["type"]
    if t == "record":
        return {f["name"]: decode(buf, f["type"]) for f in schema["fields"]}
    if t == "array":
        out = []
        while True:
            count = read_long(buf)
            if count == 0:
                return out
            if count < 0:
                read_long(buf)  # block byte size, unused
                count = -count
            for _ in range(count):
                out.append(decode(buf, schema["items"]))
    if t == "map":
        out = {}
        while True:
            count = read_long(buf)
            if count == 0:
                return out
            if count < 0:
                read_long(buf)
                count = -count
            for _ in range(count):
                k = read_bytes(buf).decode("utf-8")
                out[k] = decode(buf, schema["values"])
    raise ValueError(t)


# ------------------------------------------------------------ container files


def _write_container_header(f, schema_json, codec: str) -> None:
    f.write(MAGIC)
    meta_buf = io.BytesIO()
    meta = {
        "avro.schema": json.dumps(schema_json, separators=(",", ":")).encode(),
        "avro.codec": codec.encode(),
    }
    write_long(meta_buf, len(meta))
    for k, v in meta.items():
        write_bytes(meta_buf, k.encode())
        write_bytes(meta_buf, v)
    write_long(meta_buf, 0)
    f.write(meta_buf.getvalue())
    f.write(DEFAULT_SYNC)


def _write_block(f, count: int, payload: bytes, codec: str) -> None:
    if codec == "deflate":
        payload = zlib.compress(payload)[2:-4]  # raw deflate (avro strips wrapper)
    head = io.BytesIO()
    write_long(head, count)
    write_long(head, len(payload))
    f.write(head.getvalue())
    f.write(payload)
    f.write(DEFAULT_SYNC)


def write_container_raw(path: str, schema_json, blocks, codec: str = "deflate") -> None:
    """Write an Avro object-container file from PRE-ENCODED record payloads.

    ``blocks`` yields (record_count, payload_bytes) pairs — the native score
    encoder's output path (native_avro.encode_scores); framing/compression is
    the same code write_container uses."""
    with open(path, "wb") as f:
        _write_container_header(f, schema_json, codec)
        for count, payload in blocks:
            if count:
                _write_block(f, count, payload, codec)


def write_container(path: str, schema_json, records: Iterable[dict], codec: str = "deflate",
                    block_count: int = 4096) -> None:
    """Write an Avro object-container file (one or more blocks)."""
    schema = Schema(schema_json)
    with open(path, "wb") as f:
        _write_container_header(f, schema_json, codec)

        block: list[dict] = []

        def flush():
            if not block:
                return
            data_buf = io.BytesIO()
            for rec in block:
                encode(data_buf, schema.root, rec)
            _write_block(f, len(block), data_buf.getvalue(), codec)
            block.clear()

        for rec in records:
            block.append(rec)
            if len(block) >= block_count:
                flush()
        flush()


def read_container(path: str) -> Iterator[dict]:
    """Stream records from an Avro object-container file (framing shared with
    the native columnar path via iter_raw_blocks)."""
    schema = None
    for schema_json, payload, n_records in iter_raw_blocks(path):
        if schema is None:
            schema = Schema(schema_json)
        buf = io.BytesIO(payload)
        for _ in range(n_records):
            yield decode(buf, schema.root)


def iter_raw_blocks(path: str):
    """Yield (schema_json, payload: bytes, n_records) per container block with
    the codec already removed — the framing half of read_container, shared with
    the native columnar decoder (data/native_avro.py)."""
    for schema_json, codec, payload, n_records in iter_compressed_blocks(path):
        yield schema_json, inflate_block(payload, codec), n_records


def inflate_block(payload: bytes, codec: str) -> bytes:
    """Codec removal for one container block payload — split out of the
    framing walk so the parallel ingest pipeline (data/pipeline.py) can run
    inflate on worker threads (zlib releases the GIL) while the producer
    thread keeps framing."""
    if codec == "deflate":
        return zlib.decompress(payload, -15)
    if codec != "null":
        raise ValueError(f"Unsupported avro codec: {codec}")
    return payload


def iter_compressed_blocks(path: str):
    """Yield (schema_json, codec, payload: bytes, n_records) per container
    block with the payload still COMPRESSED — the sequential block-manifest
    walk of the parallel ingest pipeline. Framing errors (bad magic, negative
    counts, truncation, sync mismatch) raise here, on the framing thread."""
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        meta = {}
        while True:
            count = read_long(f)
            if count == 0:
                break
            if count < 0:
                read_long(f)
                count = -count
            for _ in range(count):
                k = read_bytes(f).decode()
                meta[k] = read_bytes(f)
        schema_json = json.loads(meta["avro.schema"].decode())
        codec = meta.get("avro.codec", b"null").decode()
        if codec not in ("deflate", "null"):
            raise ValueError(f"Unsupported avro codec: {codec}")
        sync = f.read(SYNC_SIZE)
        while True:
            try:
                n_records = read_long(f)
            except EOFError:
                return
            if n_records < 0:
                raise ValueError(f"{path}: negative record count (corrupt file)")
            payload_len = read_long(f)
            if payload_len < 0:
                raise ValueError(f"{path}: negative block size (corrupt file)")
            payload = f.read(payload_len)
            if len(payload) != payload_len:
                raise EOFError(f"{path}: truncated block ({len(payload)}/{payload_len} bytes)")
            yield schema_json, codec, payload, n_records
            block_sync = f.read(SYNC_SIZE)
            if block_sync != sync:
                raise ValueError(f"{path}: sync marker mismatch (corrupt block)")


def container_row_count(path: str) -> int:
    """Record count of one container file from the block FRAMING alone —
    payloads are seeked over, never read or decompressed, so counting a file
    costs O(blocks) seeks. Used by the multi-process drivers to compute each
    local row's position in the single-process concatenated row order (the
    down-sampling draw key) without exchanging counts between processes."""
    total = 0
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        while True:  # skip the metadata map
            count = read_long(f)
            if count == 0:
                break
            if count < 0:
                read_long(f)
                count = -count
            for _ in range(count):
                f.seek(read_long(f), 1)  # key
                f.seek(read_long(f), 1)  # value
        f.seek(SYNC_SIZE, 1)
        while True:
            try:
                n_records = read_long(f)
            except EOFError:
                return total
            if n_records < 0:
                # a corrupt count would silently shrink this file's total and
                # shift every later file's down-sampling draw-key offsets
                raise ValueError(f"{path}: negative record count (corrupt file)")
            payload_len = read_long(f)
            if payload_len < 0:
                raise ValueError(f"{path}: negative block size (corrupt file)")
            total += n_records
            f.seek(payload_len + SYNC_SIZE, 1)


def container_files(path) -> list:
    """All .avro part files under ``path``: a file, a directory of part files, a
    comma-separated string of either, or a list/tuple of paths (the reference's
    multi-path inputDataDirectories contract — part files concatenate across
    paths in the order given)."""
    if isinstance(path, (list, tuple)):
        # explicit list: items are taken verbatim (a path may contain a comma)
        paths = [str(p) for p in path if str(p)]
    else:
        paths = [p for p in str(path).split(",") if p]
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
        else:
            out.extend(
                os.path.join(p, name)
                for name in sorted(os.listdir(p))
                if name.endswith(".avro")
            )
    return out


def read_container_dir(path) -> Iterator[dict]:
    """Read all .avro files under one or more directories (the reference's
    part-file layout; accepts the same multi-path forms as container_files)."""
    for file_path in container_files(path):
        yield from read_container(file_path)


# ------------------------------------------------------- Photon data contracts
# Re-declared from the reference's photon-avro-schemas/src/main/avro/*.avsc.

NAME_TERM_VALUE_SCHEMA = {
    "name": "NameTermValueAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

FEATURE_SCHEMA = {
    "name": "FeatureAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE_SCHEMA = {
    "name": "TrainingExampleAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE_SCHEMA}},
        {"name": "metadataMap", "type": ["null", {"type": "map", "values": "string"}], "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}

BAYESIAN_LINEAR_MODEL_SCHEMA = {
    "name": "BayesianLinearModelAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE_SCHEMA}},
        {
            "name": "variances",
            "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
            "default": None,
        },
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

SCORING_RESULT_SCHEMA = {
    "name": "ScoringResultAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "metadataMap", "type": ["null", {"type": "map", "values": "string"}], "default": None},
    ],
}

RESPONSE_PREDICTION_SCHEMA = {
    "name": "SimplifiedResponsePrediction",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "response", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE_SCHEMA}},
        {"name": "weight", "type": "double", "default": 1.0},
        {"name": "offset", "type": "double", "default": 0.0},
    ],
}

FEATURE_SUMMARIZATION_SCHEMA = {
    "name": "FeatureSummarizationResultAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string"},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}

# LatentFactorAvro.avsc — matrix-factorization latent factors keyed by effect id
# (kept for wire-format completeness with the reference's 8 schemas)
LATENT_FACTOR_SCHEMA = {
    "name": "LatentFactorAvro",
    "namespace": "com.linkedin.photon.avro.generated",
    "type": "record",
    "fields": [
        {"name": "effectId", "type": "string"},
        {"name": "latentFactor", "type": {"type": "array", "items": "double"}},
    ],
}
