"""Feature normalization as affine algebra: x' = (x - shift) * factor.

Semantics from photon-lib normalization/NormalizationContext.scala:37-215 and
stat/FeatureDataStatistics.scala. The TPU design never materializes normalized data:
objectives fold the shift/factor into an effective coefficient vector
(ValueAndGradientAggregator.scala:34-80 documents the algebra), so normalization is a
pair of O(D) vector ops per optimizer iteration instead of a rewritten dataset.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.types import NormalizationType

Array = jnp.ndarray


@dataclasses.dataclass(frozen=True)
class FeatureDataStatistics:
    """Per-feature one-pass summary (photon-lib stat/FeatureDataStatistics.scala:1-139).

    All fields are length-D numpy arrays; computed host-side at ingest (a single pass,
    which on TPU is a handful of weighted segment reductions, see compute()).
    """

    count: int
    mean: np.ndarray
    variance: np.ndarray
    min: np.ndarray
    max: np.ndarray
    num_nonzeros: np.ndarray
    mean_abs: np.ndarray
    intercept_index: Optional[int] = None

    @staticmethod
    def compute(X, intercept_index: Optional[int] = None) -> "FeatureDataStatistics":
        """Compute from a [N, D] host matrix (dense ndarray or scipy sparse; the
        sparse path never densifies — zeros contribute implicitly, matching the
        reference's MultivariateOnlineSummarizer semantics)."""
        import scipy.sparse as _sp

        if _sp.issparse(X):
            return FeatureDataStatistics._compute_sparse(X.tocsc(), intercept_index)
        X = np.asarray(X)
        n = X.shape[0]
        if n == 0:
            raise ValueError("Cannot compute feature statistics over zero samples")
        return FeatureDataStatistics(
            count=n,
            mean=X.mean(axis=0),
            # Reference uses MultivariateOnlineSummarizer = sample variance (n-1).
            variance=X.var(axis=0, ddof=1) if n > 1 else np.zeros(X.shape[1]),
            min=X.min(axis=0) if n else np.zeros(X.shape[1]),
            max=X.max(axis=0) if n else np.zeros(X.shape[1]),
            num_nonzeros=(X != 0).sum(axis=0).astype(np.float64),
            mean_abs=np.abs(X).mean(axis=0),
            intercept_index=intercept_index,
        )

    @staticmethod
    def _compute_sparse(X, intercept_index: Optional[int]) -> "FeatureDataStatistics":
        n, d = X.shape
        if n == 0:
            raise ValueError("Cannot compute feature statistics over zero samples")
        nnz = np.diff(X.indptr).astype(np.float64)  # per column (csc)
        s1 = np.asarray(X.sum(axis=0)).ravel()
        s2 = np.asarray(X.multiply(X).sum(axis=0)).ravel()
        mean = s1 / n
        var = (
            (s2 - n * mean**2) / (n - 1) if n > 1 else np.zeros(d)
        )
        var = np.maximum(var, 0.0)  # guard tiny negative round-off
        # vectorized per-column min/max over stored values (reduceat needs a
        # guard for empty columns: their indptr slot would reduce the NEXT
        # column's first element, so mask them out afterwards)
        mins = np.zeros(d)
        maxs = np.zeros(d)
        nonempty = nnz > 0
        if X.nnz:
            starts = X.indptr[:-1]
            safe_starts = np.minimum(starts, X.nnz - 1)
            col_min = np.minimum.reduceat(X.data, safe_starts)
            col_max = np.maximum.reduceat(X.data, safe_starts)
            mins[nonempty] = col_min[nonempty]
            maxs[nonempty] = col_max[nonempty]
        # columns with implicit zeros include 0 in their range
        has_implicit_zero = nnz < n
        mins = np.where(has_implicit_zero, np.minimum(mins, 0.0), mins)
        maxs = np.where(has_implicit_zero, np.maximum(maxs, 0.0), maxs)
        mean_abs = np.asarray(np.abs(X).sum(axis=0)).ravel() / n
        return FeatureDataStatistics(
            count=n,
            mean=mean,
            variance=var,
            min=mins,
            max=maxs,
            num_nonzeros=nnz,
            mean_abs=mean_abs,
            intercept_index=intercept_index,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class NormalizationContext:
    """Affine transform x' = (x - shift) * factor; None means identity on that part.

    The coefficient-space conversions keep margins invariant
    (NormalizationContext.scala:73-124):
      original <- transformed:  w = w' .* factor;  b -= w_dot_shift
      transformed <- original:  b += w^T shift;    w' = w ./ factor
    If shifts are present an intercept index is required, with shift 0 / factor 1 there.

    Registered as a pytree (factors/shifts are leaves) so it can be passed as a
    TRACED argument into cached jitted solvers: one compiled program serves every
    normalization of the same structure, mirroring how the traced l2_weight lets
    regularization sweeps share a program.
    """

    factors: Optional[np.ndarray] = dataclasses.field(default=None)
    shifts: Optional[np.ndarray] = dataclasses.field(default=None)
    intercept_index: Optional[int] = dataclasses.field(
        default=None, metadata=dict(static=True)
    )

    def __post_init__(self):
        if self.shifts is not None and self.intercept_index is None:
            raise ValueError("Shift normalization requires an intercept index")
        if self.factors is not None and self.shifts is not None:
            if len(self.factors) != len(self.shifts):
                raise ValueError("Factors and shifts must have the same size")

    @property
    def is_identity(self) -> bool:
        return self.factors is None and self.shifts is None

    @property
    def size(self) -> int:
        if self.factors is not None:
            return len(self.factors)
        if self.shifts is not None:
            return len(self.shifts)
        return 0

    def padded_to(self, dim: int) -> "NormalizationContext":
        """Extend to ``dim`` features with identity entries (factor 1, shift 0)
        — used when feature-axis sharding pads the design matrix's D axis with
        all-zero columns (parallel/feature_sharded.py)."""
        if self.is_identity or self.size >= dim:
            return self
        extra = dim - self.size
        factors = (
            None
            if self.factors is None
            else np.concatenate([np.asarray(self.factors), np.ones(extra)])
        )
        shifts = (
            None
            if self.shifts is None
            else np.concatenate([np.asarray(self.shifts), np.zeros(extra)])
        )
        return NormalizationContext(
            factors=factors, shifts=shifts, intercept_index=self.intercept_index
        )

    # -- coefficient-space conversions (host-side; numpy) ---------------------------

    def model_to_original_space(self, coef: np.ndarray) -> np.ndarray:
        if self.is_identity:
            return coef
        out = np.array(coef, dtype=np.float64, copy=True)
        if self.factors is not None:
            out = out * np.asarray(self.factors)
        if self.shifts is not None:
            out[self.intercept_index] -= out.dot(np.asarray(self.shifts))
        return out

    def model_to_transformed_space(self, coef: np.ndarray) -> np.ndarray:
        if self.is_identity:
            return coef
        out = np.array(coef, dtype=np.float64, copy=True)
        if self.shifts is not None:
            out[self.intercept_index] += out.dot(np.asarray(self.shifts))
        if self.factors is not None:
            out = out / np.asarray(self.factors)
        return out

    # -- device-side model-space conversions (jnp; no host sync) --------------------

    def to_original_space_device(self, w: Array) -> Array:
        """``model_to_original_space`` for device arrays, batched over leading
        axes ([D] or [K, D]); traced jnp ops, so no device->host sync and safe
        under jit/vmap — including when the CONTEXT ITSELF is a traced jit
        argument (the fused coordinate-update programs pass it as a pytree, so
        factors/shifts may be tracers that a ``np.asarray`` round-trip would
        reject). Single source for every batched conversion site
        (problem.run, parallel/sweep.py, solver_cache update programs)."""
        if self.is_identity:
            return w
        if self.factors is not None:
            w = w * jnp.asarray(self.factors, dtype=w.dtype)
        if self.shifts is not None:
            s = jnp.asarray(self.shifts, dtype=w.dtype)
            w = w.at[..., self.intercept_index].add(-(w @ s))
        return w

    def to_transformed_space_device(self, w: Array) -> Array:
        """Inverse of :meth:`to_original_space_device` (warm starts enter the
        solver's transformed space)."""
        if self.is_identity:
            return w
        if self.shifts is not None:
            s = jnp.asarray(self.shifts, dtype=w.dtype)
            w = w.at[..., self.intercept_index].add(w @ s)
        if self.factors is not None:
            w = w / jnp.asarray(self.factors, dtype=w.dtype)
        return w

    # -- device-side effective-coefficient algebra ----------------------------------

    def effective_coefficients(self, coef: Array) -> tuple[Array, Array]:
        """(effective_coef, margin_shift) such that margin over RAW features equals
        the margin over normalized features:
          z = x'.w = x.(factor*w) - (factor*w).shift = x.eff + margin_shift
        (ValueAndGradientAggregator.init, reference :90-120)."""
        eff = coef if self.factors is None else coef * jnp.asarray(self.factors, dtype=coef.dtype)
        if self.shifts is None:
            shift = jnp.zeros((), dtype=coef.dtype)
        else:
            shift = -jnp.dot(eff, jnp.asarray(self.shifts, dtype=coef.dtype))
        return eff, shift

    def apply_to_gradient(self, vector_sum: Array, prefactor_sum: Array) -> Array:
        """grad_j = factor_j * (vector_sum_j - shift_j * prefactor_sum)
        — the gradient-space version of the same algebra (reference :55-75)."""
        g = vector_sum
        if self.shifts is not None:
            g = g - jnp.asarray(self.shifts, dtype=g.dtype) * prefactor_sum
        if self.factors is not None:
            g = g * jnp.asarray(self.factors, dtype=g.dtype)
        return g

    # -- factory (NormalizationContext.apply, reference :126-190) -------------------

    @staticmethod
    def build(
        normalization_type: NormalizationType,
        summary: Optional[FeatureDataStatistics] = None,
    ) -> "NormalizationContext":
        normalization_type = NormalizationType(normalization_type)
        if normalization_type == NormalizationType.NONE:
            return NormalizationContext()
        if summary is None:
            raise ValueError(f"{normalization_type} requires feature statistics")

        if normalization_type == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
            magnitude = np.maximum(np.abs(summary.max), np.abs(summary.min))
            factors = 1.0 / np.where(magnitude == 0.0, 1.0, magnitude)
            return NormalizationContext(factors=factors)

        std = np.sqrt(summary.variance)
        factors = 1.0 / np.where(std == 0.0, 1.0, std)

        if normalization_type == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
            return NormalizationContext(factors=factors)

        if normalization_type == NormalizationType.STANDARDIZATION:
            if summary.intercept_index is None:
                raise ValueError("STANDARDIZATION requires an intercept")
            shifts = np.array(summary.mean, copy=True)
            shifts[summary.intercept_index] = 0.0
            factors = np.array(factors, copy=True)
            factors[summary.intercept_index] = 1.0
            return NormalizationContext(
                factors=factors, shifts=shifts, intercept_index=summary.intercept_index
            )

        raise ValueError(f"NormalizationType {normalization_type} not recognized")


NO_NORMALIZATION = NormalizationContext()
