"""Incident records: graceful degradation made visible.

When the runtime survives something (a rejected divergent coordinate update, a
rolled-back corrupt checkpoint generation, a retried I/O failure) the event
must outlive the log stream: incidents ride in the coordinate-descent result
AND the checkpoint manifest, so a resumed run still knows its history and an
operator can audit what a "successful" run actually absorbed.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class Incident:
    """One survived failure. ``kind`` is a stable machine-readable class
    (``divergence``, ``checkpoint-corruption``, ``retry``); ``action`` records
    what the runtime did about it."""

    kind: str
    cause: str
    action: str
    coordinate_id: Optional[str] = None
    iteration: Optional[int] = None
    detail: Optional[str] = None

    def to_dict(self) -> dict:
        return {k: v for k, v in dataclasses.asdict(self).items() if v is not None}

    @classmethod
    def from_dict(cls, d: dict) -> "Incident":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def summary(self) -> str:
        where = ""
        if self.coordinate_id is not None:
            where = f" coordinate={self.coordinate_id}"
        if self.iteration is not None:
            where += f" iteration={self.iteration}"
        return f"[{self.kind}]{where}: {self.cause} -> {self.action}"
