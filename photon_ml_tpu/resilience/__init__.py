"""Fault-tolerant training runtime primitives.

The reference delegated every failure mode to Spark (RDD lineage + DISK_ONLY
persistence, CoordinateDescent.scala:130-160). The single-controller JAX
rebuild recovers explicitly, and this package holds the machinery that makes
recovery a *tested* property:

- :mod:`faultpoints` — deterministic fault injection (named crash sites, an
  armed plan that raises / crashes / delays / corrupts on the k-th hit)
- :mod:`retry` — bounded exponential backoff + seedable jitter
- :mod:`incidents` — durable records of survived failures
- :mod:`chaos` — the crash-at-every-fault-point / restart / bitwise-compare
  harness (the recovery proof run by tests/test_chaos.py and CI)

Consumers: io/checkpoint.py (generational integrity-checked checkpoints),
algorithm/coordinate_descent.py (divergence guard), parallel/distributed.py
(multi-host init retry). docs/ARCHITECTURE.md "Failure model & recovery"
catalogs the fault points and the incident schema.
"""

from photon_ml_tpu.resilience.chaos import (
    ChaosOutcome,
    assert_trees_identical,
    chaos_sweep,
    run_with_crash_at,
)
from photon_ml_tpu.resilience.faultpoints import (
    ENV_VAR,
    FP_ROUTER_EVICT,
    FP_ROUTER_PROBE,
    FP_ROUTER_READMIT,
    FP_ROUTER_RETRY,
    FP_ROUTER_SHED,
    FaultEntry,
    FaultPlan,
    InjectedCrash,
    InjectedFault,
    arm,
    armed,
    corrupt_file,
    disarm,
    faultpoint,
    register_fault_point,
    registered_fault_points,
)
from photon_ml_tpu.resilience.incidents import Incident
from photon_ml_tpu.resilience.retry import Retry, RetryBudget, RetryExhausted

__all__ = [
    "ChaosOutcome",
    "ENV_VAR",
    "FP_ROUTER_EVICT",
    "FP_ROUTER_PROBE",
    "FP_ROUTER_READMIT",
    "FP_ROUTER_RETRY",
    "FP_ROUTER_SHED",
    "FaultEntry",
    "FaultPlan",
    "Incident",
    "InjectedCrash",
    "InjectedFault",
    "Retry",
    "RetryBudget",
    "RetryExhausted",
    "arm",
    "armed",
    "assert_trees_identical",
    "chaos_sweep",
    "corrupt_file",
    "disarm",
    "faultpoint",
    "register_fault_point",
    "registered_fault_points",
    "run_with_crash_at",
]
