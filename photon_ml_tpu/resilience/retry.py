"""Bounded retry with exponential backoff + deterministic jitter.

Spark gave the reference free retries (task re-execution, stage re-submission,
fetch retry — SURVEY §2.8); the single-controller runtime gets an explicit,
*small* policy instead: transient I/O errors on checkpoint writes and a slow
multi-host coordinator become logged incidents with bounded retries, not
crashes. Jitter decorrelates concurrent retriers (every rank re-listing a
shared filesystem at the same instant is its own failure mode); the jitter
stream is seedable so tests can assert the exact backoff schedule.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)


class RetryExhausted(Exception):
    """All attempts failed; ``__cause__`` is the last underlying error."""


class RetryBudget:
    """Fleet-wide retry *budget*: a token bucket that caps how many retries
    the whole process may issue per second, regardless of how many requests
    want one.

    Per-request retry caps bound the damage ONE request can do; they do not
    bound the fleet. When a replica dies, every in-flight request against it
    fails at once, and if each is allowed even a single retry the surviving
    replicas absorb a synchronized wave of duplicate traffic exactly when
    capacity is lowest — the retry storm. A shared budget converts that wave
    into a bounded trickle: retries spend from one bucket refilled at
    ``rate``/s with ``burst`` of headroom, and a request that cannot get a
    token degrades to its original failure (an explicit, typed error the
    caller can shed on) instead of amplifying load.

    ``try_spend`` never blocks; ``denied`` counts the retries the budget
    refused (the router reports it in stats so a storm that WAS clamped is
    still visible). Thread-safe; the clock is injectable for tests."""

    def __init__(
        self,
        rate: float = 10.0,
        burst: float = 20.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if rate < 0:
            raise ValueError(f"retry budget rate must be >= 0, got {rate}")
        if burst <= 0:
            raise ValueError(f"retry budget burst must be > 0, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock
        self._tokens = float(burst)
        self._t = clock()
        self._lock = threading.Lock()
        self.spent = 0
        self.denied = 0

    def try_spend(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst, self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                self.spent += 1
                return True
            self.denied += 1
            return False

    def stats(self) -> dict:
        with self._lock:
            return {
                "rate": self.rate,
                "burst": self.burst,
                "spent": self.spent,
                "denied": self.denied,
            }


@dataclasses.dataclass
class Retry:
    """``delay(i) = min(max_delay, base_delay * 2**i) * (1 + jitter * u_i)``
    with ``u_i`` uniform in [0, 1). ``max_attempts`` counts the first try.

    ``max_elapsed`` is a TOTAL-deadline budget in seconds across the whole
    call — attempts plus backoff sleeps. Attempt counts alone cannot bound
    wall-clock (a slow filesystem can burn minutes inside max_attempts=3);
    operations living under an SLO window (the serving hot-swap) give both:
    the policy stops retrying as soon as the budget cannot fit the next sleep,
    and never starts an attempt past the deadline.

    ``sleep``, ``clock`` and ``seed`` are injectable so tests run under a fake
    clock with a fully deterministic schedule."""

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    jitter: float = 0.5
    retry_on: tuple = (OSError,)
    sleep: Callable[[float], None] = time.sleep
    seed: Optional[int] = None
    max_elapsed: Optional[float] = None
    clock: Callable[[], float] = time.monotonic

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.max_elapsed is not None and self.max_elapsed <= 0:
            raise ValueError(f"max_elapsed must be > 0, got {self.max_elapsed}")

    def delays(self) -> list[float]:
        """The full backoff schedule (max_attempts - 1 sleeps), deterministic
        for a given seed — what tests assert against."""
        rng = random.Random(self.seed)
        return [
            min(self.max_delay, self.base_delay * (2.0**i))
            * (1.0 + self.jitter * rng.random())
            for i in range(self.max_attempts - 1)
        ]

    def call(self, fn: Callable, *args, description: str = "", **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying on ``retry_on`` with the
        backoff schedule. Anything outside ``retry_on`` (including
        BaseExceptions like an injected crash) propagates immediately."""
        schedule = self.delays()
        what = description or getattr(fn, "__name__", "operation")
        start = self.clock()
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                last = e
                if attempt == self.max_attempts - 1:
                    break
                delay = schedule[attempt]
                if self.max_elapsed is not None:
                    elapsed = self.clock() - start
                    if elapsed + delay > self.max_elapsed:
                        raise RetryExhausted(
                            f"{what} failed after {attempt + 1} attempt(s); "
                            f"deadline budget exhausted ({elapsed:.3f}s elapsed "
                            f"+ {delay:.3f}s backoff > max_elapsed="
                            f"{self.max_elapsed:.3f}s): {last}"
                        ) from last
                logger.warning(
                    "%s failed (attempt %d/%d): %s — retrying in %.3fs",
                    what, attempt + 1, self.max_attempts, e, delay,
                )
                self.sleep(delay)
        raise RetryExhausted(
            f"{what} failed after {self.max_attempts} attempt(s): {last}"
        ) from last
