"""Deterministic fault injection: named fault points + an armed plan.

The reference inherits failure testing from Spark's own test matrix (lineage
recomputation is exercised by Spark, not by photon). A single-controller JAX
runtime has to *prove* its recovery paths instead, and proofs need replayable
failures: every interesting crash site is a named :func:`faultpoint` call, and
a :class:`FaultPlan` (armed from the ``PHOTON_FAULT_PLAN`` env var, the
``--fault-plan`` CLI flag, or a test fixture) makes the k-th hit of a chosen
point raise, crash, delay, or corrupt — the same failure, every run.

Plan grammar (comma/semicolon-separated entries)::

    <point>:<action>[:<k>[x<n>]]

    checkpoint.write.manifest:crash:2      # simulate process death, 2nd hit
    checkpoint.write.arrays:corrupt        # flip a byte in the 1st array file
    distributed.init:raise:1x2            # transient OSError on hits 1 and 2
    coord.update:delay=0.5                # sleep 0.5s on the 1st update

Actions:

- ``raise``   — raise :class:`InjectedFault` (an ``OSError``: the transient
  class retry policies recover from — arming it *tests* the retry path).
- ``crash``   — raise :class:`InjectedCrash` (a ``BaseException``: passes
  through ``except Exception`` handlers exactly like process death does; the
  chaos harness catches it at the top and restarts).
- ``corrupt`` — the fault point *returns* ``"corrupt"`` and the call site
  damages its own artifact (e.g. flips a byte in the file it just wrote);
  points that don't support corruption ignore the request.
- ``delay=S`` — sleep S seconds (armed slow-coordinator / slow-FS stalls).

Point names are hierarchical: an armed ``coord.update`` matches the dynamic
hits ``coord.update.<coordinate_id>``. Instrumented modules register their
static names (or prefixes) at import time so a chaos sweep can enumerate
every crash site without running anything.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import time
from contextlib import contextmanager
from typing import Optional

logger = logging.getLogger(__name__)

ENV_VAR = "PHOTON_FAULT_PLAN"

# every registered point/prefix, in registration order (chaos sweeps iterate it)
_REGISTRY: dict[str, None] = {}

# injectable for tests (delay actions under a fake clock)
_sleep = time.sleep


class InjectedFault(OSError):
    """A planned *transient* failure (flaky FS, slow write): retry policies
    treat it exactly like a real OSError and recover from it."""


class InjectedCrash(BaseException):
    """A planned process death. BaseException on purpose: generic ``except
    Exception`` recovery code must not be able to swallow it — only the chaos
    harness (or the top of the process) catches it."""


def register_fault_point(name: str) -> str:
    """Declare a fault point (or a dynamic-name prefix like ``coord.update``)
    at module import so :func:`registered_fault_points` can enumerate every
    crash site statically. Returns the name for assignment convenience."""
    _REGISTRY[name] = None
    return name


def registered_fault_points() -> tuple[str, ...]:
    return tuple(_REGISTRY)


# The front-router points are registered HERE rather than in
# serving/router.py: the router is the one subsystem whose failure domain is
# another PROCESS, so its crash sites must be enumerable (for the chaos
# registry-coverage gate) without importing the serving stack — the
# cross-process bench arms them in the router process while the replica
# processes run none of this instrumentation.
FP_ROUTER_PROBE = register_fault_point("serve.router.probe")
FP_ROUTER_EVICT = register_fault_point("serve.router.evict")
FP_ROUTER_READMIT = register_fault_point("serve.router.readmit")
FP_ROUTER_RETRY = register_fault_point("serve.router.retry")
FP_ROUTER_SHED = register_fault_point("serve.router.shed")


@dataclasses.dataclass
class FaultEntry:
    """One armed plan entry: fire ``action`` on hits [start, start+count)."""

    point: str
    action: str  # raise | crash | corrupt | delay
    start: int = 1  # 1-based hit index
    count: int = 1
    delay_seconds: float = 0.0
    hits: int = 0  # mutable: matching faultpoint() calls seen so far

    def matches(self, name: str) -> bool:
        return name == self.point or name.startswith(self.point + ".")


_ENTRY_RE = re.compile(
    r"^(?P<point>[\w.\-]+):(?P<action>raise|crash|corrupt|delay=(?P<secs>[0-9.]+))"
    r"(?::(?P<start>\d+)(?:x(?P<count>\d+|\*))?)?$"
)


class FaultPlan:
    """A parsed, armable set of :class:`FaultEntry`."""

    def __init__(self, entries: list[FaultEntry]):
        self.entries = entries
        self.fired: list[tuple[str, str, int]] = []  # (point name, action, hit#)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        entries = []
        for raw in re.split(r"[,;]", spec):
            raw = raw.strip()
            if not raw:
                continue
            m = _ENTRY_RE.match(raw)
            if m is None:
                raise ValueError(
                    f"Malformed fault-plan entry {raw!r} "
                    "(expected <point>:<action>[:<k>[x<n>]], action one of "
                    "raise|crash|corrupt|delay=<secs>)"
                )
            action = m.group("action")
            delay = 0.0
            if action.startswith("delay="):
                delay = float(m.group("secs"))
                action = "delay"
            count_raw = m.group("count")
            entries.append(
                FaultEntry(
                    point=m.group("point"),
                    action=action,
                    start=int(m.group("start") or 1),
                    count=(1 << 62) if count_raw == "*" else int(count_raw or 1),
                    delay_seconds=delay,
                )
            )
        return cls(entries)


_ACTIVE: Optional[FaultPlan] = None
_ENV_CHECKED = False


def arm(plan) -> FaultPlan:
    """Arm a plan (a :class:`FaultPlan` or a spec string). Replaces any
    previously armed plan; hit counters start fresh."""
    global _ACTIVE, _ENV_CHECKED
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    _ACTIVE = plan
    _ENV_CHECKED = True  # an explicit arm overrides the env var
    return plan


def disarm() -> None:
    global _ACTIVE, _ENV_CHECKED
    _ACTIVE = None
    _ENV_CHECKED = True


@contextmanager
def armed(spec: str):
    """Test fixture: arm ``spec`` for the block, restore the prior plan after."""
    global _ACTIVE, _ENV_CHECKED
    prev_active, prev_checked = _ACTIVE, _ENV_CHECKED
    plan = arm(spec)
    try:
        yield plan
    finally:
        _ACTIVE, _ENV_CHECKED = prev_active, prev_checked


def active_plan() -> Optional[FaultPlan]:
    """The armed plan, arming lazily from ``PHOTON_FAULT_PLAN`` on first use."""
    global _ACTIVE, _ENV_CHECKED
    if not _ENV_CHECKED:
        _ENV_CHECKED = True
        spec = os.environ.get(ENV_VAR)
        if spec:
            _ACTIVE = FaultPlan.parse(spec)
            logger.info("fault plan armed from $%s: %s", ENV_VAR, spec)
    return _ACTIVE


def faultpoint(name: str) -> Optional[str]:
    """Mark a crash site. Near-zero cost when nothing is armed.

    Returns ``"corrupt"`` when a corrupt action fires (the call site damages
    its own artifact); raise/crash/delay actions are handled here."""
    plan = active_plan()
    if plan is None:
        return None
    result = None
    for entry in plan.entries:
        if not entry.matches(name):
            continue
        entry.hits += 1
        k = entry.hits
        if not (entry.start <= k < entry.start + entry.count):
            continue
        plan.fired.append((name, entry.action, k))
        logger.warning("fault injected at %s: %s (hit %d)", name, entry.action, k)
        if entry.action == "raise":
            raise InjectedFault(f"injected fault at {name} (hit {k})")
        if entry.action == "crash":
            raise InjectedCrash(f"injected crash at {name} (hit {k})")
        if entry.action == "delay":
            _sleep(entry.delay_seconds)
        elif entry.action == "corrupt":
            result = "corrupt"
    return result


def corrupt_file(path: str, offset: int = -1) -> None:
    """Flip one byte of ``path`` in place (the canonical 'corrupt' handler:
    deterministic bit-rot / torn-write damage for armed fault points and
    corruption-matrix tests). ``offset`` indexes from the end when negative."""
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size == 0:
            f.write(b"\xff")
            return
        pos = offset % size
        f.seek(pos)
        byte = f.read(1)
        f.seek(pos)
        f.write(bytes([byte[0] ^ 0xFF]))
