"""Chaos harness: crash at every registered fault point, restart, compare.

The recovery proof for the fault-tolerant runtime (tests/test_chaos.py, the CI
``chaos`` job): for each registered fault point, arm a crash on its first hit,
run the training driver until it dies, then rerun it against the same
checkpoint directory — the restarted run's exported model must be *bitwise*
identical to an uninterrupted run's. Fault points that a given configuration
never reaches (e.g. ``distributed.init`` in a single-process run) complete
without crashing and must still match, which the sweep verifies for free.
"""

from __future__ import annotations

import dataclasses
import filecmp
import os
from typing import Callable, Optional

from photon_ml_tpu.resilience.faultpoints import (
    InjectedCrash,
    armed,
    registered_fault_points,
)


@dataclasses.dataclass
class ChaosOutcome:
    """One fault point's crash-restart result."""

    point: str
    crashed: bool  # False: the run never reached the armed point
    restarts: int
    crash_site: Optional[str] = None  # str(InjectedCrash) of the first death


def run_with_crash_at(
    run_once: Callable[[], object],
    point: str,
    occurrence: int = 1,
    max_restarts: int = 8,
) -> tuple[object, ChaosOutcome]:
    """Arm ``point`` to crash on its ``occurrence``-th hit, run, restart.

    ``run_once`` is one full driver invocation (it must be re-runnable against
    the same checkpoint directory — that re-runnability IS the property under
    test). The armed crash fires at most once (count=1), so the first restart
    normally completes; ``max_restarts`` bounds pathological loops."""
    with armed(f"{point}:crash:{occurrence}"):
        crash_site = None
        for restart in range(max_restarts + 1):
            try:
                result = run_once()
            except InjectedCrash as e:
                if crash_site is None:
                    crash_site = str(e)
                continue
            return result, ChaosOutcome(
                point=point,
                crashed=crash_site is not None,
                restarts=restart,
                crash_site=crash_site,
            )
    raise AssertionError(
        f"chaos: run did not complete after {max_restarts} restarts "
        f"(point {point!r}, first crash: {crash_site})"
    )


def chaos_sweep(
    run_once: Callable[[], object],
    points: Optional[tuple[str, ...]] = None,
    occurrence: int = 1,
) -> list[tuple[object, ChaosOutcome]]:
    """Crash-restart ``run_once`` at every registered fault point in sequence.
    The caller resets its output/checkpoint state between points and compares
    each completed result against an uninterrupted reference."""
    return [
        run_with_crash_at(run_once, p, occurrence=occurrence)
        for p in (points if points is not None else registered_fault_points())
    ]


def assert_trees_identical(reference: str, candidate: str) -> None:
    """Bitwise directory comparison (the chaos sweep's model-export check):
    same relative file set, every file byte-equal."""

    def walk(root):
        out = {}
        for dirpath, _, files in os.walk(root):
            for name in files:
                full = os.path.join(dirpath, name)
                out[os.path.relpath(full, root)] = full
        return out

    ref, cand = walk(reference), walk(candidate)
    if set(ref) != set(cand):
        raise AssertionError(
            f"exported trees differ in file sets: only-reference="
            f"{sorted(set(ref) - set(cand))} only-candidate="
            f"{sorted(set(cand) - set(ref))}"
        )
    diffs = [
        rel for rel in sorted(ref)
        if not filecmp.cmp(ref[rel], cand[rel], shallow=False)
    ]
    if diffs:
        raise AssertionError(f"exported files differ bitwise: {diffs}")
