"""GAME model containers: fixed-effect, random-effect, and the combined GameModel.

Mirrors photon-lib model/GameModel.scala:32-168, photon-api model/FixedEffectModel.scala
and model/RandomEffectModel.scala:36-304, re-shaped for TPU:

- FixedEffectModel: one GLM per feature shard (the reference broadcasts it; here the
  coefficients are just a replicated device array).
- RandomEffectModel: per-entity coefficient rows in a dense [E, K] matrix in each
  entity's PROJECTED feature space, plus [E, K] global-column ids (the projection).
  The reference keeps an RDD[(REId, GLM)] and scores via joins; here scoring is a
  gather + batched dot over the sample axis.
- GameModel: ordered coordinate -> model map; total score = sum of coordinate scores
  over the global sample axis.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_ml_tpu.types import ModelType, TaskType

Array = jnp.ndarray


@jax.jit
def random_effect_view_score(
    coeffs: Array, entity_rows: Array, local_cols: Array, vals: Array
) -> Array:
    """Per-sample gather/dot scoring kernel over a scoring view: score[i] =
    sum_k coeffs[entity_rows[i], local_cols[i, k]] * vals[i, k], with -1
    entity rows (no model) and -1 column slots (padding / columns the model
    never saw) contributing exactly 0. ONE shared implementation for the
    eager ``RandomEffectModel.score_dataset``, the fused serving engine
    (serving/engine.py) and the single-program coordinate update
    (solver_cache.re_coordinate_update_program), so every path executes
    identical jnp ops and stays numerically interchangeable.

    Jitted at module level ON PURPOSE: XLA contracts the multiply into the
    reduction (FMA) when this subgraph sits inside one fusion, so an
    op-by-op eager evaluation differs from any inlined/jitted one in the
    last ulp. One compiled form everywhere keeps the fused-vs-eager bitwise
    parity gates honest (jit-in-jit callers simply inline the same
    subgraph, which XLA fuses the same way — asserted by the update-program
    parity tests and the serving bench gate)."""
    has_model = entity_rows >= 0
    safe_rows = jnp.maximum(entity_rows, 0)
    w = coeffs[safe_rows]  # [N, K]
    safe_cols = jnp.maximum(local_cols, 0)
    gathered = jnp.take_along_axis(w, safe_cols, axis=1)  # [N, nnz]
    gathered = jnp.where(local_cols >= 0, gathered, 0.0)
    scores = jnp.sum(gathered * vals, axis=1)
    return jnp.where(has_model, scores, 0.0)


def _projectors_compatible(a, b) -> bool:
    """True when two RandomProjectors define the same projected space. Full
    matrix equality is O(d*k) host work on potentially huge matrices, so after
    the cheap structural checks we compare a deterministic sample of entries
    (a Gaussian matrix differing anywhere differs almost surely everywhere)."""
    if a is b:
        return True
    if a.matrix.shape != b.matrix.shape or a.intercept_index != b.intercept_index:
        return False
    d, k = a.matrix.shape
    rows = np.unique(np.linspace(0, d - 1, num=min(d, 16), dtype=np.int64))
    cols = np.unique(np.linspace(0, k - 1, num=min(k, 4), dtype=np.int64))
    if not np.array_equal(a.matrix[np.ix_(rows, cols)], b.matrix[np.ix_(rows, cols)]):
        return False
    na, nb = a.normalization, b.normalization
    if (na is None) != (nb is None):
        return False
    if na is not None:
        for fa, fb in ((na.factors, nb.factors), (na.shifts, nb.shifts)):
            if (fa is None) != (fb is None):
                return False
            if fa is not None and not np.array_equal(np.asarray(fa), np.asarray(fb)):
                return False
    return True


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Global GLM for one feature shard (FixedEffectModel.scala:146)."""

    model: GeneralizedLinearModel
    feature_shard_id: str = "global"

    @property
    def model_type(self) -> ModelType:
        return ModelType.FIXED_EFFECT

    @property
    def task(self) -> TaskType:
        return self.model.task

    def score_dataset(self, dataset) -> Array:
        """Score a FixedEffectDataset (margins WITHOUT its offsets: coordinate scores
        exclude offsets so they can be summed across coordinates)."""
        return dataset.data.X.matvec(self.model.coefficients.means)


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity GLMs as one dense coefficient matrix (RandomEffectModel.scala:36-304).

    coeffs[e] are entity e's coefficients in its projected space; proj_indices[e, k]
    is the global column id of local slot k (-1 = padding). Unseen entities score 0
    (the reference's behavior for entities without a model).
    """

    re_type: str  # entity id column, e.g. "userId"
    feature_shard_id: str
    task: TaskType
    entity_ids: tuple  # length E, position = row in coeffs
    coeffs: Array  # [E, K]
    proj_indices: Array  # [E, K] int32 global col ids, -1 pad
    variances: Optional[Array] = None  # [E, K]
    # set when coeffs live in a shared random-projection space (data/projector.py);
    # proj_indices then index PROJECTED columns, and export goes through
    # to_original_space() (RandomEffectModelInProjectedSpace.scala:151 semantics)
    projector: Optional[object] = None

    def __post_init__(self):
        object.__setattr__(self, "_row_by_entity", {e: i for i, e in enumerate(self.entity_ids)})

    @property
    def model_type(self) -> ModelType:
        return ModelType.RANDOM_EFFECT

    @property
    def n_entities(self) -> int:
        return len(self.entity_ids)

    def row_for_entity(self, entity_id) -> int:
        """-1 if the entity has no model."""
        return self._row_by_entity.get(entity_id, -1)

    def coefficients_for_entity(self, entity_id) -> Optional[np.ndarray]:
        row = self.row_for_entity(entity_id)
        return None if row < 0 else np.asarray(self.coeffs[row])

    def aligned_to(self, dataset) -> "RandomEffectModel":
        """Re-layout this model's coefficients into ``dataset``'s entity-row and
        projection-slot order. Needed when the model was loaded from disk (slot
        order = surviving means order) or trained on a different dataset build —
        without this, gathers through the dataset's local columns would read the
        wrong slots."""
        # Identity fast path: a model trained ON this dataset carries the
        # dataset's own proj_indices array and entity tuple (the warm-start
        # case inside coordinate descent, once per coordinate per iteration).
        # Object identity + tuple equality only — NO array materialization,
        # which on an accelerator would be a device->host transfer in the
        # descent hot loop.
        if self.proj_indices is dataset.proj_indices and (
            self.entity_ids is dataset.entity_ids
            or self.entity_ids == tuple(dataset.entity_ids)
        ):
            return self
        if self.entity_ids == tuple(dataset.entity_ids) and np.array_equal(
            np.asarray(self.proj_indices), np.asarray(dataset.proj_indices)
        ):
            return self
        src_proj = np.asarray(self.proj_indices)
        dst_proj = np.asarray(dataset.proj_indices)
        src = np.asarray(self.coeffs)
        src_var = None if self.variances is None else np.asarray(self.variances)
        E, K = dst_proj.shape
        out = np.zeros((E, K), dtype=src.dtype)
        out_var = None if src_var is None else np.zeros((E, K), dtype=src_var.dtype)
        # Tail-growth fast path: continuous training pins the previous
        # generation's entity order (build_random_effect_dataset(entity_order=))
        # so the old table is a row PREFIX of the grown one. Rows whose slot
        # layout is unchanged copy in one vectorized move; only entities whose
        # new rows changed their slot set (a subset of the active set) pay the
        # per-entity remap loop — keeping re-layout cost proportional to the
        # delta, not the corpus.
        n_old = len(self.entity_ids)
        Ks = src_proj.shape[1]
        rows_to_remap = range(E)
        if (
            E >= n_old
            and K >= Ks
            and tuple(dataset.entity_ids[:n_old]) == self.entity_ids
        ):
            same = (dst_proj[:n_old, :Ks] == src_proj).all(axis=1)
            if Ks < K:
                same &= (dst_proj[:n_old, Ks:] < 0).all(axis=1)
            keep = np.flatnonzero(same)
            out[keep, :Ks] = src[keep]
            if out_var is not None:
                out_var[keep, :Ks] = src_var[keep]
            # tail rows (i >= n_old) are NEW entities: no source row, stay zero
            rows_to_remap = np.flatnonzero(~same)
        for i in rows_to_remap:
            e = dataset.entity_ids[i]
            r = self.row_for_entity(e)
            if r < 0:
                continue
            col_val = {int(c): k for k, c in enumerate(src_proj[r]) if c >= 0}
            for k, c in enumerate(dst_proj[i]):
                kk = col_val.get(int(c), -1) if c >= 0 else -1
                if kk >= 0:
                    out[i, k] = src[r, kk]
                    if out_var is not None:
                        out_var[i, k] = src_var[r, kk]
        # hand back the DATASET's own entity tuple and proj array (the re-laid
        # out table matches them by construction): the next aligned_to against
        # this dataset then short-circuits on object identity instead of
        # re-materializing and comparing the [E, K] projection table
        return dataclasses.replace(
            self,
            entity_ids=tuple(dataset.entity_ids),
            coeffs=jnp.asarray(out),
            proj_indices=dataset.proj_indices,
            variances=None if out_var is None else jnp.asarray(out_var),
        )

    def score_dataset(self, dataset) -> Array:
        """Score a RandomEffectDataset-like object exposing per-sample projected
        features: ``scoring_view()`` -> (entity_rows [N], local_cols [N, nnz],
        vals [N, nnz]) where local_cols index into the DATASET's slot layout; the
        model is aligned to that layout first."""
        ds_projector = getattr(dataset, "projector", None)
        if self.projector is not None and ds_projector is None:
            # projected model vs original-space dataset: score via back-projection
            return self.to_original_space().score_dataset(dataset)
        if (
            self.projector is not None
            and ds_projector is not None
            and not _projectors_compatible(self.projector, ds_projector)
        ):
            # two DIFFERENT projections: shapes may even match, but coefficients
            # in one random basis dotted with features in another are garbage
            raise ValueError(
                "Model and dataset were built with different RandomProjectors "
                "(matrix/normalization mismatch); rebuild the scoring dataset "
                "with the model's projector (GameTransformer does this "
                "automatically)"
            )
        if self.projector is None and ds_projector is not None:
            # original-space model vs projected dataset: proj_indices would be
            # interpreted as projected slot ids — silently garbage. There is no
            # exact original->projected coefficient transport (P is not square),
            # so refuse (e.g. a loaded/back-projected model warm-starting a
            # RANDOM_PROJECTION coordinate: rebuild datasets without the
            # projector, or refit from scratch).
            raise ValueError(
                "Cannot score an original-space RandomEffectModel against a "
                "random-projection dataset; drop the coordinate's projector "
                "config or retrain the model in projected space"
            )
        model = self.aligned_to(dataset)
        entity_rows, local_cols, vals = dataset.scoring_view(model)
        return random_effect_view_score(model.coeffs, entity_rows, local_cols, vals)

    def update_entities(self, new_coeffs: Array, variances: Optional[Array] = None) -> "RandomEffectModel":
        return dataclasses.replace(self, coeffs=new_coeffs, variances=variances)

    def to_original_space(self) -> "RandomEffectModel":
        """Back-project a random-projection model into the original feature space
        (coef_orig = P @ w, margin-invariant). Per-entity coefficients become the
        entity's non-zero back-projected columns under an index-map layout, so the
        result saves/scores like any other RandomEffectModel. No-op without a
        projector. Variances don't survive (no exact linear transport through P);
        the reference likewise drops them for projected models."""
        if self.projector is None:
            return self
        E = self.n_entities
        kp = self.projector.projected_dim
        d_orig = self.projector.original_dim
        if E == 0:
            return dataclasses.replace(
                self,
                coeffs=jnp.zeros((0, 1), dtype=np.asarray(self.coeffs).dtype),
                proj_indices=jnp.full((0, 1), -1, dtype=jnp.int32),
                variances=None,
                projector=None,
            )
        proj_tbl = np.asarray(self.proj_indices)
        coeffs_src = np.asarray(self.coeffs)
        # un-pad with one vectorized scatter: slot k holds projected column
        # proj_tbl[i, k]
        W_proj = np.zeros((E, kp), dtype=coeffs_src.dtype)
        rows_idx, slots = np.nonzero(proj_tbl >= 0)
        W_proj[rows_idx, proj_tbl[rows_idx, slots]] = coeffs_src[rows_idx, slots]
        dense = self.projector.project_coefficients_back(W_proj)  # [E, d] batched
        nz = [np.flatnonzero(dense[i]) for i in range(E)]
        K = max((len(c) for c in nz), default=1) or 1
        coeffs = np.zeros((E, K), dtype=dense.dtype)
        proj = np.full((E, K), -1, dtype=np.int32)
        for i, cols in enumerate(nz):
            coeffs[i, : len(cols)] = dense[i, cols]
            proj[i, : len(cols)] = cols
        return dataclasses.replace(
            self,
            coeffs=jnp.asarray(coeffs),
            proj_indices=jnp.asarray(proj),
            variances=None,
            projector=None,
        )


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Ordered coordinateId -> model (GameModel.scala:32-168)."""

    models: Mapping[str, object]  # str -> FixedEffectModel | RandomEffectModel

    def get_model(self, coordinate_id: str):
        return self.models.get(coordinate_id)

    def update_model(self, coordinate_id: str, model) -> "GameModel":
        if coordinate_id not in self.models:
            raise KeyError(f"Unknown coordinate {coordinate_id}")
        old = self.models[coordinate_id]
        if type(old) is not type(model):
            raise TypeError(
                f"Coordinate {coordinate_id}: cannot replace {type(old).__name__} "
                f"with {type(model).__name__} (GameModel type-consistency check)"
            )
        new = dict(self.models)
        new[coordinate_id] = model
        return GameModel(models=new)

    def select(self, coordinate_ids) -> "GameModel":
        """Sub-model over a subset of coordinates, in the given order
        (the reference slices GAME models per coordinate when scoring
        sub-problems and locking coordinates for partial retrains)."""
        missing = [c for c in coordinate_ids if c not in self.models]
        if missing:
            raise KeyError(f"Unknown coordinates {missing}")
        return GameModel(models={c: self.models[c] for c in coordinate_ids})

    @property
    def coordinate_ids(self) -> list[str]:
        return list(self.models.keys())

    @property
    def task(self) -> TaskType:
        for m in self.models.values():
            return m.task
        raise ValueError("Empty GAME model")

    def __iter__(self):
        return iter(self.models.items())

    def __len__(self):
        return len(self.models)
