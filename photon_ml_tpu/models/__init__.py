from photon_ml_tpu.models.glm import (
    Coefficients,
    GeneralizedLinearModel,
    LogisticRegressionModel,
    LinearRegressionModel,
    PoissonRegressionModel,
    SmoothedHingeLossLinearSVMModel,
    model_class_for_task,
)
from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel, GameModel

__all__ = [
    "Coefficients",
    "GeneralizedLinearModel",
    "LogisticRegressionModel",
    "LinearRegressionModel",
    "PoissonRegressionModel",
    "SmoothedHingeLossLinearSVMModel",
    "model_class_for_task",
    "FixedEffectModel",
    "RandomEffectModel",
    "GameModel",
]
