"""GLM model classes: Coefficients + per-task models.

Mirrors the reference's model hierarchy — Coefficients (photon-lib
model/Coefficients.scala:31-141), GeneralizedLinearModel and its four task
subclasses (photon-api supervised/**, e.g. LogisticRegressionModel.scala:154) —
as thin pytree wrappers around jnp arrays. Scoring is a design-matrix matvec;
``predict`` applies the task's mean function (link inverse).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.data.matrix import DesignMatrix
from photon_ml_tpu.function.losses import mean_function_for_task
from photon_ml_tpu.types import TaskType

Array = jnp.ndarray


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Coefficients:
    """Model coefficients: means + optional variances (Coefficients.scala:31-141)."""

    means: Array
    variances: Optional[Array] = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def compute_score(self, X: DesignMatrix) -> Array:
        """Dot-product scores for a batch (computeScore, Coefficients.scala:53-59)."""
        return X.matvec(self.means)

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(means=jnp.zeros((dim,), dtype=dtype))


@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """A trained GLM for one task (GeneralizedLinearModel.scala:168)."""

    coefficients: Coefficients
    task: TaskType

    def score(self, data: LabeledData) -> Array:
        """Raw margin including offsets (scoring contract for coordinate descent)."""
        return data.X.matvec(self.coefficients.means) + data.offsets

    def score_features(self, X: DesignMatrix) -> Array:
        return self.coefficients.compute_score(X)

    def predict(self, X: DesignMatrix, offsets: Optional[Array] = None) -> Array:
        """Mean response: link-inverse of margin (sigmoid / identity / exp)."""
        z = self.coefficients.compute_score(X)
        if offsets is not None:
            z = z + offsets
        return mean_function_for_task(self.task)(z)

    def classify(self, X: DesignMatrix, threshold: float = 0.5) -> Array:
        if not TaskType(self.task).is_classification:
            raise ValueError(f"{self.task} is not a classification task")
        return (self.predict(X) > threshold).astype(jnp.int32)

    @property
    def dim(self) -> int:
        return self.coefficients.dim

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.coefficients.means)


class LogisticRegressionModel(GeneralizedLinearModel):
    def __init__(self, coefficients: Coefficients):
        super().__init__(coefficients, TaskType.LOGISTIC_REGRESSION)


class LinearRegressionModel(GeneralizedLinearModel):
    def __init__(self, coefficients: Coefficients):
        super().__init__(coefficients, TaskType.LINEAR_REGRESSION)


class PoissonRegressionModel(GeneralizedLinearModel):
    def __init__(self, coefficients: Coefficients):
        super().__init__(coefficients, TaskType.POISSON_REGRESSION)


class SmoothedHingeLossLinearSVMModel(GeneralizedLinearModel):
    def __init__(self, coefficients: Coefficients):
        super().__init__(coefficients, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM)


_MODEL_CLASSES = {
    TaskType.LOGISTIC_REGRESSION: LogisticRegressionModel,
    TaskType.LINEAR_REGRESSION: LinearRegressionModel,
    TaskType.POISSON_REGRESSION: PoissonRegressionModel,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SmoothedHingeLossLinearSVMModel,
}

# Reference fully-qualified class names, used in BayesianLinearModelAvro.modelClass
# for cross-framework model exchange (ModelProcessingUtils semantics).
REFERENCE_CLASS_NAMES = {
    TaskType.LOGISTIC_REGRESSION: "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    TaskType.LINEAR_REGRESSION: "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    TaskType.POISSON_REGRESSION: "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
_TASK_BY_CLASS_NAME = {v: k for k, v in REFERENCE_CLASS_NAMES.items()}


def model_class_for_task(task: TaskType):
    return _MODEL_CLASSES[TaskType(task)]


def task_for_reference_class(class_name: str) -> Optional[TaskType]:
    return _TASK_BY_CLASS_NAME.get(class_name)
