"""The GLM objective: value / gradient / Hessian products as fused array programs.

This single module replaces the reference's whole aggregator family —
ValueAndGradientAggregator.scala:34-280, HessianVectorAggregator.scala:37-173,
HessianDiagonalAggregator.scala, HessianMatrixAggregator.scala:31-129 — and the
Distributed/SingleNode objective-function split (DistributedGLMLossFunction.scala,
SingleNodeGLMLossFunction.scala). There is no distributed/local fork here: the same
jitted function runs on one chip, and under a sharded-in-data jit/shard_map the
reductions become psum over the mesh (the treeAggregate equivalent) automatically.

Normalization is folded in algebraically (never materializing normalized data):
  margins   z = X.(factor*w) - (factor*w).shift + offset
  gradient  g_j = factor_j * (X^T(w*dz)_j - shift_j * sum(w*dz))
  H.v          = factor * (X^T(w*dzz*dv) - shift * sum(w*dzz*dv)),
                 dv = X.(factor*v) - (factor*v).shift
which is exactly the effectiveCoefficients/marginShift algebra of the reference.

The objective value is sum_i w_i * l(z_i, y_i) (+ lambda/2 ||coef||^2 when l2 > 0),
matching the un-averaged reference convention. l2_weight is a traced argument so
regularization sweeps re-use one compiled program (the reference mutates
regularizationWeight for the same reason, DistributedOptimizationProblem.scala:64-75).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.function.losses import PointwiseLoss
from photon_ml_tpu.normalization import NO_NORMALIZATION, NormalizationContext

Array = jnp.ndarray


@dataclasses.dataclass(frozen=True)
class GLMObjective:
    """Pointwise loss + optional normalization + optional L2 term.

    All methods are pure and jit/vmap-compatible; ``data`` is a LabeledData pytree and
    ``coef`` lives in the *transformed* (normalized) space, as in the reference.
    """

    loss: PointwiseLoss
    normalization: NormalizationContext = NO_NORMALIZATION
    # Callers that vmap the objective (per-entity buckets, batched sweeps,
    # bootstrap) must disable the Pallas fast path: pallas_call has no batching
    # rule for this kernel, and those inner problems are the wrong regime for
    # it anyway (small D, batch axis provides the parallelism).
    allow_fused: bool = True
    # Set when the objective runs INSIDE shard_map over a sample-sharded data
    # axis: every data reduction (loss sum, gradient vector sum, prefactor
    # sums, Hessian blocks) is psum'd over this named axis before the
    # replicated algebra (L2 terms, normalization gradient transform) is
    # applied. This is what lets the opaque Pallas kernels run per-device on a
    # multi-chip mesh: each device fuses over its own [N/m, D] block and the
    # psum plays the role of GSPMD's auto-inserted all-reduce
    # (ValueAndGradientAggregator.scala:240-255's treeAggregate, made explicit).
    psum_axis: object = None

    # -- internals -------------------------------------------------------------------

    def _margins(self, data: LabeledData, coef: Array) -> Array:
        eff, margin_shift = self.normalization.effective_coefficients(coef)
        return data.X.matvec(eff) + margin_shift + data.offsets

    def _l2_value(self, coef: Array, l2_weight) -> Array:
        return 0.5 * l2_weight * jnp.dot(coef, coef)

    def _psum(self, x: Array) -> Array:
        """Cross-device data-reduction sum (identity outside shard_map)."""
        if self.psum_axis is None:
            return x
        return jax.lax.psum(x, self.psum_axis)

    @staticmethod
    def _weighted(weights: Array, x: Array) -> Array:
        """weights * x with weight-0 rows EXCLUDED rather than multiplied:
        0 * inf = NaN would otherwise let an excluded/padded row whose margin
        overflows the pointwise loss (e.g. exp in Poisson at f32) poison the
        whole reduction. Weight-0 rows appear everywhere by design: down-sampled
        negatives, padded entity buckets, weight-masked learning-curve subsets."""
        return jnp.where(weights != 0, weights * x, jnp.zeros((), dtype=x.dtype))

    # -- public API ------------------------------------------------------------------

    def value(self, data: LabeledData, coef: Array, l2_weight=0.0) -> Array:
        z = self._margins(data, coef)
        l = self.loss.loss(z, data.labels)
        data_sum = self._psum(jnp.sum(self._weighted(data.weights, l)))
        return data_sum + self._l2_value(coef, l2_weight)

    def value_and_gradient(
        self, data: LabeledData, coef: Array, l2_weight=0.0
    ) -> tuple[Array, Array]:
        fused = self._fused_value_and_gradient(data, coef, l2_weight)
        if fused is not None:
            return fused
        z = self._margins(data, coef)
        l, dz = self.loss.loss_and_dz(z, data.labels)
        wdz = self._weighted(data.weights, dz)
        value = self._psum(jnp.sum(self._weighted(data.weights, l)))
        value = value + self._l2_value(coef, l2_weight)
        vector_sum = self._psum(data.X.rmatvec(wdz))
        grad = self.normalization.apply_to_gradient(vector_sum, self._psum(jnp.sum(wdz)))
        return value, grad + l2_weight * coef

    def _fused_eligible(self, X, coef) -> bool:
        """Shared eligibility gate for the Pallas fast paths: opt-in switch on,
        dense f32/bf16 single-device problem, f32 coefficients. The
        value+gradient and HVP evaluations share exactly this decision; the
        full-Hessian path adds a tighter dimension cap on top
        (pallas_glm.MAX_HESS_DIM — its [D, D] VMEM accumulator is the binding
        constraint), so a wide NEWTON solve may fuse its gradient evaluations
        while building the Hessian through the stock lowering. That mix is
        numerically fine — every path computes the same math — the shared gate
        exists so eligibility rules evolve in one place."""
        from photon_ml_tpu.data.matrix import DenseDesignMatrix
        from photon_ml_tpu.ops import pallas_glm

        return (
            self.allow_fused
            and isinstance(X, DenseDesignMatrix)
            and X.values.ndim == 2
            and X.dtype in (jnp.float32, jnp.bfloat16)
            and coef.dtype == jnp.float32
            and pallas_glm.should_fuse(X.n_cols, per_device=self.psum_axis is not None)
        )

    def _fused_value_and_gradient(self, data: LabeledData, coef: Array, l2_weight):
        """Opt-in Pallas fast path (ops/pallas_glm.py): the two-matmul XLA
        lowering reads X from HBM twice per evaluation; the fused kernel reads
        it once. Returns None when ineligible (= stock path)."""
        from photon_ml_tpu.ops import pallas_glm

        X = data.X
        if not self._fused_eligible(X, coef):
            return None
        eff, margin_shift = self.normalization.effective_coefficients(coef)
        val, vec, wsum = pallas_glm.fused_loss_grad_sums(
            X.values,
            data.labels,
            data.offsets,
            data.weights,
            eff,
            jnp.broadcast_to(jnp.asarray(margin_shift, jnp.float32), ()),
            loss_and_dz=self.loss.loss_and_dz,
            interpret=pallas_glm.interpret_mode(),
        )
        value = self._psum(val) + self._l2_value(coef, l2_weight)
        grad = self.normalization.apply_to_gradient(self._psum(vec), self._psum(wsum))
        return value, grad + l2_weight * coef

    def _fused_hessian_vector(self, data: LabeledData, coef, vector, l2_weight):
        """Pallas fast path for the HVP (one X pass instead of three); same
        gating as _fused_value_and_gradient. TRON runs one HVP per CG step, so
        this is the hottest op of a TRON solve."""
        from photon_ml_tpu.ops import pallas_glm

        X = data.X
        if not self._fused_eligible(X, coef):
            return None
        eff, margin_shift = self.normalization.effective_coefficients(coef)
        eff_v, shift_v = self.normalization.effective_coefficients(vector)
        vec, usum = pallas_glm.fused_hessian_vector_sums(
            X.values,
            data.labels,
            data.offsets,
            data.weights,
            eff,
            jnp.asarray(margin_shift, jnp.float32),
            eff_v,
            jnp.asarray(shift_v, jnp.float32),
            dzz=self.loss.dzz,
            interpret=pallas_glm.interpret_mode(),
        )
        hv = self.normalization.apply_to_gradient(self._psum(vec), self._psum(usum))
        return hv + l2_weight * vector

    def gradient(self, data: LabeledData, coef: Array, l2_weight=0.0) -> Array:
        return self.value_and_gradient(data, coef, l2_weight)[1]

    def hessian_vector(
        self, data: LabeledData, coef: Array, vector: Array, l2_weight=0.0
    ) -> Array:
        """Gauss-Newton/true Hessian-vector product (TRON inner loop)."""
        fused = self._fused_hessian_vector(data, coef, vector, l2_weight)
        if fused is not None:
            return fused
        z = self._margins(data, coef)
        dzz = self.loss.dzz(z, data.labels)
        eff_v, shift_v = self.normalization.effective_coefficients(vector)
        dv = data.X.matvec(eff_v) + shift_v  # normalized-space directional margins
        u = self._weighted(data.weights, dzz * dv)
        vector_sum = self._psum(data.X.rmatvec(u))
        hv = self.normalization.apply_to_gradient(vector_sum, self._psum(jnp.sum(u)))
        return hv + l2_weight * vector

    def hessian_diagonal(self, data: LabeledData, coef: Array, l2_weight=0.0) -> Array:
        """diag(H) for SIMPLE variance (HessianDiagonalAggregator semantics)."""
        z = self._margins(data, coef)
        d = self._weighted(data.weights, self.loss.dzz(z, data.labels))
        sq = data.X.rmatvec_sq(d)  # sum_i d_i x_ij^2
        norm = self.normalization
        if norm.shifts is not None:
            shifts = jnp.asarray(norm.shifts, dtype=sq.dtype)
            lin = data.X.rmatvec(d)  # sum_i d_i x_ij
            sq = sq - 2.0 * shifts * lin + shifts * shifts * jnp.sum(d)
        if norm.factors is not None:
            f = jnp.asarray(norm.factors, dtype=sq.dtype)
            sq = sq * f * f
        # sq is linear in the per-sample sums, so one psum after the
        # normalization algebra equals psum-ing each constituent sum
        return self._psum(sq) + l2_weight

    def hessian_matrix(self, data: LabeledData, coef: Array, l2_weight=0.0) -> Array:
        """Full d x d Hessian for FULL variance (HessianMatrixAggregator.scala:31-129)
        and the NEWTON/direct-IRLS solvers' per-iteration build.

        Dispatches on the design matrix's storage class: dense materializes
        the normalized design (modest feature dims — the reference's FULL
        variance restriction); sparse accumulates the weighted Gram
        block-of-columns at a time (SparseDesignMatrix.gram) and applies the
        shift/factor normalization algebraically, so ``re_solver="auto"``-
        style direct selection is no longer dense-only on the FE side.
        """
        fused = self._fused_hessian_matrix(data, coef, l2_weight)
        if fused is not None:
            return fused
        z = self._margins(data, coef)
        d = self._weighted(data.weights, self.loss.dzz(z, data.labels))
        sparse = self._sparse_hessian_matrix(data.X, d, l2_weight)
        if sparse is not None:
            return sparse
        A = data.X.to_dense()
        if A.dtype == jnp.bfloat16:
            # variance math runs at the reduction dtype: applying shifts/factors
            # in bf16 would double the rounding error (cf. DenseDesignMatrix._sq)
            A = A.astype(d.dtype)
        norm = self.normalization
        if norm.shifts is not None:
            A = A - jnp.asarray(norm.shifts, dtype=A.dtype)[None, :]
        if norm.factors is not None:
            A = A * jnp.asarray(norm.factors, dtype=A.dtype)[None, :]
        H = self._psum(A.T @ (A * d[:, None]))
        return H + l2_weight * jnp.eye(H.shape[0], dtype=H.dtype)

    def _sparse_hessian_matrix(self, X, d: Array, l2_weight):
        """Sparse-storage Hessian: G = X^T diag(d) X accumulated without a
        dense [N, D] (SparseDesignMatrix.gram), then the dense branch's
        normalized-design algebra applied as rank-one corrections —
        with F = diag(factors) and shift vector s,

          H = F (G - lin s^T - s lin^T + (sum d) s s^T) F,   lin = X^T d

        which is exactly (X - 1 s^T)^T D (X - 1 s^T) scaled by F on both
        sides. Returns None for dense storage (the caller's stock path)."""
        from photon_ml_tpu.data.matrix import SparseDesignMatrix

        if not isinstance(X, SparseDesignMatrix):
            return None
        G = X.gram(d)
        if G.dtype != d.dtype:
            # variance math runs at the reduction dtype (cf. the dense branch)
            G = G.astype(d.dtype)
        norm = self.normalization
        if norm.shifts is not None:
            s = jnp.asarray(norm.shifts, dtype=G.dtype)
            lin = X.rmatvec(d)
            G = (
                G
                - lin[:, None] * s[None, :]
                - s[:, None] * lin[None, :]
                + jnp.sum(d) * (s[:, None] * s[None, :])
            )
        if norm.factors is not None:
            f = jnp.asarray(norm.factors, dtype=G.dtype)
            G = G * (f[:, None] * f[None, :])
        H = self._psum(G)
        return H + l2_weight * jnp.eye(H.shape[0], dtype=H.dtype)

    def _fused_hessian_matrix(self, data: LabeledData, coef, l2_weight):
        """Pallas fast path for the full Hessian (the NEWTON per-iteration hot
        op): one X pass, normalized rows built in VMEM instead of
        materializing the normalized design in HBM."""
        from photon_ml_tpu.ops import pallas_glm

        X = data.X
        if (
            not self._fused_eligible(X, coef)
            or X.n_cols > pallas_glm.MAX_HESS_DIM
        ):
            return None
        eff, margin_shift = self.normalization.effective_coefficients(coef)
        d = X.n_cols
        norm = self.normalization
        shifts = (
            jnp.zeros((d,), jnp.float32)
            if norm.shifts is None
            else jnp.asarray(norm.shifts, jnp.float32)
        )
        factors = (
            jnp.ones((d,), jnp.float32)
            if norm.factors is None
            else jnp.asarray(norm.factors, jnp.float32)
        )
        H = pallas_glm.fused_hessian_matrix(
            X.values,
            data.labels,
            data.offsets,
            data.weights,
            eff,
            jnp.asarray(margin_shift, jnp.float32),
            shifts,
            factors,
            dzz=self.loss.dzz,
            interpret=pallas_glm.interpret_mode(),
        )
        return self._psum(H) + l2_weight * jnp.eye(d, dtype=H.dtype)

    # -- scoring ---------------------------------------------------------------------

    def margins(self, data: LabeledData, coef: Array) -> Array:
        return self._margins(data, coef)


def make_value_and_grad(objective: GLMObjective, data: LabeledData, l2_weight=0.0):
    """Close over data: returns f(coef) -> (value, grad) for the optimizers."""

    def fn(coef: Array):
        return objective.value_and_gradient(data, coef, l2_weight)

    return fn


def make_hessian_vector(objective: GLMObjective, data: LabeledData, l2_weight=0.0):
    def fn(coef: Array, vector: Array):
        return objective.hessian_vector(data, coef, vector, l2_weight)

    return fn
