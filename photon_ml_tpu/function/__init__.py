from photon_ml_tpu.function.losses import (
    PointwiseLoss,
    logistic_loss,
    squared_loss,
    poisson_loss,
    smoothed_hinge_loss,
    loss_for_task,
)
from photon_ml_tpu.function.objective import GLMObjective

__all__ = [
    "PointwiseLoss",
    "logistic_loss",
    "squared_loss",
    "poisson_loss",
    "smoothed_hinge_loss",
    "loss_for_task",
    "GLMObjective",
]
