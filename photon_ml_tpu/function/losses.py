"""Pointwise GLM losses: l(z, y) with first and second derivatives in the margin z.

TPU-first contract: each loss exposes vectorized ``loss_and_dz(z, y) -> (l, dz)`` and
``dzz(z, y)`` over whole margin arrays, so the objective computes all per-sample
quantities in one fused elementwise pass that XLA folds into the matvec epilogue.

Semantics match the reference exactly:
- logistic: photon-api function/glm/LogisticLossFunction.scala (log1p-exp stable form)
- squared: photon-api function/glm/SquaredLossFunction.scala (1/2 (z-y)^2)
- poisson: photon-api function/glm/PoissonLossFunction.scala (exp(z) - y z)
- smoothed hinge: photon-api function/svm/SmoothedHingeLossFunction.scala:33-112
  (Rennie's smoothed hinge; piecewise quadratic; labels mapped {< 0.5 -> -1, else +1})
- the positive-response threshold 0.5 comes from MathConst.POSITIVE_RESPONSE_THRESHOLD.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp

from photon_ml_tpu.types import TaskType

Array = jnp.ndarray

POSITIVE_RESPONSE_THRESHOLD = 0.5


def _log1p_exp(x: Array) -> Array:
    # Numerically stable log(1 + exp(x)) == logaddexp(0, x).
    return jnp.logaddexp(0.0, x)


def _sigmoid(x: Array) -> Array:
    return 1.0 / (1.0 + jnp.exp(-x))


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A pointwise loss l(z, y) with dz and dzz (photon-lib PointwiseLossFunction.scala:36-54).

    ``has_hessian`` gates TwiceDiff-only optimizers (TRON): the smoothed hinge has no
    second derivative in the reference (DiffFunction only), so TRON rejects it.
    """

    name: str
    loss_and_dz: Callable[[Array, Array], tuple[Array, Array]]
    dzz: Callable[[Array, Array], Array]
    has_hessian: bool = True

    def loss(self, z: Array, y: Array) -> Array:
        return self.loss_and_dz(z, y)[0]


def _logistic_loss_and_dz(z: Array, y: Array) -> tuple[Array, Array]:
    pos = y > POSITIVE_RESPONSE_THRESHOLD
    # positive: log1pExp(-z), dz = -sigmoid(-z);  negative: log1pExp(z), dz = sigmoid(z)
    loss = jnp.where(pos, _log1p_exp(-z), _log1p_exp(z))
    dz = jnp.where(pos, -_sigmoid(-z), _sigmoid(z))
    return loss, dz


def _logistic_dzz(z: Array, y: Array) -> Array:
    s = _sigmoid(z)
    return s * (1.0 - s)


def _squared_loss_and_dz(z: Array, y: Array) -> tuple[Array, Array]:
    delta = z - y
    return delta * delta / 2.0, delta


def _squared_dzz(z: Array, y: Array) -> Array:
    return jnp.ones_like(z)


def _poisson_loss_and_dz(z: Array, y: Array) -> tuple[Array, Array]:
    pred = jnp.exp(z)
    return pred - z * y, pred - y


def _poisson_dzz(z: Array, y: Array) -> Array:
    return jnp.exp(z)


def _smoothed_hinge_loss_and_dz(z: Array, y: Array) -> tuple[Array, Array]:
    mod_label = jnp.where(y < POSITIVE_RESPONSE_THRESHOLD, -1.0, 1.0)
    zy = mod_label * z
    loss = jnp.where(zy <= 0.0, 0.5 - zy, jnp.where(zy < 1.0, 0.5 * (1.0 - zy) ** 2, 0.0))
    deriv = jnp.where(zy < 0.0, -1.0, jnp.where(zy < 1.0, zy - 1.0, 0.0))
    return loss, deriv * mod_label


def _smoothed_hinge_dzz(z: Array, y: Array) -> Array:
    # Not defined in the reference (DiffFunction only). Provide the a.e. second
    # derivative (1 on the quadratic segment) for optional quasi-Newton and the
    # direct IRLS solves. The mask is cast to z's dtype explicitly: a
    # jnp.where over two python scalars has no array to anchor its dtype and
    # silently promotes to f64 under x64 (MP001's promotion hazard — this was
    # latent until the direct solver became the first dzz consumer for hinge).
    mod_label = jnp.where(y < POSITIVE_RESPONSE_THRESHOLD, -1.0, 1.0)
    zy = mod_label * z
    return ((zy >= 0.0) & (zy < 1.0)).astype(z.dtype)


logistic_loss = PointwiseLoss("logistic", _logistic_loss_and_dz, _logistic_dzz)
squared_loss = PointwiseLoss("squared", _squared_loss_and_dz, _squared_dzz)
poisson_loss = PointwiseLoss("poisson", _poisson_loss_and_dz, _poisson_dzz)
smoothed_hinge_loss = PointwiseLoss(
    "smoothed_hinge", _smoothed_hinge_loss_and_dz, _smoothed_hinge_dzz, has_hessian=False
)

_TASK_LOSSES = {
    TaskType.LOGISTIC_REGRESSION: logistic_loss,
    TaskType.LINEAR_REGRESSION: squared_loss,
    TaskType.POISSON_REGRESSION: poisson_loss,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: smoothed_hinge_loss,
}


def loss_for_task(task: TaskType) -> PointwiseLoss:
    """Task dispatch (reference ObjectiveFunctionHelper.buildFactory:39-44)."""
    return _TASK_LOSSES[TaskType(task)]


def mean_function_for_task(task: TaskType) -> Callable[[Array], Array]:
    """Link-inverse used for predictions (reference GLM model classes, supervised/)."""
    task = TaskType(task)
    if task == TaskType.LOGISTIC_REGRESSION:
        return _sigmoid
    if task == TaskType.POISSON_REGRESSION:
        return jnp.exp
    return lambda z: z
