"""Bootstrap training: coefficient confidence intervals + metric CIs.

Parity target: photon-diagnostics BootstrapTraining.scala:30-181 and
bootstrap/BootstrapTrainingDiagnostic.scala:152. The reference trains k models
on bootstrap resamples (RDD.sample per resample) and folds per-coefficient
streaming summaries.

TPU-first design: a bootstrap resample IS a multinomial reweighting of the
sample axis — instead of materializing k resampled datasets, draw a [k, n]
matrix of multinomial counts, multiply into the base weights, and ``vmap`` the
jitted L-BFGS solve over the k axis. One XLA program trains ALL bootstrap
models simultaneously on the MXU; no data movement, no per-resample shuffles.
Non-smooth configs (L1/elastic net via OWLQN, TRON trust region) fall back to a
sequential loop over the same reweighted problems.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.function.objective import make_value_and_grad
from photon_ml_tpu.optimization.lbfgs import minimize_lbfgs
from photon_ml_tpu.optimization.problem import GLMOptimizationProblem
from photon_ml_tpu.types import OptimizerType, RegularizationType

Array = jnp.ndarray


@dataclasses.dataclass(frozen=True)
class CoefficientSummary:
    """Per-coefficient bootstrap distribution summary
    (BootstrapTraining.aggregateCoefficientConfidenceIntervals: the reference
    streams min/max/mean/var; with all k models resident we report exact
    quantiles as well)."""

    mean: float
    std: float
    min: float
    max: float
    lower_ci: float  # 2.5%
    median: float
    upper_ci: float  # 97.5%

    def interval_contains_zero(self) -> bool:
        return self.lower_ci <= 0.0 <= self.upper_ci


@dataclasses.dataclass(frozen=True)
class BootstrapReport:
    """bootstrap/BootstrapReport.scala: per-coefficient summaries + per-metric
    distributions over the bootstrap models."""

    coefficient_summaries: list  # [d] CoefficientSummary
    metric_distributions: dict  # metric name -> CoefficientSummary over k values
    num_models: int
    coefficients: np.ndarray  # [k, d] raw bootstrap coefficients


def _summary(values: np.ndarray) -> CoefficientSummary:
    lo, med, hi = np.percentile(values, [2.5, 50.0, 97.5])
    return CoefficientSummary(
        mean=float(values.mean()),
        std=float(values.std(ddof=1)) if len(values) > 1 else 0.0,
        min=float(values.min()),
        max=float(values.max()),
        lower_ci=float(lo),
        median=float(med),
        upper_ci=float(hi),
    )


def bootstrap_training(
    problem: GLMOptimizationProblem,
    data: LabeledData,
    num_bootstraps: int = 10,
    seed: int = 0,
    metrics: Optional[dict[str, Callable]] = None,
    use_vmap: Optional[bool] = None,
) -> BootstrapReport:
    """Train ``num_bootstraps`` models on multinomial-reweighted resamples.

    metrics: {name: fn(scores, labels, weights) -> float} evaluated per model on
    the FULL dataset (the reference evaluates each bootstrap model with its
    metric map and aggregates).

    use_vmap: None (default) auto-selects the vmapped L-BFGS fast path for
    smooth configs; True forces it (error if the config is non-smooth); False
    forces the sequential per-resample loop — same resample weights, so the two
    paths are directly comparable.
    """
    if num_bootstraps < 2:
        raise ValueError("need at least 2 bootstrap resamples")
    n = data.n
    rng = np.random.default_rng(seed)
    counts = rng.multinomial(n, np.full(n, 1.0 / n), size=num_bootstraps)  # [k, n]
    base_w = np.asarray(data.weights)
    weight_matrix = jnp.asarray(counts * base_w[None, :], dtype=data.weights.dtype)

    cfg = problem.configuration
    opt_type = OptimizerType(cfg.optimizer_config.optimizer_type)
    reg_type = cfg.regularization_context.regularization_type
    smooth = opt_type == OptimizerType.LBFGS and reg_type in (
        RegularizationType.NONE,
        RegularizationType.L2,
    )
    if use_vmap and not smooth:
        raise ValueError(
            "use_vmap=True requires a smooth config (LBFGS with NONE/L2 reg)"
        )
    if use_vmap is not None:
        smooth = use_vmap

    if smooth:
        obj = dataclasses.replace(problem.objective, allow_fused=False)  # vmapped
        l2 = cfg.l2_weight

        def solve(weights: Array) -> Array:
            d = dataclasses.replace(data, weights=weights)
            vg = make_value_and_grad(obj, d, l2)
            return minimize_lbfgs(
                vg,
                jnp.zeros(data.dim, dtype=weight_matrix.dtype),
                max_iterations=cfg.optimizer_config.max_iterations,
                tolerance=cfg.optimizer_config.tolerance,
                history_length=cfg.optimizer_config.history_length,
            ).coefficients

        coeffs = np.asarray(jax.jit(jax.vmap(solve))(weight_matrix))  # [k, d]
    else:
        rows = []
        for k in range(num_bootstraps):
            d = dataclasses.replace(data, weights=weight_matrix[k])
            glm, _ = problem.run(d)
            rows.append(np.asarray(glm.coefficients.means))
        coeffs = np.stack(rows)

    summaries = [_summary(coeffs[:, j]) for j in range(coeffs.shape[1])]

    metric_dists: dict[str, CoefficientSummary] = {}
    if metrics:
        labels = np.asarray(data.labels)
        weights = np.asarray(data.weights)
        offsets = np.asarray(data.offsets)
        scores = np.stack(
            [np.asarray(data.X.matvec(jnp.asarray(coeffs[k]))) for k in range(num_bootstraps)]
        )  # [k, n]
        for name, fn in metrics.items():
            vals = np.array(
                [fn(scores[k] + offsets, labels, weights) for k in range(num_bootstraps)]
            )
            metric_dists[name] = _summary(vals)

    return BootstrapReport(
        coefficient_summaries=summaries,
        metric_distributions=metric_dists,
        num_models=num_bootstraps,
        coefficients=coeffs,
    )
