"""Fitting diagnostic: learning curves (metric vs training-set fraction).

Parity target: photon-diagnostics fitting/FittingDiagnostic.scala:30-131 — tag
samples into NUM_TRAINING_PARTITIONS random partitions, hold the last out,
train on growing prefixes (1/8, 2/8, ... 7/8) with warm start carried between
portions, and record each metric on both the training prefix and the holdout.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping, Optional

import numpy as np

from photon_ml_tpu.data.dataset import LabeledData

NUM_TRAINING_PARTITIONS = 8
MIN_SAMPLES_PER_PARTITION_PER_DIMENSION = 10


@dataclasses.dataclass(frozen=True)
class FittingReport:
    """fitting/FittingReport.scala: per-metric learning curves.

    metrics: {metric name: (portions [%], train values, holdout values)}
    """

    metrics: dict
    message: str = ""


def fitting_diagnostic(
    data: LabeledData,
    model_factory: Callable,
    metrics: Mapping[str, Callable],
    seed: int = 0,
    num_partitions: int = NUM_TRAINING_PARTITIONS,
) -> FittingReport:
    """model_factory(subset: LabeledData, warm_start) -> (model, warm_start');
    metrics: {name: fn(scores, labels, weights) -> float}. The returned model
    must expose .score(LabeledData) -> margins (GeneralizedLinearModel API).

    Returns an empty report when the dataset is too small for stable curves
    (FittingDiagnostic returns an empty map below dimension *
    MIN_SAMPLES_PER_PARTITION_PER_DIMENSION samples)."""
    n = data.n
    min_samples = data.dim * MIN_SAMPLES_PER_PARTITION_PER_DIMENSION
    if n <= min_samples:
        return FittingReport(
            metrics={},
            message=(
                f"insufficient data for learning curves: {n} samples <= "
                f"{min_samples} (dim * {MIN_SAMPLES_PER_PARTITION_PER_DIMENSION})"
            ),
        )

    rng = np.random.default_rng(seed)
    tags = rng.integers(0, num_partitions, size=n)
    holdout_idx = np.flatnonzero(tags == num_partitions - 1)
    holdout = _subset(data, holdout_idx)

    portions: list[float] = []
    train_vals: dict[str, list[float]] = {m: [] for m in metrics}
    test_vals: dict[str, list[float]] = {m: [] for m in metrics}
    warm = None
    for max_tag in range(num_partitions - 1):
        idx = np.flatnonzero(tags <= max_tag)
        subset = _subset(data, idx)
        portions.append(100.0 * len(idx) / n)
        model, warm = model_factory(subset, warm)
        train_scores = np.asarray(model.score(subset))
        test_scores = np.asarray(model.score(holdout))
        for name, fn in metrics.items():
            train_vals[name].append(
                float(fn(train_scores, np.asarray(subset.labels), np.asarray(subset.weights)))
            )
            test_vals[name].append(
                float(fn(test_scores, np.asarray(holdout.labels), np.asarray(holdout.weights)))
            )

    return FittingReport(
        metrics={
            name: (portions, train_vals[name], test_vals[name]) for name in metrics
        }
    )


def _subset(data: LabeledData, idx: np.ndarray) -> LabeledData:
    import jax.numpy as jnp

    from photon_ml_tpu.data.dataset import LabeledData as LD

    X = data.X
    # DesignMatrix variants: use the underlying host matrix when available
    take = getattr(X, "take_rows", None)
    if take is not None:
        sub_X = take(idx)
    else:
        raise TypeError(
            f"{type(X).__name__} does not support row subsetting (take_rows)"
        )
    return LD(
        X=sub_X,
        labels=jnp.asarray(np.asarray(data.labels)[idx]),
        offsets=jnp.asarray(np.asarray(data.offsets)[idx]),
        weights=jnp.asarray(np.asarray(data.weights)[idx]),
    )
