"""Fitting diagnostic: learning curves (metric vs training-set fraction).

Parity target: photon-diagnostics fitting/FittingDiagnostic.scala:30-131 — tag
samples into NUM_TRAINING_PARTITIONS random partitions, hold the last out,
train on growing prefixes (1/8, 2/8, ... 7/8) with warm start carried between
portions, and record each metric on both the training prefix and the holdout.

TPU-first shape discipline: the reference trains on physically growing RDD
subsets; here every portion trains on the SAME full-shape arrays with the
excluded rows' weights zeroed. The weighted GLM objective is indifferent to
weight-0 rows, so the result is identical — but every portion (and every other
same-shaped solve in the process) reuses ONE compiled XLA program instead of
recompiling per subset shape.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping

import numpy as np

from photon_ml_tpu.data.dataset import LabeledData

NUM_TRAINING_PARTITIONS = 8
MIN_SAMPLES_PER_PARTITION_PER_DIMENSION = 10


@dataclasses.dataclass(frozen=True)
class FittingReport:
    """fitting/FittingReport.scala: per-metric learning curves.

    metrics: {metric name: (portions [%], train values, holdout values)}
    """

    metrics: dict
    message: str = ""


def fitting_diagnostic(
    data: LabeledData,
    model_factory: Callable,
    metrics: Mapping[str, Callable],
    seed: int = 0,
    num_partitions: int = NUM_TRAINING_PARTITIONS,
) -> FittingReport:
    """model_factory(subset: LabeledData, warm_start) -> (model, warm_start');
    metrics: {name: fn(scores, labels, weights) -> float}. The returned model
    must expose .score(LabeledData) -> margins (GeneralizedLinearModel API).

    The ``subset`` handed to the factory is the full-shape dataset with
    excluded rows' weights set to 0 (weighted training ignores them); metric
    values are computed on the genuinely-included rows only.

    Returns an empty report when the dataset is too small for stable curves
    (FittingDiagnostic returns an empty map below dimension *
    MIN_SAMPLES_PER_PARTITION_PER_DIMENSION samples)."""
    import jax.numpy as jnp

    n = data.n
    min_samples = data.dim * MIN_SAMPLES_PER_PARTITION_PER_DIMENSION
    if n <= min_samples:
        return FittingReport(
            metrics={},
            message=(
                f"insufficient data for learning curves: {n} samples <= "
                f"{min_samples} (dim * {MIN_SAMPLES_PER_PARTITION_PER_DIMENSION})"
            ),
        )

    rng = np.random.default_rng(seed)
    tags = rng.integers(0, num_partitions, size=n)
    holdout_idx = np.flatnonzero(tags == num_partitions - 1)
    labels_np = np.asarray(data.labels)
    weights_np = np.asarray(data.weights)

    portions: list[float] = []
    train_vals: dict[str, list[float]] = {m: [] for m in metrics}
    test_vals: dict[str, list[float]] = {m: [] for m in metrics}
    warm = None
    for max_tag in range(num_partitions - 1):
        mask = tags <= max_tag
        idx = np.flatnonzero(mask)
        portions.append(100.0 * len(idx) / n)
        masked = LabeledData(
            X=data.X,
            labels=data.labels,
            offsets=data.offsets,
            weights=jnp.asarray(
                np.where(mask, weights_np, 0.0), dtype=data.weights.dtype
            ),
        )
        model, warm = model_factory(masked, warm)
        scores = np.asarray(model.score(data))  # full shape: one compiled matvec
        for name, fn in metrics.items():
            train_vals[name].append(
                float(fn(scores[idx], labels_np[idx], weights_np[idx]))
            )
            test_vals[name].append(
                float(
                    fn(
                        scores[holdout_idx],
                        labels_np[holdout_idx],
                        weights_np[holdout_idx],
                    )
                )
            )

    return FittingReport(
        metrics={
            name: (portions, train_vals[name], test_vals[name]) for name in metrics
        }
    )
