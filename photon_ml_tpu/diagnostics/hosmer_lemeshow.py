"""Hosmer-Lemeshow calibration test for logistic models.

Parity target: photon-diagnostics hl/*.scala —
- bin count heuristic: min(numDimensions + 2, 0.9*sqrt(n) + 0.9*log1p(n))
  (DefaultPredictedProbabilityVersusObservedFrequencyBinner.scala:55-61; both
  heuristic terms use factor A = 0.9, matching the reference's code as written)
- uniform probability bins over [0, 1); each bin counts observed positives /
  negatives; expected positives = ceil(total * bin midpoint probability)
  (PredictedProbabilityVersusObservedFrequencyHistogramBin.scala:51-64)
- chi^2 = sum over bins of (obs-exp)^2/exp for pos and neg sides, d.o.f. =
  bins - 2, plus cumulative probability and standard confidence cutoffs
  (HosmerLemeshowDiagnostic.scala:47-95).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import numpy as np
from scipy import stats

STANDARD_CONFIDENCE_LEVELS = (
    0.000001, 0.01, 0.05, 0.1, 0.2, 0.3, 0.4, 0.5,
    0.6, 0.7, 0.8, 0.9, 0.95, 0.99, 0.999999,
)
MINIMUM_EXPECTED_IN_BUCKET = 5
DATA_HEURISTIC_FACTOR_A = 0.9


@dataclasses.dataclass(frozen=True)
class HistogramBin:
    lower_bound: float
    upper_bound: float
    observed_pos: int
    observed_neg: int

    @property
    def total(self) -> int:
        return self.observed_pos + self.observed_neg

    @property
    def expected_pos(self) -> int:
        mid = (self.lower_bound + self.upper_bound) / 2.0
        return int(math.ceil(self.total * mid))

    @property
    def expected_neg(self) -> int:
        return self.total - self.expected_pos


@dataclasses.dataclass(frozen=True)
class HosmerLemeshowReport:
    """hl/HosmerLemeshowReport.scala."""

    bins: list
    chi_squared: float
    degrees_of_freedom: int
    chi_squared_prob: float  # P(X^2 <= observed) — high means poor calibration
    cutoffs: list  # (confidence level, chi^2 cutoff)
    warnings: list

    @property
    def p_value(self) -> float:
        """P(X^2 >= observed) under H0 (well calibrated)."""
        return 1.0 - self.chi_squared_prob


def default_bin_count(num_samples: int, num_dimensions: int) -> int:
    from_dims = num_dimensions + 2
    from_data = int(
        DATA_HEURISTIC_FACTOR_A * math.sqrt(num_samples)
        + DATA_HEURISTIC_FACTOR_A * math.log1p(num_samples)
    )
    return max(3, min(from_data, from_dims))


def hosmer_lemeshow_test(
    predicted_probabilities: np.ndarray,
    labels: np.ndarray,
    num_bins: Optional[int] = None,
    num_dimensions: Optional[int] = None,
) -> HosmerLemeshowReport:
    """Run the HL test on predicted P(y=1) vs binary labels."""
    p = np.asarray(predicted_probabilities, dtype=np.float64)
    y = np.asarray(labels, dtype=np.float64) > 0.5
    if np.any((p < 0) | (p > 1)):
        raise ValueError("predicted probabilities must be in [0, 1]")
    n = len(p)
    if num_bins is None:
        num_bins = default_bin_count(n, num_dimensions if num_dimensions is not None else 1)

    edges = np.linspace(0.0, 1.0, num_bins + 1)
    # values == 1.0 belong to the last bin (upper bounds exclusive elsewhere)
    idx = np.minimum(np.digitize(p, edges[1:-1], right=False), num_bins - 1)
    bins = []
    warnings = []
    chi2 = 0.0
    for b in range(num_bins):
        mask = idx == b
        hb = HistogramBin(
            lower_bound=float(edges[b]),
            upper_bound=float(edges[b + 1]),
            observed_pos=int(y[mask].sum()),
            observed_neg=int((~y[mask]).sum()),
        )
        bins.append(hb)
        if hb.expected_pos > 0:
            chi2 += (hb.observed_pos - hb.expected_pos) ** 2 / hb.expected_pos
        if hb.expected_pos and hb.expected_pos < MINIMUM_EXPECTED_IN_BUCKET:
            warnings.append(
                f"bin [{hb.lower_bound:.3f}, {hb.upper_bound:.3f}): expected positive "
                f"count {hb.expected_pos} too small for a sound chi^2 estimate"
            )
        if hb.expected_neg > 0:
            chi2 += (hb.observed_neg - hb.expected_neg) ** 2 / hb.expected_neg
        if hb.expected_neg and hb.expected_neg < MINIMUM_EXPECTED_IN_BUCKET:
            warnings.append(
                f"bin [{hb.lower_bound:.3f}, {hb.upper_bound:.3f}): expected negative "
                f"count {hb.expected_neg} too small for a sound chi^2 estimate"
            )

    dof = max(1, num_bins - 2)
    dist = stats.chi2(dof)
    return HosmerLemeshowReport(
        bins=bins,
        chi_squared=float(chi2),
        degrees_of_freedom=dof,
        chi_squared_prob=float(dist.cdf(chi2)),
        cutoffs=[(lvl, float(dist.ppf(lvl))) for lvl in STANDARD_CONFIDENCE_LEVELS],
        warnings=warnings,
    )
