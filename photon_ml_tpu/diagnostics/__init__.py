"""Offline model diagnostics (the photon-diagnostics module).

Parity targets (all under /root/reference/photon-diagnostics/src/main):
- BootstrapTraining.scala:30-181 + bootstrap/BootstrapTrainingDiagnostic.scala —
  per-coefficient confidence intervals from bootstrap resamples (diagnostics/bootstrap.py)
- fitting/FittingDiagnostic.scala:30-131 — learning curves vs training fraction
  (diagnostics/fitting.py)
- hl/*.scala — Hosmer-Lemeshow calibration test for logistic models
  (diagnostics/hosmer_lemeshow.py)
- featureimportance/*.scala — expected-magnitude and variance feature importance
  (diagnostics/feature_importance.py)
- independence/KendallTauAnalysis.scala:131 — prediction-error independence
  (diagnostics/independence.py)
- reporting/**/*.scala — logical -> physical report tree rendered to HTML/text
  (diagnostics/reporting.py)
"""

from photon_ml_tpu.diagnostics.bootstrap import (
    BootstrapReport,
    CoefficientSummary,
    bootstrap_training,
)
from photon_ml_tpu.diagnostics.feature_importance import (
    FeatureImportanceReport,
    expected_magnitude_importance,
    variance_importance,
)
from photon_ml_tpu.diagnostics.fitting import FittingReport, fitting_diagnostic
from photon_ml_tpu.diagnostics.hosmer_lemeshow import (
    HosmerLemeshowReport,
    hosmer_lemeshow_test,
)
from photon_ml_tpu.diagnostics.independence import (
    KendallTauReport,
    kendall_tau_analysis,
    prediction_error_independence,
)
from photon_ml_tpu.diagnostics.reporting import (
    BarChart,
    BulletedList,
    Chapter,
    Document,
    LineChart,
    ScatterChart,
    Section,
    SimpleText,
    Table,
    render_html,
    render_text,
)
from photon_ml_tpu.diagnostics.transformers import (
    assemble_document,
    bootstrap_section,
    feature_importance_section,
    fitting_section,
    hosmer_lemeshow_section,
    independence_section,
    model_section,
    parameters_section,
    summary_section,
)

__all__ = [
    "BarChart",
    "BootstrapReport",
    "BulletedList",
    "Chapter",
    "CoefficientSummary",
    "Document",
    "FeatureImportanceReport",
    "FittingReport",
    "HosmerLemeshowReport",
    "KendallTauReport",
    "LineChart",
    "ScatterChart",
    "Section",
    "SimpleText",
    "Table",
    "assemble_document",
    "bootstrap_section",
    "bootstrap_training",
    "expected_magnitude_importance",
    "feature_importance_section",
    "fitting_diagnostic",
    "fitting_section",
    "hosmer_lemeshow_section",
    "hosmer_lemeshow_test",
    "independence_section",
    "kendall_tau_analysis",
    "model_section",
    "parameters_section",
    "prediction_error_independence",
    "summary_section",
    "render_html",
    "render_text",
    "variance_importance",
]
