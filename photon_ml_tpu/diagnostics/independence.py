"""Kendall-tau prediction-error independence analysis.

Parity target: photon-diagnostics independence/KendallTauAnalysis.scala:35-90 +
PredictionErrorIndependenceDiagnostic.scala — test whether prediction errors are
independent of predictions by counting concordant/discordant pairs between the
(prediction, error) series. The reference subsamples to ~sqrt(n) items (sample
rate sqrt(n)/n, KendallTauAnalysis.scala:37) and compares all pairs; same here,
with the pair comparison vectorized.

Formulas (KendallTauAnalysis.scala:64-90):
    tau_alpha = (C - D) / (C + D)
    tau_beta  = (C - D) / sqrt((P - T_a)(P - T_b)),  P = m(m-1)/2
    z = 3 (C - D) / sqrt(m(m-1)(2m+5)/2)   (normal approximation)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import numpy as np
from scipy import stats


@dataclasses.dataclass(frozen=True)
class KendallTauReport:
    """independence/KendallTauReport.scala."""

    num_concordant: int
    num_discordant: int
    num_ties_a: int
    num_ties_b: int
    num_items: int
    tau_alpha: float
    tau_beta: float
    z_score: float
    p_value: float  # two-sided, H0: independence


def kendall_tau_analysis(
    a: np.ndarray,
    b: np.ndarray,
    max_items: Optional[int] = None,
    seed: int = 0,
) -> KendallTauReport:
    """Kendall tau over paired series (a, b) — typically (prediction, error).

    Subsamples to ~sqrt(n) items like the reference when n is large (pass
    max_items to override)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    if a.shape != b.shape:
        raise ValueError("series must have the same length")
    n = len(a)
    target = max_items if max_items is not None else max(int(math.sqrt(n)), min(n, 100))
    if n > target:
        rng = np.random.default_rng(seed)
        keep = rng.choice(n, size=target, replace=False)
        a, b = a[keep], b[keep]
    m = len(a)
    if m < 2:
        raise ValueError("need at least 2 items")

    # vectorized all-pairs comparison over the subsample (m ~ sqrt(n))
    da = np.sign(a[:, None] - a[None, :])
    db = np.sign(b[:, None] - b[None, :])
    upper = np.triu_indices(m, k=1)
    prod = da[upper] * db[upper]
    concordant = int((prod > 0).sum())
    discordant = int((prod < 0).sum())
    ties_a = int((da[upper] == 0).sum())
    ties_b = int((db[upper] == 0).sum())

    pairs = m * (m - 1) // 2
    no_ties_a = pairs - ties_a
    no_ties_b = pairs - ties_b
    cd = concordant + discordant
    tau_alpha = (concordant - discordant) / cd if cd else 0.0
    denom = math.sqrt(float(no_ties_a) * float(no_ties_b))
    tau_beta = (concordant - discordant) / denom if denom else 0.0
    z = 3.0 * (concordant - discordant) / math.sqrt(m * (m - 1) * (2 * m + 5) / 2.0)
    p = 2.0 * (1.0 - stats.norm.cdf(abs(z)))
    return KendallTauReport(
        num_concordant=concordant,
        num_discordant=discordant,
        num_ties_a=ties_a,
        num_ties_b=ties_b,
        num_items=m,
        tau_alpha=tau_alpha,
        tau_beta=tau_beta,
        z_score=float(z),
        p_value=float(p),
    )


def prediction_error_independence(
    predictions: np.ndarray, labels: np.ndarray, **kwargs
) -> KendallTauReport:
    """PredictionErrorIndependenceDiagnostic: tau between predictions and
    (label - prediction) errors."""
    predictions = np.asarray(predictions, dtype=np.float64)
    errors = np.asarray(labels, dtype=np.float64) - predictions
    return kendall_tau_analysis(predictions, errors, **kwargs)
