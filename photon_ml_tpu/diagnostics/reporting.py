"""Report tree: chapters/sections/content rendered to HTML or text.

Parity target: photon-diagnostics reporting/**/*.scala — the logical->physical
report pipeline (DocumentPhysicalReport / ChapterPhysicalReport /
SectionPhysicalReport / SimpleTextPhysicalReport / BulletedListPhysicalReport,
rendered by html/HTMLRenderStrategy.scala:72 with numbering via
NumberingContext). The reference renders plots through xchart+batik; here
learning-curve style data renders as inline SVG line charts — no plotting
dependency needed.
"""

from __future__ import annotations

import dataclasses
import html as _html
from typing import Optional, Sequence


# shared series palette for every chart mark (one definition: a palette
# tweak must not desynchronize colors across chart types in one report)
_SERIES_COLORS = ("#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd", "#8c564b")


def _nice_ticks(lo: float, hi: float, target: int = 5) -> list:
    """'Nice number' axis ticks covering [lo, hi] — steps of 1/2/2.5/5 x 10^k
    (the convention xchart's axis renderer follows), at most ~target+1 of
    them, endpoints included only when they land on the grid."""
    import math

    if not (hi > lo) or not (math.isfinite(lo) and math.isfinite(hi)):
        return [lo]
    raw = (hi - lo) / max(target, 1)
    mag = 10.0 ** math.floor(math.log10(raw))
    for mult in (1.0, 2.0, 2.5, 5.0, 10.0):
        step = mult * mag
        if step >= raw:
            break
    first = math.ceil(lo / step) * step
    # index-based, not accumulation: a step below one ulp of the endpoints
    # (values one ulp apart pass the hi > lo guard) would never advance an
    # accumulating `t += step` — an infinite loop inside report rendering
    n = int(min(math.floor((hi - first) / step + 1e-9), 2 * target + 2)) + 1
    if n < 1 or first + step == first:
        return [lo, hi]
    ticks = [first + i * step for i in range(n)]
    return [0.0 if abs(t) < 1e-12 * step else t for t in ticks]


def _axes_and_grid(parts, width, height, pad, title, x_label, y_label,
                   sx=None, x_ticks=(), sy=None, y_ticks=()):
    """Shared chart furniture: title, frame, axis labels, and tick labels
    with light gridlines (PlotUtils.scala axis-range quality, inline-SVG
    form). Appends to ``parts`` in background order — call before marks."""
    parts.append(
        f'<text x="{width/2:.0f}" y="18" text-anchor="middle" font-weight="bold">'
        f"{_html.escape(title)}</text>"
    )
    parts.append(
        f'<text x="{width/2:.0f}" y="{height-6}" text-anchor="middle" font-size="12">'
        f"{_html.escape(x_label)}</text>"
    )
    parts.append(
        f'<text x="14" y="{height/2:.0f}" text-anchor="middle" font-size="12" '
        f'transform="rotate(-90 14 {height/2:.0f})">{_html.escape(y_label)}</text>'
    )
    if sy is not None:
        for t in y_ticks:
            y = sy(t)
            parts.append(
                f'<line x1="{pad}" y1="{y:.1f}" x2="{width-pad}" y2="{y:.1f}" '
                'stroke="#ddd" stroke-width="1"/>'
            )
            parts.append(
                f'<text x="{pad-6}" y="{y+3.5:.1f}" font-size="10" '
                f'text-anchor="end">{t:.4g}</text>'
            )
    if sx is not None:
        for t in x_ticks:
            x = sx(t)
            parts.append(
                f'<line x1="{x:.1f}" y1="{pad}" x2="{x:.1f}" y2="{height-pad}" '
                'stroke="#eee" stroke-width="1"/>'
            )
            parts.append(
                f'<text x="{x:.1f}" y="{height-pad+14}" font-size="10" '
                f'text-anchor="middle">{t:.4g}</text>'
            )
    # frame on top of the gridlines
    parts.append(
        f'<line x1="{pad}" y1="{height-pad}" x2="{width-pad}" y2="{height-pad}" stroke="#333"/>'
    )
    parts.append(
        f'<line x1="{pad}" y1="{pad}" x2="{pad}" y2="{height-pad}" stroke="#333"/>'
    )


def _legend(parts, series_labels, width, pad):
    """In-plot legend, top-right: color swatch + label per series (the old
    right-margin text rendered outside the viewport and was clipped).
    Swatch colors key on the series' ORIGINAL index — marks are colored by
    unfiltered position, so skipping an empty-labeled series must not shift
    its neighbours' colors."""
    entries = [(i, str(l)) for i, l in enumerate(series_labels) if str(l)]
    if not entries:
        return
    box_w = 10 + 7 * max(len(l) for _, l in entries) + 24
    x0 = width - pad - box_w - 4
    y0 = pad + 4
    parts.append(
        f'<rect x="{x0}" y="{y0}" width="{box_w}" height="{4 + 16*len(entries)}" '
        'fill="white" fill-opacity="0.85" stroke="#ccc"/>'
    )
    for row, (i, label) in enumerate(entries):
        color = _SERIES_COLORS[i % len(_SERIES_COLORS)]
        yy = y0 + 14 + 16 * row
        parts.append(
            f'<rect x="{x0+6}" y="{yy-8}" width="12" height="8" fill="{color}"/>'
        )
        parts.append(
            f'<text x="{x0+24}" y="{yy}" font-size="11">{_html.escape(label)}</text>'
        )


@dataclasses.dataclass(frozen=True)
class SimpleText:
    text: str


@dataclasses.dataclass(frozen=True)
class BulletedList:
    items: Sequence[str]


@dataclasses.dataclass(frozen=True)
class Table:
    header: Sequence[str]
    rows: Sequence[Sequence]
    caption: str = ""


@dataclasses.dataclass(frozen=True)
class LineChart:
    """Inline-SVG line chart (PlotPhysicalReport equivalent). Each series is
    (label, xs, ys)."""

    title: str
    x_label: str
    y_label: str
    series: Sequence[tuple]

    def to_svg(self, width: int = 640, height: int = 360) -> str:
        pad = 48
        xs_all = [x for _, xs, _ in self.series for x in xs]
        ys_all = [y for _, _, ys in self.series for y in ys]
        if not xs_all:
            return "<svg/>"
        x0, x1 = min(xs_all), max(xs_all)
        y0, y1 = min(ys_all), max(ys_all)
        if x1 == x0:
            x1 = x0 + 1.0
        if y1 == y0:
            y1 = y0 + 1.0

        def sx(x):
            return pad + (x - x0) / (x1 - x0) * (width - 2 * pad)

        def sy(y):
            return height - pad - (y - y0) / (y1 - y0) * (height - 2 * pad)

        colors = _SERIES_COLORS
        parts = [
            f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" height="{height}">',
        ]
        _axes_and_grid(
            parts, width, height, pad, self.title, self.x_label, self.y_label,
            sx=sx, x_ticks=_nice_ticks(x0, x1), sy=sy, y_ticks=_nice_ticks(y0, y1),
        )
        for i, (label, xs, ys) in enumerate(self.series):
            color = colors[i % len(colors)]
            pts = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in zip(xs, ys))
            parts.append(
                f'<polyline fill="none" stroke="{color}" stroke-width="2" points="{pts}"/>'
            )
        _legend(parts, [label for label, _, _ in self.series], width, pad)
        parts.append("</svg>")
        return "".join(parts)


@dataclasses.dataclass(frozen=True)
class BarChart:
    """Inline-SVG grouped bar chart (the reference renders these through
    xchart's StyleManager.ChartType.Bar — PlotUtils.scala ranges). Each series
    is (label, xs, heights); bars are grouped per x position."""

    title: str
    x_label: str
    y_label: str
    series: Sequence[tuple]
    y_min: Optional[float] = None
    y_max: Optional[float] = None

    def to_svg(self, width: int = 640, height: int = 360) -> str:
        pad = 48
        xs_all = sorted({x for _, xs, _ in self.series for x in xs})
        ys_all = [y for _, _, ys in self.series for y in ys]
        if not xs_all:
            return "<svg/>"
        # both ends include the bar baseline (0): with all-negative values an
        # unclamped range would put the baseline off-canvas and render every
        # bar full-height (e.g. log-likelihood summary charts)
        y0 = min(0.0, *ys_all) if self.y_min is None else self.y_min
        y1 = max(0.0, *ys_all) if self.y_max is None else self.y_max
        if y1 == y0:
            y1 = y0 + 1.0
        n_groups = len(xs_all)
        n_series = max(1, len(self.series))
        group_w = (width - 2 * pad) / n_groups
        bar_w = max(1.0, group_w * 0.8 / n_series)
        x_pos = {x: i for i, x in enumerate(xs_all)}

        def sy(y):
            return height - pad - (y - y0) / (y1 - y0) * (height - 2 * pad)

        colors = _SERIES_COLORS
        parts = [
            f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" height="{height}">',
        ]
        _axes_and_grid(
            parts, width, height, pad, self.title, self.x_label, self.y_label,
            sy=sy, y_ticks=_nice_ticks(y0, y1),
        )
        # the bar baseline (y=0) sits wherever the range puts it
        parts.append(
            f'<line x1="{pad}" y1="{sy(0.0):.1f}" x2="{width-pad}" y2="{sy(0.0):.1f}" stroke="#333"/>'
        )
        for gi, x in enumerate(xs_all):
            gx = pad + gi * group_w + group_w * 0.1
            parts.append(
                f'<text x="{gx + group_w*0.4:.1f}" y="{height-pad+14}" font-size="9" '
                f'text-anchor="middle">{x:.3g}</text>'
            )
        for si, (label, xs, ys) in enumerate(self.series):
            color = colors[si % len(colors)]
            for x, y in zip(xs, ys):
                gx = pad + x_pos[x] * group_w + group_w * 0.1 + si * bar_w
                top, base = sorted((sy(y), sy(max(y0, 0.0))))
                parts.append(
                    f'<rect x="{gx:.1f}" y="{top:.1f}" width="{bar_w:.1f}" '
                    f'height="{max(base-top, 0.5):.1f}" fill="{color}" fill-opacity="0.8"/>'
                )
        _legend(parts, [label for label, _, _ in self.series], width, pad)
        parts.append("</svg>")
        return "".join(parts)


@dataclasses.dataclass(frozen=True)
class ScatterChart:
    """Inline-SVG scatter plot (ChartType.Scatter; e.g. the reference's
    'Error v. Prediction' plot). Each series is (label, xs, ys)."""

    title: str
    x_label: str
    y_label: str
    series: Sequence[tuple]

    def to_svg(self, width: int = 640, height: int = 360) -> str:
        pad = 48
        xs_all = [x for _, xs, _ in self.series for x in xs]
        ys_all = [y for _, _, ys in self.series for y in ys]
        if not xs_all:
            return "<svg/>"
        x0, x1 = min(xs_all), max(xs_all)
        y0, y1 = min(ys_all), max(ys_all)
        if x1 == x0:
            x1 = x0 + 1.0
        if y1 == y0:
            y1 = y0 + 1.0

        def sx(x):
            return pad + (x - x0) / (x1 - x0) * (width - 2 * pad)

        def sy(y):
            return height - pad - (y - y0) / (y1 - y0) * (height - 2 * pad)

        colors = _SERIES_COLORS
        parts = [
            f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" height="{height}">',
        ]
        _axes_and_grid(
            parts, width, height, pad, self.title, self.x_label, self.y_label,
            sx=sx, x_ticks=_nice_ticks(x0, x1), sy=sy, y_ticks=_nice_ticks(y0, y1),
        )
        for i, (label, xs, ys) in enumerate(self.series):
            color = colors[i % len(colors)]
            for x, y in zip(xs, ys):
                parts.append(
                    f'<circle cx="{sx(x):.1f}" cy="{sy(y):.1f}" r="2.5" '
                    f'fill="{color}" fill-opacity="0.6"/>'
                )
        _legend(parts, [label for label, _, _ in self.series], width, pad)
        parts.append("</svg>")
        return "".join(parts)


@dataclasses.dataclass(frozen=True)
class Section:
    title: str
    contents: Sequence  # SimpleText | BulletedList | Table | charts | Section


@dataclasses.dataclass(frozen=True)
class Chapter:
    title: str
    sections: Sequence[Section]


@dataclasses.dataclass(frozen=True)
class Document:
    title: str
    chapters: Sequence[Chapter]


# ------------------------------------------------------------------ rendering


def render_text(doc: Document) -> str:
    """Plain-text rendering with hierarchical numbering (NumberingContext)."""
    lines = [doc.title, "=" * len(doc.title), ""]
    for ci, chapter in enumerate(doc.chapters, 1):
        lines += [f"{ci}. {chapter.title}", "-" * (len(chapter.title) + 4), ""]
        for si, section in enumerate(chapter.sections, 1):
            lines += _render_section_text(section, f"{ci}.{si}")
    return "\n".join(lines)


def _render_section_text(section: Section, number: str) -> list:
    lines = [f"{number} {section.title}", ""]
    sub = 0
    for item in section.contents:
        if isinstance(item, SimpleText):
            lines += [item.text, ""]
        elif isinstance(item, BulletedList):
            lines += [f"  * {x}" for x in item.items] + [""]
        elif isinstance(item, Table):
            widths = [
                max(len(str(h)), *(len(str(r[i])) for r in item.rows)) if item.rows else len(str(h))
                for i, h in enumerate(item.header)
            ]
            fmt = "  ".join(f"{{:<{w}}}" for w in widths)
            lines.append(fmt.format(*[str(h) for h in item.header]))
            lines += [fmt.format(*[str(c) for c in row]) for row in item.rows]
            if item.caption:
                lines.append(f"({item.caption})")
            lines.append("")
        elif isinstance(item, (LineChart, BarChart, ScatterChart)):
            lines += [f"[chart: {item.title}]", ""]
        elif isinstance(item, Section):
            sub += 1
            lines += _render_section_text(item, f"{number}.{sub}")
    return lines


def render_html(doc: Document) -> str:
    """HTML rendering (html/HTMLRenderStrategy.scala equivalent; charts inline SVG)."""
    out = [
        "<!DOCTYPE html><html><head><meta charset='utf-8'>",
        f"<title>{_html.escape(doc.title)}</title>",
        "<style>body{font-family:sans-serif;margin:2em;max-width:60em}"
        "table{border-collapse:collapse}td,th{border:1px solid #999;"
        "padding:4px 8px}th{background:#eee}</style></head><body>",
        f"<h1>{_html.escape(doc.title)}</h1>",
    ]
    for ci, chapter in enumerate(doc.chapters, 1):
        out.append(f"<h2>{ci}. {_html.escape(chapter.title)}</h2>")
        for si, section in enumerate(chapter.sections, 1):
            out.append(_render_section_html(section, f"{ci}.{si}", level=3))
    out.append("</body></html>")
    return "".join(out)


def _render_section_html(section: Section, number: str, level: int) -> str:
    h = min(level, 6)
    out = [f"<h{h}>{number} {_html.escape(section.title)}</h{h}>"]
    sub = 0
    for item in section.contents:
        if isinstance(item, SimpleText):
            out.append(f"<p>{_html.escape(item.text)}</p>")
        elif isinstance(item, BulletedList):
            out.append(
                "<ul>" + "".join(f"<li>{_html.escape(str(x))}</li>" for x in item.items) + "</ul>"
            )
        elif isinstance(item, Table):
            rows = "".join(
                "<tr>" + "".join(f"<td>{_html.escape(str(c))}</td>" for c in row) + "</tr>"
                for row in item.rows
            )
            head = "".join(f"<th>{_html.escape(str(h_))}</th>" for h_ in item.header)
            cap = f"<caption>{_html.escape(item.caption)}</caption>" if item.caption else ""
            out.append(f"<table>{cap}<tr>{head}</tr>{rows}</table>")
        elif isinstance(item, (LineChart, BarChart, ScatterChart)):
            out.append(item.to_svg())
        elif isinstance(item, Section):
            sub += 1
            out.append(_render_section_html(item, f"{number}.{sub}", level + 1))
    return "".join(out)
