"""Report -> Section transformers and full-document assembly.

Parity target: the reference's logical->physical report pipeline —
*ToPhysicalReportTransformer classes (BootstrapToPhysicalReportTransformer,
FeatureImportanceToPhysicalReportTransformer, FittingToPhysicalReportTransformer,
NaiveHosmerLemeshowToPhysicalReportTransformer,
PredictionErrorIndependencePhysicalReportTransformer,
ModelDiagnosticToPhysicalReportTransformer) plus the combined document
assembly (reporting/reports/combined/DiagnosticToPhysicalReportTransformer
.scala:36-137: Summary chapter with best-model-by-metric + per-metric charts,
System chapter, Detailed Model Diagnostics chapter with one Model Analysis
section per lambda).

Section titles mirror the reference's constants so a reader of either report
finds the same chapter set. Where the reference renders a statistic only as a
plot, a table of the same numbers is added — the numbers stay greppable. The
reference's system/parameters chapter is empty in its snapshot (circular-
dependency TODO in ParametersToPhysicalReportTransformer.scala); here it
renders the actual driver parameters.
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.diagnostics.bootstrap import BootstrapReport
from photon_ml_tpu.diagnostics.feature_importance import FeatureImportanceReport
from photon_ml_tpu.diagnostics.fitting import FittingReport
from photon_ml_tpu.diagnostics.hosmer_lemeshow import HosmerLemeshowReport
from photon_ml_tpu.diagnostics.independence import KendallTauReport
from photon_ml_tpu.diagnostics.reporting import (
    BarChart,
    BulletedList,
    Chapter,
    Document,
    LineChart,
    ScatterChart,
    Section,
    SimpleText,
    Table,
)

# Section titles from the reference's transformer objects
HL_SECTION = "Hosmer-Lemeshow Goodness-of-Fit Test for Logistic Regression"
BOOTSTRAP_SECTION = "Bootstrap Analysis"
FIT_SECTION = "Fit Analysis"
IMPORTANCE_SECTION_PREFIX = "Feature importance"
INDEPENDENCE_SECTION = "Error / Prediction Independence Analysis"
MODEL_SECTION_PREFIX = "Model Analysis"
SUMMARY_CHAPTER = "Summary"
MODEL_CHAPTER = "Detailed Model Diagnostics"
PARAMETERS_SECTION = "Command-line options"


# ------------------------------------------------------------------ bootstrap


def bootstrap_section(report: BootstrapReport, index_map=None, top_k: int = 20) -> Section:
    """BootstrapToPhysicalReportTransformer.transform: Metrics Distributions,
    Bagged Model Metrics, Coefficient Analysis for Important Features,
    Features Straddling Zero (BootstrapToPhysicalReportTransformer.scala)."""

    def key(j):
        name = index_map.get_feature_name(j) if index_map is not None else None
        return name if name is not None else str(j)

    def five_number(s):
        return (s.min, s.lower_ci, s.median, s.upper_ci, s.max)

    # Metrics Distributions: the reference plots min/Q1/median/Q3/max per
    # metric; same five-number summary as chart + table
    metric_contents = []
    if report.metric_distributions:
        labels = ("min", "2.5%", "median", "97.5%", "max")
        for name, s in sorted(report.metric_distributions.items()):
            vals = five_number(s)
            metric_contents.append(
                BarChart(
                    title=f"Bootstrap distribution of {name}",
                    x_label="",
                    y_label=name,
                    series=[(f"{l}: {v:.4g}", [float(i)], [v])
                            for i, (l, v) in enumerate(zip(labels, vals))],
                )
            )
        metric_contents.append(
            Table(
                ("metric", *labels),
                [
                    (name, *(f"{v:.4g}" for v in five_number(s)))
                    for name, s in sorted(report.metric_distributions.items())
                ],
            )
        )
    sections = []
    if metric_contents:
        sections.append(Section("Metrics Distributions", metric_contents))
        sections.append(
            Section(
                "Bagged Model Metrics",
                [BulletedList([
                    f"Metric: {name}, value: {s.mean:.6g} (mean over "
                    f"{report.num_models} bootstrap models)"
                    for name, s in sorted(report.metric_distributions.items())
                ])],
            )
        )

    # Coefficient Analysis for Important Features: top-|median| coefficients
    # with their full bootstrap distribution
    order = np.argsort([-abs(s.median) for s in report.coefficient_summaries])[:top_k]
    rows = [
        (
            key(int(j)),
            f"{report.coefficient_summaries[j].mean:.4g}",
            f"{report.coefficient_summaries[j].std:.4g}",
            f"{report.coefficient_summaries[j].lower_ci:.4g}",
            f"{report.coefficient_summaries[j].median:.4g}",
            f"{report.coefficient_summaries[j].upper_ci:.4g}",
        )
        for j in order
    ]
    sections.append(
        Section(
            "Coefficient Analysis for Important Features",
            [
                SimpleText(
                    f"Bootstrap over {report.num_models} resampled models; "
                    f"top {len(rows)} coefficients by |median|."
                ),
                Table(("feature", "mean", "st.dev", "2.5%", "median", "97.5%"), rows),
            ],
        )
    )

    # Features Straddling Zero (interquartile/CI range containing 0)
    straddling = [
        (int(j), s)
        for j, s in enumerate(report.coefficient_summaries)
        if s.interval_contains_zero() and (s.lower_ci != 0.0 or s.upper_ci != 0.0)
    ]
    straddling.sort(key=lambda x: -abs(x[1].median))
    sections.append(
        Section(
            "Features Straddling Zero",
            [
                SimpleText(
                    "Total features with confidence interval straddling zero: "
                    f"{len(straddling)}"
                ),
                BulletedList([
                    f"Feature {key(j)}: median {s.median:.4g} in "
                    f"[{s.lower_ci:.4g}, {s.upper_ci:.4g}]"
                    for j, s in straddling[:top_k]
                ]),
            ],
        )
    )
    return Section(BOOTSTRAP_SECTION, sections)


# ---------------------------------------------------------- feature importance


def feature_importance_section(report: FeatureImportanceReport, top_k: int = 20) -> Section:
    """FeatureImportanceToPhysicalReportTransformer: importance-distribution
    plot (% features with greater importance vs relative importance) +
    ranked feature descriptions."""
    sorted_desc = [v for _, _, v in report.ranked]  # ranked is descending
    contents = []
    if sorted_desc:
        # rank -> importance curve: x = % of features with greater importance
        pct = 100.0 * np.arange(len(sorted_desc)) / len(sorted_desc)
        contents.append(
            LineChart(
                title=report.importance_type,
                x_label="% features with greater importance",
                y_label="Relative importance",
                series=[(report.importance_description, list(pct), sorted_desc)],
            )
        )
    rows = [(k, str(i), f"{v:.4g}") for k, i, v in report.top(top_k)]
    contents += [
        SimpleText(report.importance_description),
        Table(("feature", "index", "importance"), rows,
              caption=f"top {len(rows)} features"),
    ]
    return Section(f"{IMPORTANCE_SECTION_PREFIX} [{report.importance_type}]", contents)


# ------------------------------------------------------------------- fitting


def fitting_section(report: FittingReport) -> Section:
    """FittingToPhysicalReportTransformer: Messages + Metric Plots (train vs
    holdout metric against portion of training set)."""
    sections = []
    if report.message:
        sections.append(Section("Messages", [SimpleText(report.message)]))
    plots = []
    for metric in sorted(report.metrics):
        portions, train_vals, test_vals = report.metrics[metric]
        plots.append(
            LineChart(
                title=metric,
                x_label="Portion of training set",
                y_label="Metric value",
                series=[
                    ("Training set", portions, train_vals),
                    ("Holdout set", portions, test_vals),
                ],
            )
        )
        plots.append(
            Table(
                ("portion", "training set", "holdout set"),
                [
                    (f"{p:.3g}", f"{tr:.6g}", f"{te:.6g}")
                    for p, tr, te in zip(portions, train_vals, test_vals)
                ],
                caption=metric,
            )
        )
    if plots:
        sections.append(Section("Metric Plots", plots))
    return Section(FIT_SECTION, sections)


# ------------------------------------------------------------ Hosmer-Lemeshow


def hosmer_lemeshow_section(report: HosmerLemeshowReport) -> Section:
    """NaiveHosmerLemeshowToPhysicalReportTransformer: Plots (observed vs
    expected positive rate, counts by score, cumulative counts, label
    breakdown) + Analysis (test description, point probability, cutoff
    analysis) + binning / chi-square message subsections."""
    bins = report.bins
    mids_pct = [100.0 * (b.lower_bound + b.upper_bound) / 2.0 for b in bins]
    observed_rate = [
        100.0 * b.observed_pos / b.total if b.total else 0.0 for b in bins
    ]
    pos = [float(b.observed_pos) for b in bins]
    neg = [float(b.observed_neg) for b in bins]
    tot = [float(b.total) for b in bins]
    plots = Section(
        "Plots",
        [
            BarChart(
                title="Observed positive rate versus predicted positive rate",
                x_label="Predicted positive rate",
                y_label="Observed positive rate",
                series=[("Observed", mids_pct, observed_rate),
                        ("Expected", mids_pct, mids_pct)],
                y_min=0.0, y_max=100.0,
            ),
            BarChart(
                title="Count by Score",
                x_label="Score",
                y_label="Count",
                series=[("Positive", mids_pct, pos), ("Negative", mids_pct, neg),
                        ("Total", mids_pct, tot)],
            ),
            BarChart(
                title="Cumulative count by Score",
                x_label="Score",
                y_label="Cumulative Count",
                series=[
                    ("Positive", mids_pct, list(np.cumsum(pos))),
                    ("Negative", mids_pct, list(np.cumsum(neg))),
                    ("Total", mids_pct, list(np.cumsum(tot))),
                ],
            ),
            # the reference reuses its LABEL_BREAKDOWN_TITLE ("Count by
            # Score") for this aggregate chart too; retitled here so the two
            # charts are distinguishable
            BarChart(
                title="Count by Label (total)",
                x_label="",
                y_label="Count",
                series=[("Positive", [0.0], [sum(pos)]),
                        ("Negative", [0.0], [sum(neg)])],
            ),
        ],
    )

    # Analysis: HosmerLemeshowReport.getTestDescription /
    # getPointProbabilityAnalysis / getCutoffAnalysis prose
    cutoff_lines = []
    for level, cutoff in report.cutoffs:
        verdict = (
            "reject H0 (evidence of mis-calibration) at this level"
            if report.chi_squared > cutoff
            else "cannot reject H0 at this level"
        )
        cutoff_lines.append(
            f"Pr[X <= {cutoff:12.9f}] = {100.0 * level:.7f}%: {verdict}"
        )
    analysis = Section(
        "Analysis",
        [
            BulletedList([
                f"Chi^2 = [{report.chi_squared:.6f}] on "
                f"[{report.degrees_of_freedom}] degrees of freedom",
                f"Pr[Chi^2 < {report.chi_squared:.6f}] = "
                f"[{100.0 * report.chi_squared_prob:.9g}%] "
                f"(p-value under H0 well-calibrated: {report.p_value:.4g})",
            ]),
            BulletedList(cutoff_lines),
        ],
    )
    binning_rows = [
        (
            f"[{b.lower_bound:.3f}, {b.upper_bound:.3f})",
            str(b.observed_pos),
            str(b.expected_pos),
            str(b.observed_neg),
            str(b.expected_neg),
        )
        for b in bins
    ]
    binning = Section(
        "Messages generated during histogram calculation",
        [
            Table(("probability bin", "obs +", "exp +", "obs -", "exp -"),
                  binning_rows),
            BulletedList(report.warnings)
            if report.warnings
            else SimpleText("No binning warnings."),
        ],
    )
    chi_sq_msgs = Section(
        "Messages generated during Chi square calculation",
        [SimpleText(
            f"chi^2 summed over {len(bins)} bins (positive and negative "
            f"sides); degrees of freedom = bins - 2 = {report.degrees_of_freedom}."
        )],
    )
    return Section(HL_SECTION, [plots, analysis, binning, chi_sq_msgs])


# --------------------------------------------------------------- independence


def independence_section(report: KendallTauReport, predictions=None, errors=None) -> Section:
    """PredictionErrorIndependencePhysicalReportTransformer: error-vs-
    prediction scatter + Kendall Tau Independence Test statistics."""
    sections = []
    if predictions is not None and errors is not None and len(predictions):
        p = np.asarray(predictions, dtype=np.float64)
        e = np.asarray(errors, dtype=np.float64)
        if len(p) > 2000:  # plot stays bounded; the test has its own sampling
            idx = np.linspace(0, len(p) - 1, 2000).astype(int)
            p, e = p[idx], e[idx]
        sections.append(
            Section(
                "Plot",
                [ScatterChart(
                    title="Error v. Prediction",
                    x_label="Prediction",
                    y_label="Label - Prediction",
                    series=[("Prediction error", list(p), list(e))],
                )],
            )
        )
    pairs = report.num_items * (report.num_items - 1) // 2
    effective = pairs - max(report.num_ties_a, report.num_ties_b)
    sections.append(
        Section(
            "Kendall Tau Independence Test",
            [Table(
                ("statistic", "value"),
                [
                    ("items (sampled)", str(report.num_items)),
                    ("total pairs", str(pairs)),
                    ("effective pairs", str(effective)),
                    ("concordant pairs", str(report.num_concordant)),
                    ("discordant pairs", str(report.num_discordant)),
                    ("ties (prediction)", str(report.num_ties_a)),
                    ("ties (error)", str(report.num_ties_b)),
                    ("tau alpha", f"{report.tau_alpha:.4f}"),
                    ("tau beta", f"{report.tau_beta:.4f}"),
                    ("z score", f"{report.z_score:.4f}"),
                    ("p value (H0: independent)", f"{report.p_value:.4g}"),
                ],
            )],
        )
    )
    return Section(INDEPENDENCE_SECTION, sections)


# ------------------------------------------------------------ model assembly


def model_section(
    model_description: str,
    lambda_value: float,
    metrics: dict,
    subsections=(),
) -> Section:
    """ModelDiagnosticToPhysicalReportTransformer.transform: 'Model Analysis:
    <desc>, lambda=<λ>' with Validation Set Metrics first, then whichever
    per-model diagnostic sections ran."""
    metrics_section = Section(
        "Validation Set Metrics",
        [BulletedList([
            f"Metric: [{name}], value: [{value:.6g}]"
            for name, value in sorted(metrics.items())
        ])],
    )
    return Section(
        f"{MODEL_SECTION_PREFIX}: {model_description}, lambda={lambda_value:g}",
        [metrics_section, *subsections],
    )


def summary_section(metrics_by_lambda: dict, best_is_max: dict = None) -> Section:
    """DiagnosticToPhysicalReportTransformer.transformSummary: which lambda
    did best per metric, plus a per-metric chart over lambdas.

    metrics_by_lambda: {lambda: {metric: value}};
    best_is_max: {metric: bool} (defaults to True — higher is better)."""
    by_metric: dict = {}
    for lam, metrics in metrics_by_lambda.items():
        for name, value in metrics.items():
            by_metric.setdefault(name, {})[lam] = value
    best_lines = []
    charts = []
    for name in sorted(by_metric):
        values = by_metric[name]
        maximize = True if best_is_max is None else best_is_max.get(name, True)
        best_lambda = (max if maximize else min)(values, key=values.get)
        best_lines.append(
            f"Metric {name} best: {values[best_lambda]:.6g} @ lambda = {best_lambda:g}"
        )
        lams = sorted(values)
        charts.append(
            BarChart(
                title=name,
                x_label="lambda",
                y_label=name,
                # group x = the actual lambda (ticks label real values;
                # BarChart positions groups by order, so uneven spacing is fine)
                series=[(f"Lambda = {lam:g}", [float(lam)], [values[lam]])
                        for lam in lams],
            )
        )
    # the reference nests a "Summary" section inside the "Summary" chapter;
    # its MODEL_METRICS_SUMMARY constant is the better title for the content
    return Section("Model Metrics", [BulletedList(best_lines), *charts])


def parameters_section(params: dict) -> Section:
    """ParametersToPhysicalReportTransformer: the reference's version renders
    an empty list (circular-dependency TODO in its snapshot); here the actual
    driver parameters render grouped under the same section title."""
    return Section(
        PARAMETERS_SECTION,
        [BulletedList([f"{k}: {v}" for k, v in sorted(params.items())
                       if v is not None])],
    )


def assemble_document(
    title: str,
    params: dict,
    metrics_by_lambda: dict,
    model_sections,
    best_is_max: dict = None,
    extra_chapters=(),
) -> Document:
    """DiagnosticToPhysicalReportTransformer.transform: Summary chapter,
    System chapter (command-line options), Detailed Model Diagnostics chapter
    with one Model Analysis section per lambda (sorted by lambda)."""
    chapters = [
        Chapter(SUMMARY_CHAPTER, [summary_section(metrics_by_lambda, best_is_max)]),
        Chapter("System", [parameters_section(params)]),
        Chapter(MODEL_CHAPTER, list(model_sections)),
        *extra_chapters,
    ]
    return Document(title, chapters)
