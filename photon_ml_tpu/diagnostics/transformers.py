"""Report -> Section transformers (the reference's *ToPhysicalReportTransformer
classes: BootstrapToPhysicalReportTransformer,
FeatureImportanceToPhysicalReportTransformer, FittingToPhysicalReportTransformer,
NaiveHosmerLemeshowToPhysicalReportTransformer,
PredictionErrorIndependencePhysicalReportTransformer)."""

from __future__ import annotations

from photon_ml_tpu.diagnostics.bootstrap import BootstrapReport
from photon_ml_tpu.diagnostics.feature_importance import FeatureImportanceReport
from photon_ml_tpu.diagnostics.fitting import FittingReport
from photon_ml_tpu.diagnostics.hosmer_lemeshow import HosmerLemeshowReport
from photon_ml_tpu.diagnostics.independence import KendallTauReport
from photon_ml_tpu.diagnostics.reporting import (
    BulletedList,
    LineChart,
    Section,
    SimpleText,
    Table,
)


def bootstrap_section(report: BootstrapReport, index_map=None, top_k: int = 20) -> Section:
    def key(j):
        return index_map.get_feature_name(j) if index_map is not None else str(j)

    import numpy as np

    order = np.argsort(
        [-abs(s.median) for s in report.coefficient_summaries]
    )[:top_k]
    rows = [
        (
            key(int(j)),
            f"{report.coefficient_summaries[j].lower_ci:.4g}",
            f"{report.coefficient_summaries[j].median:.4g}",
            f"{report.coefficient_summaries[j].upper_ci:.4g}",
            "yes" if report.coefficient_summaries[j].interval_contains_zero() else "no",
        )
        for j in order
    ]
    metric_rows = [
        (name, f"{s.lower_ci:.4g}", f"{s.median:.4g}", f"{s.upper_ci:.4g}")
        for name, s in report.metric_distributions.items()
    ]
    contents = [
        SimpleText(f"Bootstrap over {report.num_models} resampled models."),
        Table(("feature", "2.5%", "median", "97.5%", "CI contains 0"), rows,
              caption=f"top {len(rows)} coefficients by |median|"),
    ]
    if metric_rows:
        contents.append(Table(("metric", "2.5%", "median", "97.5%"), metric_rows))
    return Section("Bootstrap confidence intervals", contents)


def feature_importance_section(report: FeatureImportanceReport, top_k: int = 20) -> Section:
    rows = [(k, str(i), f"{v:.4g}") for k, i, v in report.top(top_k)]
    return Section(
        f"Feature importance ({report.importance_type})",
        [
            SimpleText(report.importance_description),
            Table(("feature", "index", "importance"), rows),
        ],
    )


def fitting_section(report: FittingReport) -> Section:
    contents = []
    if report.message:
        contents.append(SimpleText(report.message))
    for metric, (portions, train_vals, test_vals) in report.metrics.items():
        contents.append(
            LineChart(
                title=f"{metric} vs training set size",
                x_label="% of training data",
                y_label=metric,
                series=[("train", portions, train_vals), ("holdout", portions, test_vals)],
            )
        )
    return Section("Learning curves", contents)


def hosmer_lemeshow_section(report: HosmerLemeshowReport) -> Section:
    rows = [
        (
            f"[{b.lower_bound:.3f}, {b.upper_bound:.3f})",
            str(b.observed_pos),
            str(b.expected_pos),
            str(b.observed_neg),
            str(b.expected_neg),
        )
        for b in report.bins
    ]
    contents = [
        SimpleText(
            f"chi^2 = {report.chi_squared:.4f} with {report.degrees_of_freedom} d.o.f.; "
            f"P(chi^2 >= observed | well-calibrated) = {report.p_value:.4g}"
        ),
        Table(("probability bin", "obs +", "exp +", "obs -", "exp -"), rows),
    ]
    if report.warnings:
        contents.append(BulletedList(report.warnings))
    return Section("Hosmer-Lemeshow calibration", contents)


def independence_section(report: KendallTauReport) -> Section:
    return Section(
        "Prediction-error independence (Kendall tau)",
        [
            Table(
                ("statistic", "value"),
                [
                    ("items (sampled)", str(report.num_items)),
                    ("concordant pairs", str(report.num_concordant)),
                    ("discordant pairs", str(report.num_discordant)),
                    ("tau alpha", f"{report.tau_alpha:.4f}"),
                    ("tau beta", f"{report.tau_beta:.4f}"),
                    ("z score", f"{report.z_score:.4f}"),
                    ("p value (H0: independent)", f"{report.p_value:.4g}"),
                ],
            )
        ],
    )
