"""Per-feature importance diagnostics.

Parity target: photon-diagnostics featureimportance/*.scala —
- ExpectedMagnitudeFeatureImportanceDiagnostic.scala:25-60: importance =
  |coefficient * E|x|| (falls back to |coefficient| without summary)
- VarianceFeatureImportanceDiagnostic.scala: importance = |coefficient| *
  sqrt(Var[x]) (contribution to score variance)
- FeatureImportanceReport: ranked features + an importance histogram.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.normalization import FeatureDataStatistics


@dataclasses.dataclass(frozen=True)
class FeatureImportanceReport:
    """featureimportance/FeatureImportanceReport.scala: importance type +
    description + ranked (feature key, index, importance)."""

    importance_type: str
    importance_description: str
    ranked: list  # [(feature key, index, importance)] descending importance

    def top(self, k: int) -> list:
        return self.ranked[:k]


def _rank(importances: np.ndarray, index_map: Optional[IndexMap]) -> list:
    order = np.argsort(-importances, kind="mergesort")
    out = []
    for j in order:
        key = index_map.get_feature_name(int(j)) if index_map is not None else str(int(j))
        out.append((key, int(j), float(importances[j])))
    return out


def expected_magnitude_importance(
    coefficients: np.ndarray,
    summary: Optional[FeatureDataStatistics] = None,
    index_map: Optional[IndexMap] = None,
) -> FeatureImportanceReport:
    """|w_j * E|x_j||, the expected magnitude of the feature's score contribution."""
    coefficients = np.asarray(coefficients, dtype=np.float64)
    if summary is not None:
        importances = np.abs(coefficients * np.asarray(summary.mean_abs))
        desc = "Expected magnitude of inner product contribution"
    else:
        importances = np.abs(coefficients)
        desc = "Magnitude of feature coefficient"
    return FeatureImportanceReport(
        importance_type="Inner product expectation",
        importance_description=desc,
        ranked=_rank(importances, index_map),
    )


def variance_importance(
    coefficients: np.ndarray,
    summary: FeatureDataStatistics,
    index_map: Optional[IndexMap] = None,
) -> FeatureImportanceReport:
    """|w_j| * std(x_j): the feature's contribution to score variance."""
    coefficients = np.asarray(coefficients, dtype=np.float64)
    importances = np.abs(coefficients) * np.sqrt(np.asarray(summary.variance))
    return FeatureImportanceReport(
        importance_type="Variance contribution",
        importance_description="Contribution of the feature to the score variance",
        ranked=_rank(importances, index_map),
    )
