"""Legacy single-GLM training driver (staged workflow).

Parity target: photon-client Driver.scala:59-543 + DriverStage.scala:45-50 +
PhotonMLCmdLineParser.scala — the deprecated pre-GAME CLI: read name-term-value
Avro training data, summarize features, train one GLM per regularization weight
(warm-started sweep via the ModelTraining facade), compute the per-model metric
map on validation data, select the best model per task metric, and write models
in the legacy TEXT format. The diagnostics tier (bootstrap CIs, fitting curves,
Hosmer-Lemeshow calibration, feature importance, prediction-error independence)
renders into one ``model-diagnostic.html`` (Driver.REPORT_FILE:504).

Stages (DriverStage.scala): INIT -> PREPROCESSED -> TRAINED -> VALIDATED, with
the same assert-and-advance bookkeeping so downstream tooling can introspect
how far a run progressed.
"""

from __future__ import annotations

import argparse

import enum
import json
import os
import shutil
import sys
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.cli.parsers import add_version_argument
from photon_ml_tpu.data.dataset import LabeledData
from photon_ml_tpu.data.index_map import IndexMap, feature_key
from photon_ml_tpu.data.readers import read_avro
from photon_ml_tpu.data.validators import DataValidationType, sanity_check_data
from photon_ml_tpu.estimators.model_training import train_generalized_linear_model
from photon_ml_tpu.evaluation.metric_map import (
    SELECTION_METRIC,
    evaluate_model,
    select_best_model,
)
from photon_ml_tpu.io.model_io import write_models_in_text
from photon_ml_tpu.normalization import (
    NO_NORMALIZATION,
    FeatureDataStatistics,
    NormalizationContext,
)
from photon_ml_tpu.optimization.config import RegularizationContext
from photon_ml_tpu.optimization.constraints import build_bound_vectors
from photon_ml_tpu.types import (
    NormalizationType,
    OptimizerType,
    RegularizationType,
    TaskType,
)
from photon_ml_tpu.util import Event, EventEmitter, PhotonLogger, Timed

LEARNED_MODELS_TEXT = "learned-models-text"
BEST_MODEL_TEXT = "best-model-text"
REPORT_FILE = "model-diagnostic.html"
SUMMARY_FILE = "feature-summary.avro"


class DriverStage(enum.IntEnum):
    """DriverStage.scala:45-50 — ordered pipeline stages."""

    INIT = 0
    PREPROCESSED = 1
    TRAINED = 2
    VALIDATED = 3


class DiagnosticMode(str, enum.Enum):
    NONE = "NONE"
    TRAIN = "TRAIN"
    VALIDATE = "VALIDATE"
    ALL = "ALL"


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-ml-legacy-driver",
        description="Deprecated single-GLM staged training driver.",
    )
    add_version_argument(p)
    p.add_argument("--training-data-directory", required=True)
    p.add_argument("--validating-data-directory", default=None)
    p.add_argument("--output-directory", required=True)
    p.add_argument("--delete-output-dirs-if-exist", action="store_true")
    p.add_argument("--training-task", required=True,
                   choices=[t.value for t in TaskType])
    p.add_argument("--optimizer", default="LBFGS",
                   choices=[o.value for o in OptimizerType])
    p.add_argument("--regularization-type", default="L2",
                   choices=[r.value for r in RegularizationType])
    p.add_argument("--regularization-weights", default="0.1,1,10,100",
                   help="Comma-separated lambda sweep (warm-started)")
    p.add_argument("--elastic-net-alpha", type=float, default=0.5)
    p.add_argument("--max-number-iterations", type=int, default=100)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--normalization-type", default="NONE",
                   choices=[n.value for n in NormalizationType])
    p.add_argument("--summarization-output-dir", default=None)
    p.add_argument("--coefficient-box-constraints", default=None,
                   help="JSON constraint-map array (GLMSuite format)")
    p.add_argument("--selected-features-file", default=None,
                   help="Text file of 'name<TAB>term' lines restricting features")
    p.add_argument("--intercept", dest="intercept", action="store_true",
                   default=True)
    p.add_argument("--no-intercept", dest="intercept", action="store_false")
    p.add_argument("--use-warm-start", dest="warm_start", action="store_true",
                   default=True)
    p.add_argument("--no-warm-start", dest="warm_start", action="store_false")
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.value for v in DataValidationType])
    p.add_argument("--diagnostic-mode", default="NONE",
                   choices=[m.value for m in DiagnosticMode])
    p.add_argument("--log-level", default="INFO")
    return p


def _selected_features_map(path: str, intercept: bool) -> IndexMap:
    keys = []
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            name, _, term = line.partition("\t")
            keys.append(feature_key(name, term))
    if not keys:
        raise ValueError(f"Selected-features file {path!r} lists no features")
    return IndexMap.build(keys, add_intercept=intercept)


class LegacyDriver:
    """The staged workflow object (Driver.scala:59-543)."""

    def __init__(self, args: argparse.Namespace, logger, emitter: EventEmitter):
        self.args = args
        self.logger = logger
        self.emitter = emitter
        self.stage = DriverStage.INIT
        self.stage_history: list[DriverStage] = []
        self.task = TaskType(args.training_task)
        self.regularization_context = RegularizationContext(
            RegularizationType(args.regularization_type),
            args.elastic_net_alpha
            if RegularizationType(args.regularization_type)
            == RegularizationType.ELASTIC_NET
            else None,
        )
        self.reg_weights = [float(w) for w in args.regularization_weights.split(",") if w]
        self.index_map: Optional[IndexMap] = None
        self.train_data: Optional[LabeledData] = None
        self.validation_data: Optional[LabeledData] = None
        self.summary: Optional[FeatureDataStatistics] = None
        self.normalization = NO_NORMALIZATION
        self.constraint_bounds = None
        self.lambda_models: list = []
        self.lambda_trackers: list = []
        self.per_model_metrics: dict = {}
        self.best: Optional[tuple] = None

    # -- stage bookkeeping (assertDriverStage/updateStage) ---------------------

    def _assert_stage(self, expected: DriverStage):
        if self.stage != expected:
            raise RuntimeError(
                f"Expected driver stage {expected.name} but it is {self.stage.name}"
            )

    def _update_stage(self, new: DriverStage):
        self.stage_history.append(self.stage)
        self.stage = new

    # -- stages ----------------------------------------------------------------

    def preprocess(self):
        args = self.args
        selected = (
            _selected_features_map(args.selected_features_file, args.intercept)
            if args.selected_features_file
            else None
        )
        raw, self.index_map = read_avro(
            args.training_data_directory, index_map=selected,
            add_intercept=args.intercept,
        )
        if raw.n == 0:
            raise ValueError("No training data found")
        self.train_data = LabeledData.build(
            raw.X, raw.labels, offsets=raw.offsets, weights=raw.weights,
            dtype=jnp.float64,
        )
        self.logger.info(
            "training data: %d samples, %d features (incl. intercept)",
            raw.n, self.index_map.size,
        )
        sanity_check_data(
            self.task, raw.labels, offsets=raw.offsets, weights=raw.weights,
            feature_shards={"global": raw.X},
            validation_type=DataValidationType(args.data_validation),
        )

        if args.validating_data_directory:
            vraw, _ = read_avro(
                args.validating_data_directory, index_map=self.index_map,
                add_intercept=args.intercept,
            )
            if vraw.n == 0:
                raise ValueError("No validation data found")
            self.validation_data = LabeledData.build(
                vraw.X, vraw.labels, offsets=vraw.offsets, weights=vraw.weights,
                dtype=jnp.float64,
            )
            sanity_check_data(
                self.task, vraw.labels, offsets=vraw.offsets, weights=vraw.weights,
                feature_shards={"global": vraw.X},
                validation_type=DataValidationType(args.data_validation),
            )

        norm_type = NormalizationType(args.normalization_type)
        if (
            args.summarization_output_dir
            or norm_type != NormalizationType.NONE
            or DiagnosticMode(args.diagnostic_mode) != DiagnosticMode.NONE
        ):
            # summarize from the host-side matrix as read (sparse stays sparse
            # — FeatureDataStatistics has a never-densify CSC path); the
            # diagnostics tier needs the summary for importance reports
            self.summary = FeatureDataStatistics.compute(
                raw.X, intercept_index=self.index_map.intercept_index
            )
            if args.summarization_output_dir:
                self._write_summary(args.summarization_output_dir)
            if norm_type != NormalizationType.NONE:
                self.normalization = NormalizationContext.build(norm_type, self.summary)

        if args.coefficient_box_constraints:
            if not self.normalization.is_identity:
                raise ValueError(
                    "Normalization and box constraints should not be used together"
                )
            self.constraint_bounds = build_bound_vectors(
                args.coefficient_box_constraints, self.index_map
            )

    def _write_summary(self, out_dir: str):
        from photon_ml_tpu.data import avro_io
        from photon_ml_tpu.io.model_io import _split_key

        os.makedirs(out_dir, exist_ok=True)
        s = self.summary

        def records():
            for j in range(self.index_map.size):
                key = self.index_map.get_feature_name(j)
                if key is None:
                    continue
                name, term = _split_key(key)
                yield {
                    "featureName": name,
                    "featureTerm": term,
                    "metrics": {
                        "mean": float(s.mean[j]),
                        "variance": float(s.variance[j]),
                        "min": float(s.min[j]),
                        "max": float(s.max[j]),
                        "numNonzeros": float(s.num_nonzeros[j]),
                    },
                }

        avro_io.write_container(
            os.path.join(out_dir, SUMMARY_FILE),
            avro_io.FEATURE_SUMMARIZATION_SCHEMA,
            records(),
        )

    def train(self):
        self.emitter.send_event(Event("TrainingStartEvent"))
        self.lambda_models, self.lambda_trackers = train_generalized_linear_model(
            self.train_data,
            self.task,
            OptimizerType(self.args.optimizer),
            self.regularization_context,
            self.reg_weights,
            normalization=self.normalization,
            max_iterations=self.args.max_number_iterations,
            tolerance=self.args.tolerance,
            constraint_bounds=self.constraint_bounds,
            use_warm_start=self.args.warm_start,
        )
        for lam, result in self.lambda_trackers:
            self.logger.info(
                "lambda=%g: %s in %d iterations (final value %.6g)",
                lam, result.reason_name(), int(result.iterations),
                float(result.value),
            )

    def validate(self):
        raw = self.validation_data
        for lam, model in self.lambda_models:
            metrics = evaluate_model(
                model, raw.X, np.asarray(raw.labels), np.asarray(raw.offsets)
            )
            self.per_model_metrics[lam] = metrics
            for name in sorted(metrics):
                self.logger.info("lambda=%g metric [%s] = %.6g", lam, name,
                                 metrics[name])
        self.best = select_best_model(
            self.task, self.lambda_models, self.per_model_metrics
        )
        self.logger.info(
            "best model: lambda=%g by %s", self.best[0], SELECTION_METRIC[self.task]
        )

    def diagnose(self, out_path: str):
        """Drive the diagnostics tier into one HTML report (REPORT_FILE).

        Document shape mirrors the reference's combined transformer
        (DiagnosticToPhysicalReportTransformer.scala:36-137): a Summary
        chapter (best lambda per metric + per-metric charts over the sweep),
        a System chapter with the actual command-line options (the
        reference's own parameters section is empty — circular-dependency
        TODO in its snapshot), and a Detailed Model Diagnostics chapter with
        one 'Model Analysis: <desc>, lambda=λ' section per swept lambda.
        Cheap per-model diagnostics (validation metrics, feature importance,
        Hosmer-Lemeshow, prediction-error independence) run for EVERY
        lambda; the expensive training diagnostics (bootstrap, fitting
        curves) run on the selected best lambda."""
        from photon_ml_tpu.diagnostics import (
            assemble_document,
            bootstrap_section,
            bootstrap_training,
            expected_magnitude_importance,
            feature_importance_section,
            fitting_diagnostic,
            fitting_section,
            hosmer_lemeshow_section,
            hosmer_lemeshow_test,
            independence_section,
            model_section,
            prediction_error_independence,
            render_html,
            variance_importance,
        )
        from photon_ml_tpu.evaluation.evaluators import rmse
        from photon_ml_tpu.evaluation.metric_map import LARGER_IS_BETTER
        from photon_ml_tpu.optimization.common import OptimizerConfig
        from photon_ml_tpu.optimization.config import GLMOptimizationConfiguration
        from photon_ml_tpu.optimization.problem import GLMOptimizationProblem

        mode = DiagnosticMode(self.args.diagnostic_mode)
        best_lambda, best_model = (
            self.best if self.best is not None else self.lambda_models[-1]
        )

        def make_problem(lam):
            return GLMOptimizationProblem(
                task=self.task,
                configuration=GLMOptimizationConfiguration(
                    optimizer_config=OptimizerConfig(
                        optimizer_type=OptimizerType(self.args.optimizer),
                        max_iterations=self.args.max_number_iterations,
                        tolerance=self.args.tolerance,
                    ),
                    regularization_context=self.regularization_context,
                    regularization_weight=lam,
                ),
                normalization=self.normalization,
            )

        model_desc = f"{self.task.value} ({self.args.optimizer})"
        model_sections = []
        for lam, model in sorted(self.lambda_models, key=lambda x: x[0]):
            subsections = []
            means = np.asarray(model.coefficients.means)
            if mode in (DiagnosticMode.VALIDATE, DiagnosticMode.ALL) and (
                self.validation_data is not None
            ):
                v = self.validation_data
                preds = np.asarray(
                    model.predict(v.X, np.asarray(v.offsets, dtype=np.float64))
                )
                labels = np.asarray(v.labels, dtype=np.float64)
                errors = labels - preds
                kt = prediction_error_independence(preds, labels)
                subsections.append(independence_section(kt, preds, errors))
            if self.summary is not None:
                subsections.append(feature_importance_section(
                    expected_magnitude_importance(
                        means, self.summary, index_map=self.index_map
                    )
                ))
                subsections.append(feature_importance_section(
                    variance_importance(
                        means, self.summary, index_map=self.index_map
                    )
                ))
            if (
                mode in (DiagnosticMode.TRAIN, DiagnosticMode.ALL)
                and lam == best_lambda
            ):
                problem = make_problem(lam)

                def factory(subset, warm):
                    glm, _ = problem.run(subset, warm)
                    return glm, glm

                fit = fitting_diagnostic(
                    self.train_data, factory, {"RMSE": rmse}, seed=11
                )
                subsections.append(fitting_section(fit))
                boot_metrics = {"RMSE": rmse}
                if self.task in (
                    TaskType.LOGISTIC_REGRESSION,
                    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
                ):
                    from photon_ml_tpu.evaluation.evaluators import auc_roc

                    boot_metrics["AUC"] = auc_roc
                boot = bootstrap_training(
                    problem, self.train_data, num_bootstraps=8, seed=7,
                    metrics=boot_metrics,
                )
                subsections.append(
                    bootstrap_section(boot, index_map=self.index_map)
                )
            if mode in (DiagnosticMode.VALIDATE, DiagnosticMode.ALL) and (
                self.validation_data is not None
                and self.task == TaskType.LOGISTIC_REGRESSION
            ):
                hl = hosmer_lemeshow_test(preds, labels)
                subsections.append(hosmer_lemeshow_section(hl))
            model_sections.append(model_section(
                model_desc, lam, self.per_model_metrics.get(lam, {}), subsections
            ))

        doc = assemble_document(
            title=f"Modeling run: {self.task.value} "
            f"(best lambda = {best_lambda:g})",
            params={
                k: v for k, v in vars(self.args).items() if k != "log_level"
            },
            metrics_by_lambda=self.per_model_metrics,
            model_sections=model_sections,
            best_is_max=dict(LARGER_IS_BETTER),
        )
        with open(out_path, "w") as f:
            f.write(render_html(doc))
        self.logger.info("diagnostic report written to %s", out_path)

    # -- orchestration (Driver.run:145-196) ------------------------------------

    def run(self):
        args = self.args
        out = args.output_directory
        self._assert_stage(DriverStage.INIT)
        with Timed("preprocess", self.logger):
            self.preprocess()
        self._update_stage(DriverStage.PREPROCESSED)

        self._assert_stage(DriverStage.PREPROCESSED)
        with Timed("train", self.logger):
            self.train()
        self._update_stage(DriverStage.TRAINED)

        if args.validating_data_directory:
            self._assert_stage(DriverStage.TRAINED)
            with Timed("validate", self.logger):
                self.validate()
            self._update_stage(DriverStage.VALIDATED)

        write_models_in_text(
            self.lambda_models, os.path.join(out, LEARNED_MODELS_TEXT), self.index_map
        )
        if self.best is not None:
            write_models_in_text(
                [self.best], os.path.join(out, BEST_MODEL_TEXT), self.index_map
            )

        if DiagnosticMode(args.diagnostic_mode) != DiagnosticMode.NONE:
            with Timed("diagnose", self.logger):
                self.diagnose(os.path.join(out, REPORT_FILE))

        with open(os.path.join(out, "stage-history.json"), "w") as f:
            json.dump(
                [s.name for s in self.stage_history + [self.stage]], f
            )
        self.emitter.send_event(Event("TrainingFinishEvent"))


def run(args: argparse.Namespace) -> dict:
    # process the output dir upfront and fail early (Driver.run:152-154)
    out = args.output_directory
    if os.path.exists(out):
        if args.delete_output_dirs_if_exist:
            shutil.rmtree(out)
        elif os.listdir(out):
            raise FileExistsError(
                f"Output directory {out!r} exists; pass --delete-output-dirs-if-exist"
            )
    os.makedirs(out, exist_ok=True)

    logger = PhotonLogger(
        os.path.join(args.output_directory, "logs", "photon.log"),
        level=args.log_level,
    )
    emitter = EventEmitter()
    emitter.send_event(Event("PhotonSetupEvent"))
    driver = LegacyDriver(args, logger, emitter)
    driver.run()
    return {
        "stage": driver.stage.name,
        "models": len(driver.lambda_models),
        "best_lambda": None if driver.best is None else driver.best[0],
    }


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    try:
        run(args)
    except Exception as e:  # pragma: no cover - CLI surface
        print(f"legacy-driver: error: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
