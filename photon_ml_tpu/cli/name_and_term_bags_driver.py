"""Distinct (name, term) extraction per feature bag, written as text.

Parity target: photon-client data/avro/NameAndTermFeatureBagsDriver.scala:1-219 —
for each configured feature bag, collect the distinct (name, term) pairs in the
data and write them one-per-line ("name<TAB>term") for downstream feature-map
building from feature-bag text files (GameDriver.prepareFeatureMapsDefault).
"""

from __future__ import annotations

import argparse
import os
import sys

from photon_ml_tpu.cli.parsers import add_version_argument
from photon_ml_tpu.data import avro_io


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="name-and-term-bags-driver",
        description="Extract distinct (name, term) feature sets per bag.",
    )
    add_version_argument(p)
    p.add_argument("--input-data-directories", required=True)
    p.add_argument("--output-directory", required=True)
    p.add_argument("--feature-bags", required=True,
                   help="Comma-separated record fields holding FeatureAvro arrays")
    return p


def run(args: argparse.Namespace) -> dict:
    bags = [b for b in args.feature_bags.split(",") if b]
    distinct: dict[str, set] = {b: set() for b in bags}
    for rec in avro_io.read_container_dir(args.input_data_directories):
        for bag in bags:
            for f in rec.get(bag) or ():
                distinct[bag].add((f["name"], f["term"]))
    os.makedirs(args.output_directory, exist_ok=True)
    counts = {}
    for bag, pairs in distinct.items():
        path = os.path.join(args.output_directory, bag)
        with open(path, "w") as f:
            for name, term in sorted(pairs):
                f.write(f"{name}\t{term}\n")
        counts[bag] = len(pairs)
    return {"counts": counts, "output_directory": args.output_directory}


def main(argv=None) -> int:
    result = run(build_arg_parser().parse_args(argv))
    for bag, count in result["counts"].items():
        print(f"{bag}: {count} features")
    return 0


if __name__ == "__main__":
    sys.exit(main())
