"""GAME serving CLI driver: replay traffic through the resilient frontend.

No Spark analog — the reference never shipped an online scorer (its GAME
serving story ends at batch score files). This driver stands up the
micro-batching :class:`~photon_ml_tpu.serving.ServingFrontend` over the
newest valid generation of a training run's checkpoint directory
(io/checkpoint.py gen-<n>/ layout) and replays Avro scoring traffic through
it in request-sized chunks — the operational smoke test for the serving
path: micro-batching, deadline shedding, and (with ``--hot-swap-watch``)
zero-downtime generational hot-swap while requests are in flight.

With ``--fleet-replicas N`` the replay runs through the serving FLEET tier
instead (serving/fleet.py): N replicas behind the ModelRouter with
round-robin + overload failover, hot-swap upgraded to replica-at-a-time
rolling rollout with a canary gate, and (``--fleet-http-port``) the HTTP
transport (serving/transport.py) listening while the replay runs.

Scores land as ScoringResultAvro part files (same format as the batch
scoring driver); a JSON stats line (QPS, p50/p99 latency, sheds broken out
by cause — overload vs deadline vs quota vs shutdown — per-generation
served-request counts, swaps, serving generation(s)) goes to the log and the
returned dict. Shed requests (deadline/overload/quota) keep their rows in
the output as NaN — sheds are explicit, never silently missing rows.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
import time

import numpy as np

from photon_ml_tpu.cli.game_scoring_driver import _write_scores
from photon_ml_tpu.cli.game_training_driver import _load_index_maps
from photon_ml_tpu.cli.parsers import (
    add_version_argument,
    parse_feature_shard_configuration,
)
from photon_ml_tpu.data.readers import read_merged_avro
from photon_ml_tpu.models.game import RandomEffectModel
from photon_ml_tpu.util import PhotonLogger, Timed
from photon_ml_tpu.util.date_range import resolve_input_paths


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game-serving-driver",
        description="Serve scoring traffic through the micro-batching frontend "
                    "from a generational checkpoint directory.",
    )
    add_version_argument(p)
    p.add_argument("--checkpoint-directory", required=True,
                   help="Generational checkpoint root (the training driver's "
                        "<--checkpoint-directory>/config_<i>): the newest "
                        "generation that passes SHA-256 verification serves")
    p.add_argument("--input-data-directories", required=True)
    p.add_argument("--input-data-date-range", default=None)
    p.add_argument("--input-data-days-range", default=None)
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--override-output-directory", action="store_true")
    p.add_argument("--feature-shard-configurations", action="append", required=True)
    p.add_argument("--index-map-directory", default=None,
                   help="Saved training index maps (<training-output>/index-maps): "
                        "serving requests must map features into the SAME global "
                        "columns the checkpointed coefficients were trained in")
    p.add_argument("--model-id", default=None)
    p.add_argument("--compilation-cache-directory", default=None)
    from photon_ml_tpu.cli.runtime import add_ingest_arguments, add_serving_arguments

    add_ingest_arguments(p)
    add_serving_arguments(p)
    p.add_argument("--log-level", default="INFO")
    p.add_argument("--application-name", default="game-serving")
    return p


def run(args: argparse.Namespace) -> dict:
    from photon_ml_tpu.cli.runtime import configure_compilation_cache, prepare_output_root
    from photon_ml_tpu.serving import FrontendConfig
    from photon_ml_tpu.serving.hotswap import GenerationWatcher, serve_from_checkpoint

    configure_compilation_cache(args)
    root = args.root_output_directory
    prepare_output_root(root, args.override_output_directory, 0, 1)
    logger = PhotonLogger(os.path.join(root, "logs", "photon.log"), level=args.log_level)
    frontend = watcher = router = http_server = None
    fleet_mode = int(getattr(args, "fleet_replicas", 0) or 0) > 0
    try:
        shard_configs = dict(
            parse_feature_shard_configuration(a)
            for a in args.feature_shard_configurations
        )
        index_maps = _load_index_maps(args.index_map_directory, shard_configs)
        missing = sorted(s for s in shard_configs if s not in index_maps)
        if missing:
            raise FileNotFoundError(
                f"No saved index maps for shard(s) {missing}; pass "
                f"--index-map-directory pointing at the training run's "
                f"<output>/index-maps"
            )

        config = FrontendConfig(
            max_batch=args.serving_max_batch,
            max_wait_ms=args.serving_max_wait_ms,
            max_queue_depth=args.serving_queue_depth,
            default_deadline_ms=args.serving_deadline_ms,
        )
        model_name = args.model_id or "default"
        if fleet_mode:
            from photon_ml_tpu.serving import ModelRouter, ReplicaSet

            with Timed("load newest generation", logger):
                replica_set = ReplicaSet.from_checkpoint(
                    args.checkpoint_directory,
                    n_replicas=args.fleet_replicas,
                    name=model_name,
                    config=config,
                )
            router = ModelRouter()
            router.add_model(model_name, replica_set)
            manager = replica_set  # GenerationWatcher duck type (check_once)
            engine = replica_set.replicas[0].engine
            logger.info(
                "serving generations %s across %d replicas",
                replica_set.generations, args.fleet_replicas,
            )
        else:
            with Timed("load newest generation", logger):
                frontend, manager = serve_from_checkpoint(
                    args.checkpoint_directory, config=config
                )
            engine = frontend.engine
            logger.info("serving generation %d", frontend.generation)
        id_tags = sorted(
            {
                m.re_type
                for _, m in engine.model
                if isinstance(m, RandomEffectModel)
            }
        )

        input_paths = resolve_input_paths(
            args.input_data_directories,
            getattr(args, "input_data_date_range", None),
            getattr(args, "input_data_days_range", None),
        )
        with Timed("read data", logger):
            data, index_maps, uids = read_merged_avro(
                input_paths, shard_configs, index_maps, id_tags,
                ingest_workers=getattr(args, "ingest_workers", None),
            )
        logger.info("replaying %d samples through the serving frontend", data.n)

        if args.hot_swap_watch:
            watcher = GenerationWatcher(
                manager, poll_interval_s=args.hot_swap_poll_seconds
            )

        if fleet_mode:
            if getattr(args, "fleet_http_port", None) is not None:
                from photon_ml_tpu.serving import FleetHTTPServer

                # warm every replica BEFORE the endpoint exists: /readyz
                # (liveness vs readiness — engine.warmed) must answer 200
                # from the first probe a front router sends, or a restarted
                # replica sits in an evicted/unready limbo for a probe cycle
                # it didn't need
                warm_req = data.select(
                    np.arange(min(data.n, int(args.serving_request_batch)))
                )
                with Timed("warm replicas (compile first bucket)", logger):
                    for replica in replica_set.replicas:
                        replica.engine.score(warm_req)
                http_server = FleetHTTPServer(
                    router, port=args.fleet_http_port
                ).start()
                logger.info(
                    "fleet HTTP endpoint listening on %s:%d (readiness: %s)",
                    http_server.host, http_server.port,
                    json.dumps(router.readiness()),
                )
            submit = lambda req: router.submit(model_name, req)  # noqa: E731
            stats_fn = router.stats
            incidents = lambda: (  # noqa: E731
                router.incidents
                + router.replica_set(model_name).incidents
                + [
                    i
                    for r in router.replica_set(model_name).replicas
                    for i in r.frontend.incidents
                ]
            )
        else:
            submit = frontend.submit
            stats_fn = frontend.stats
            incidents = lambda: frontend.incidents  # noqa: E731

        scores, stats = _replay(submit, stats_fn, data, args, logger)
        if http_server is not None:
            stats["http_endpoint"] = f"{http_server.host}:{http_server.port}"
        stats["output_directory"] = root
        stats["incidents"] = [i.to_dict() for i in incidents()]
        with Timed("write scores", logger):
            _write_scores(
                os.path.join(root, "scores", "part-00000.avro"),
                uids, scores, data, args.model_id or "",
            )
        logger.info("serving stats: %s", json.dumps(stats))
        return {"scores": scores, "stats": stats, "output_directory": root}
    finally:
        if watcher is not None:
            watcher.stop()
        if http_server is not None:
            http_server.close()
        if frontend is not None:
            frontend.close()
        if router is not None:
            router.close()
        logger.close()


def _sheds_by_cause(stats: dict) -> dict:
    """The dashboard breakout: shed counts by CAUSE (overload vs deadline vs
    quota vs shutdown) summed over the frontend — or, in fleet mode, the
    router level plus every model's replica-set aggregate (whose shed_* keys
    already sum their replicas, so the nested per-replica dicts are not
    walked again)."""
    causes = {"overload": 0, "deadline": 0, "quota": 0, "shutdown": 0}

    def add(d: dict) -> None:
        causes["overload"] += int(d.get("shed_overload", 0))
        causes["deadline"] += int(d.get("shed_deadline", 0))
        causes["quota"] += int(d.get("shed_quota", 0))
        causes["shutdown"] += int(d.get("shed_shutdown", 0))

    add(stats)
    for model_stats in (stats.get("models") or {}).values():
        add(model_stats)
    return causes


def _served_by_generation(stats: dict) -> dict:
    """Merged per-generation served-request counts across the frontend (or
    every model's replica-set aggregate in fleet mode)."""
    out: collections.Counter = collections.Counter()
    for d in [stats, *list((stats.get("models") or {}).values())]:
        for g, c in (d.get("served_by_generation") or {}).items():
            out[int(g)] += int(c)
    return {g: int(c) for g, c in sorted(out.items())}


def _replay(submit, stats_fn, data, args, logger) -> tuple[np.ndarray, dict]:
    """Windowed closed-loop replay: chunk the table into request-sized
    GameInputs, keep a bounded window of futures outstanding (so the replay
    itself cannot overload the queue it is testing), and reassemble scores in
    row order. Shed chunks stay NaN. ``submit`` is either a frontend's or the
    fleet router's; ``stats_fn`` the matching stats provider."""
    from photon_ml_tpu.serving import DeadlineExceeded, Overloaded, QuotaExceeded

    n = data.n
    chunk = max(1, int(args.serving_request_batch))
    scores = np.full(n, np.nan)
    window: collections.deque = collections.deque()
    window_cap = max(4, min(args.serving_queue_depth // 2, 64))
    served = shed = 0
    latencies = []
    generations = set()

    def drain_one():
        nonlocal served, shed
        start, stop, fut, t0 = window.popleft()
        try:
            out = fut.result(timeout=300.0)
        except (Overloaded, DeadlineExceeded, QuotaExceeded) as e:
            shed += 1
            logger.warning("request rows [%d, %d) shed: %s", start, stop, e)
            return
        latencies.append(time.perf_counter() - t0)
        scores[start:stop] = out
        generations.add(fut.generation)
        served += 1

    t_start = time.perf_counter()
    for start in range(0, n, chunk):
        stop = min(start + chunk, n)
        req = data.select(np.arange(start, stop))
        if len(window) >= window_cap:
            drain_one()
        try:
            # the deadline rides on FrontendConfig.default_deadline_ms (run()
            # wired --serving-deadline-ms there); one authoritative path
            fut = submit(req)
        except (Overloaded, DeadlineExceeded, QuotaExceeded) as e:
            shed += 1
            logger.warning("request rows [%d, %d) shed at admission: %s", start, stop, e)
            continue
        window.append((start, stop, fut, time.perf_counter()))
    while window:
        drain_one()
    elapsed = time.perf_counter() - t_start

    lat_ms = np.asarray(latencies or [0.0]) * 1e3
    stats = {
        "requests_served": served,
        "requests_shed": shed,
        "qps": round(served / elapsed, 2) if elapsed > 0 else None,
        "samples_per_sec": round(float(np.sum(~np.isnan(scores))) / elapsed, 2)
        if elapsed > 0
        else None,
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
        "generations_served": sorted(g for g in generations if g is not None),
        **stats_fn(),
    }
    stats["sheds_by_cause"] = _sheds_by_cause(stats)
    stats["served_by_generation"] = _served_by_generation(stats)
    return scores, stats


def main(argv=None) -> int:
    run(build_arg_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
