"""GAME serving CLI driver: replay traffic through the resilient frontend.

No Spark analog — the reference never shipped an online scorer (its GAME
serving story ends at batch score files). This driver stands up the
micro-batching :class:`~photon_ml_tpu.serving.ServingFrontend` over the
newest valid generation of a training run's checkpoint directory
(io/checkpoint.py gen-<n>/ layout) and replays Avro scoring traffic through
it in request-sized chunks — the operational smoke test for the serving
path: micro-batching, deadline shedding, and (with ``--hot-swap-watch``)
zero-downtime generational hot-swap while requests are in flight.

Scores land as ScoringResultAvro part files (same format as the batch
scoring driver); a JSON stats line (QPS, p50/p99 latency, sheds, swaps,
serving generation(s)) goes to the log and the returned dict. Shed requests
(deadline/overload) keep their rows in the output as NaN — sheds are
explicit, never silently missing rows.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys
import time

import numpy as np

from photon_ml_tpu.cli.game_scoring_driver import _write_scores
from photon_ml_tpu.cli.game_training_driver import _load_index_maps
from photon_ml_tpu.cli.parsers import (
    add_version_argument,
    parse_feature_shard_configuration,
)
from photon_ml_tpu.data.readers import read_merged_avro
from photon_ml_tpu.models.game import RandomEffectModel
from photon_ml_tpu.util import PhotonLogger, Timed
from photon_ml_tpu.util.date_range import resolve_input_paths


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game-serving-driver",
        description="Serve scoring traffic through the micro-batching frontend "
                    "from a generational checkpoint directory.",
    )
    add_version_argument(p)
    p.add_argument("--checkpoint-directory", required=True,
                   help="Generational checkpoint root (the training driver's "
                        "<--checkpoint-directory>/config_<i>): the newest "
                        "generation that passes SHA-256 verification serves")
    p.add_argument("--input-data-directories", required=True)
    p.add_argument("--input-data-date-range", default=None)
    p.add_argument("--input-data-days-range", default=None)
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--override-output-directory", action="store_true")
    p.add_argument("--feature-shard-configurations", action="append", required=True)
    p.add_argument("--index-map-directory", default=None,
                   help="Saved training index maps (<training-output>/index-maps): "
                        "serving requests must map features into the SAME global "
                        "columns the checkpointed coefficients were trained in")
    p.add_argument("--model-id", default=None)
    p.add_argument("--compilation-cache-directory", default=None)
    from photon_ml_tpu.cli.runtime import add_ingest_arguments, add_serving_arguments

    add_ingest_arguments(p)
    add_serving_arguments(p)
    p.add_argument("--log-level", default="INFO")
    p.add_argument("--application-name", default="game-serving")
    return p


def run(args: argparse.Namespace) -> dict:
    from photon_ml_tpu.cli.runtime import configure_compilation_cache, prepare_output_root
    from photon_ml_tpu.serving import FrontendConfig
    from photon_ml_tpu.serving.hotswap import GenerationWatcher, serve_from_checkpoint

    configure_compilation_cache(args)
    root = args.root_output_directory
    prepare_output_root(root, args.override_output_directory, 0, 1)
    logger = PhotonLogger(os.path.join(root, "logs", "photon.log"), level=args.log_level)
    frontend = watcher = None
    try:
        shard_configs = dict(
            parse_feature_shard_configuration(a)
            for a in args.feature_shard_configurations
        )
        index_maps = _load_index_maps(args.index_map_directory, shard_configs)
        missing = sorted(s for s in shard_configs if s not in index_maps)
        if missing:
            raise FileNotFoundError(
                f"No saved index maps for shard(s) {missing}; pass "
                f"--index-map-directory pointing at the training run's "
                f"<output>/index-maps"
            )

        config = FrontendConfig(
            max_batch=args.serving_max_batch,
            max_wait_ms=args.serving_max_wait_ms,
            max_queue_depth=args.serving_queue_depth,
            default_deadline_ms=args.serving_deadline_ms,
        )
        with Timed("load newest generation", logger):
            frontend, manager = serve_from_checkpoint(
                args.checkpoint_directory, config=config
            )
        logger.info("serving generation %d", frontend.generation)
        id_tags = sorted(
            {
                m.re_type
                for _, m in frontend.engine.model
                if isinstance(m, RandomEffectModel)
            }
        )

        input_paths = resolve_input_paths(
            args.input_data_directories,
            getattr(args, "input_data_date_range", None),
            getattr(args, "input_data_days_range", None),
        )
        with Timed("read data", logger):
            data, index_maps, uids = read_merged_avro(
                input_paths, shard_configs, index_maps, id_tags,
                ingest_workers=getattr(args, "ingest_workers", None),
            )
        logger.info("replaying %d samples through the serving frontend", data.n)

        if args.hot_swap_watch:
            watcher = GenerationWatcher(
                manager, poll_interval_s=args.hot_swap_poll_seconds
            )

        scores, stats = _replay(frontend, data, args, logger)
        with Timed("write scores", logger):
            _write_scores(
                os.path.join(root, "scores", "part-00000.avro"),
                uids, scores, data, args.model_id or "",
            )
        stats["output_directory"] = root
        stats["incidents"] = [i.to_dict() for i in frontend.incidents]
        logger.info("serving stats: %s", json.dumps(stats))
        return {"scores": scores, "stats": stats, "output_directory": root}
    finally:
        if watcher is not None:
            watcher.stop()
        if frontend is not None:
            frontend.close()
        logger.close()


def _replay(frontend, data, args, logger) -> tuple[np.ndarray, dict]:
    """Windowed closed-loop replay: chunk the table into request-sized
    GameInputs, keep a bounded window of futures outstanding (so the replay
    itself cannot overload the queue it is testing), and reassemble scores in
    row order. Shed chunks stay NaN."""
    from photon_ml_tpu.serving import DeadlineExceeded, Overloaded

    n = data.n
    chunk = max(1, int(args.serving_request_batch))
    scores = np.full(n, np.nan)
    window: collections.deque = collections.deque()
    window_cap = max(4, min(args.serving_queue_depth // 2, 64))
    served = shed = 0
    latencies = []
    generations = set()

    def drain_one():
        nonlocal served, shed
        start, stop, fut, t0 = window.popleft()
        try:
            out = fut.result(timeout=300.0)
        except (Overloaded, DeadlineExceeded) as e:
            shed += 1
            logger.warning("request rows [%d, %d) shed: %s", start, stop, e)
            return
        latencies.append(time.perf_counter() - t0)
        scores[start:stop] = out
        generations.add(fut.generation)
        served += 1

    t_start = time.perf_counter()
    for start in range(0, n, chunk):
        stop = min(start + chunk, n)
        req = data.select(np.arange(start, stop))
        if len(window) >= window_cap:
            drain_one()
        try:
            # the deadline rides on FrontendConfig.default_deadline_ms (run()
            # wired --serving-deadline-ms there); one authoritative path
            fut = frontend.submit(req)
        except (Overloaded, DeadlineExceeded) as e:
            shed += 1
            logger.warning("request rows [%d, %d) shed at admission: %s", start, stop, e)
            continue
        window.append((start, stop, fut, time.perf_counter()))
    while window:
        drain_one()
    elapsed = time.perf_counter() - t_start

    lat_ms = np.asarray(latencies or [0.0]) * 1e3
    stats = {
        "requests_served": served,
        "requests_shed": shed,
        "qps": round(served / elapsed, 2) if elapsed > 0 else None,
        "samples_per_sec": round(float(np.sum(~np.isnan(scores))) / elapsed, 2)
        if elapsed > 0
        else None,
        "p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
        "p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
        "generations_served": sorted(g for g in generations if g is not None),
        **frontend.stats(),
    }
    return scores, stats


def main(argv=None) -> int:
    run(build_arg_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
