"""Composite-argument grammar shared by the CLI drivers.

Parity target: photon-client io/scopt/ScoptParserHelpers.scala:1-495 — the
``key=value`` list grammar with "," as the list delimiter, "|" as the secondary
(in-value) list delimiter, and "-" as the range delimiter, used by
``--feature-shard-configurations`` and ``--coordinate-configurations``; plus
exact round-trip printing (parseFromCommandLine / printForCommandLine). Key
names match the reference constants (ScoptParserHelpers.scala:47-98) so
reference command lines work unchanged.
"""

from __future__ import annotations

import enum
from typing import Mapping, Optional

from photon_ml_tpu.data.projector import ProjectorConfig, ProjectorType
from photon_ml_tpu.estimators.config import (
    CoordinateConfiguration,
    FeatureShardConfiguration,
    FixedEffectDataConfiguration,
    RandomEffectDataConfiguration,
)
from photon_ml_tpu.optimization.common import OptimizerConfig
from photon_ml_tpu.optimization.config import (
    GLMOptimizationConfiguration,
    RegularizationContext,
)
from photon_ml_tpu.types import OptimizerType, RegularizationType

KV_DELIMITER = "="
LIST_DELIMITER = ","
SECONDARY_LIST_DELIMITER = "|"
RANGE_DELIMITER = "-"

# Feature shard configuration keys (ScoptParserHelpers.scala:47-55)
FEATURE_SHARD_CONFIG_NAME = "name"
FEATURE_SHARD_CONFIG_FEATURE_BAGS = "feature.bags"
FEATURE_SHARD_CONFIG_INTERCEPT = "intercept"

# Coordinate configuration keys (ScoptParserHelpers.scala:57-98)
COORDINATE_CONFIG_NAME = "name"
COORDINATE_DATA_CONFIG_RANDOM_EFFECT_TYPE = "random.effect.type"
COORDINATE_DATA_CONFIG_FEATURE_SHARD = "feature.shard"
COORDINATE_DATA_CONFIG_MIN_PARTITIONS = "min.partitions"
COORDINATE_DATA_CONFIG_ACTIVE_DATA_LOWER_BOUND = "active.data.lower.bound"
COORDINATE_DATA_CONFIG_ACTIVE_DATA_UPPER_BOUND = "active.data.upper.bound"
COORDINATE_DATA_CONFIG_PASSIVE_DATA_BOUND = "passive.data.bound"
COORDINATE_DATA_CONFIG_FEATURES_TO_SAMPLES_RATIO = "features.to.samples.ratio"
# TPU-build extension: shared Gaussian random projection per coordinate
COORDINATE_DATA_CONFIG_PROJECTED_DIM = "projected.dim"
COORDINATE_DATA_CONFIG_PROJECTION_SEED = "projection.seed"

COORDINATE_OPT_CONFIG_OPTIMIZER = "optimizer"
COORDINATE_OPT_CONFIG_MAX_ITER = "max.iter"
COORDINATE_OPT_CONFIG_TOLERANCE = "tolerance"
COORDINATE_OPT_CONFIG_REGULARIZATION = "regularization"
COORDINATE_OPT_CONFIG_REG_ALPHA = "reg.alpha"
COORDINATE_OPT_CONFIG_REG_WEIGHTS = "reg.weights"
COORDINATE_OPT_CONFIG_DOWN_SAMPLING_RATE = "down.sampling.rate"


class ModelOutputMode(str, enum.Enum):
    """io/ModelOutputMode.scala:20-46."""

    NONE = "NONE"
    BEST = "BEST"
    EXPLICIT = "EXPLICIT"
    TUNED = "TUNED"
    ALL = "ALL"


def parse_kv_args(arg: str) -> dict[str, str]:
    """"k1=v1,k2=v2" -> {k1: v1, k2: v2} (duplicate keys rejected)."""
    out: dict[str, str] = {}
    for part in arg.split(LIST_DELIMITER):
        part = part.strip()
        if not part:
            continue
        if KV_DELIMITER not in part:
            raise ValueError(f"Malformed key=value token {part!r} in {arg!r}")
        k, _, v = part.partition(KV_DELIMITER)
        k, v = k.strip(), v.strip()
        if k in out:
            raise ValueError(f"Duplicate key {k!r} in {arg!r}")
        out[k] = v
    return out


def _pop(kv: dict, key: str, required: bool = False, default=None):
    if key in kv:
        return kv.pop(key)
    if required:
        raise ValueError(f"Missing required key {key!r}")
    return default


def parse_feature_shard_configuration(arg: str) -> tuple[str, FeatureShardConfiguration]:
    """"name=shardA,feature.bags=bag1|bag2[,intercept=true]"
    (ScoptParserHelpers.parseFeatureShardConfiguration)."""
    kv = parse_kv_args(arg)
    name = _pop(kv, FEATURE_SHARD_CONFIG_NAME, required=True)
    bags = tuple(
        b for b in _pop(kv, FEATURE_SHARD_CONFIG_FEATURE_BAGS, required=True).split(
            SECONDARY_LIST_DELIMITER
        )
        if b
    )
    if not bags:
        raise ValueError(f"Feature shard {name!r} has no feature bags")
    intercept = _pop(kv, FEATURE_SHARD_CONFIG_INTERCEPT, default="true").lower() == "true"
    if kv:
        raise ValueError(f"Unknown feature shard config keys: {sorted(kv)}")
    return name, FeatureShardConfiguration(feature_bags=bags, has_intercept=intercept)


def parse_coordinate_configuration(arg: str) -> tuple[str, CoordinateConfiguration]:
    """One "--coordinate-configurations" composite value -> (coordinate id, config)
    (ScoptParserHelpers.parseCoordinateConfiguration). Keys per
    ScoptParserHelpers.scala:77-98; presence of random.effect.type selects the
    random-effect shape and validates fixed-only/random-only keys."""
    kv = parse_kv_args(arg)
    name = _pop(kv, COORDINATE_CONFIG_NAME, required=True)
    shard = _pop(kv, COORDINATE_DATA_CONFIG_FEATURE_SHARD, required=True)
    _pop(kv, COORDINATE_DATA_CONFIG_MIN_PARTITIONS)  # Spark-ism: accepted, unused

    optimizer = OptimizerType(_pop(kv, COORDINATE_OPT_CONFIG_OPTIMIZER, required=True).upper())
    max_iter = int(_pop(kv, COORDINATE_OPT_CONFIG_MAX_ITER, required=True))
    tolerance = float(_pop(kv, COORDINATE_OPT_CONFIG_TOLERANCE, required=True))

    reg_type = RegularizationType(
        _pop(kv, COORDINATE_OPT_CONFIG_REGULARIZATION, default="NONE").upper()
    )
    alpha = _pop(kv, COORDINATE_OPT_CONFIG_REG_ALPHA)
    reg_ctx = (
        RegularizationContext(reg_type, elastic_net_alpha=float(alpha))
        if alpha is not None
        else RegularizationContext(reg_type)
    )
    weights_raw = _pop(kv, COORDINATE_OPT_CONFIG_REG_WEIGHTS)
    reg_weights = (
        tuple(float(w) for w in weights_raw.split(SECONDARY_LIST_DELIMITER) if w)
        if weights_raw
        else ()
    )

    re_type = _pop(kv, COORDINATE_DATA_CONFIG_RANDOM_EFFECT_TYPE)
    down_sampling = float(_pop(kv, COORDINATE_OPT_CONFIG_DOWN_SAMPLING_RATE, default="1.0"))
    if re_type is None:
        for key in (
            COORDINATE_DATA_CONFIG_ACTIVE_DATA_LOWER_BOUND,
            COORDINATE_DATA_CONFIG_ACTIVE_DATA_UPPER_BOUND,
            COORDINATE_DATA_CONFIG_PASSIVE_DATA_BOUND,
            COORDINATE_DATA_CONFIG_FEATURES_TO_SAMPLES_RATIO,
            COORDINATE_DATA_CONFIG_PROJECTED_DIM,
        ):
            if key in kv:
                raise ValueError(f"{key!r} is only valid for random-effect coordinates")
        data_config = FixedEffectDataConfiguration(feature_shard_id=shard)
    else:
        if down_sampling != 1.0:
            raise ValueError("down.sampling.rate is only valid for fixed-effect coordinates")
        lower = int(_pop(kv, COORDINATE_DATA_CONFIG_ACTIVE_DATA_LOWER_BOUND, default="1"))
        upper_raw = _pop(kv, COORDINATE_DATA_CONFIG_ACTIVE_DATA_UPPER_BOUND)
        _pop(kv, COORDINATE_DATA_CONFIG_PASSIVE_DATA_BOUND)  # implied by upper bound
        ratio_raw = _pop(kv, COORDINATE_DATA_CONFIG_FEATURES_TO_SAMPLES_RATIO)
        proj_dim_raw = _pop(kv, COORDINATE_DATA_CONFIG_PROJECTED_DIM)
        proj_seed = int(_pop(kv, COORDINATE_DATA_CONFIG_PROJECTION_SEED, default="0"))
        projector = (
            ProjectorConfig(
                ProjectorType.RANDOM_PROJECTION,
                projected_dim=int(proj_dim_raw),
                seed=proj_seed,
            )
            if proj_dim_raw is not None
            else None
        )
        data_config = RandomEffectDataConfiguration(
            random_effect_type=re_type,
            feature_shard_id=shard,
            active_data_lower_bound=lower,
            active_data_upper_bound=int(upper_raw) if upper_raw is not None else None,
            # features.to.samples.ratio caps per-entity features relative to its
            # sample count; resolved against actual counts at dataset build via
            # features_max — we conservatively map ratio r to features_max only
            # when an upper bound exists (r * bound), the reference's effective cap
            features_max=(
                int(float(ratio_raw) * int(upper_raw))
                if ratio_raw is not None and upper_raw is not None
                else None
            ),
            projector=projector,
        )

    if kv:
        raise ValueError(f"Unknown coordinate config keys: {sorted(kv)}")

    return name, CoordinateConfiguration(
        data_config=data_config,
        optimization_config=GLMOptimizationConfiguration(
            optimizer_config=OptimizerConfig(
                optimizer_type=optimizer, max_iterations=max_iter, tolerance=tolerance
            ),
            regularization_context=reg_ctx,
            regularization_weight=reg_weights[0] if reg_weights else 0.0,
        ),
        reg_weights=reg_weights,
        down_sampling_rate=down_sampling,
    )


def coordinate_configuration_to_string(name: str, cfg: CoordinateConfiguration) -> str:
    """Round-trip printer (ScoptParserHelpers.coordinateConfigsToStrings)."""
    oc = cfg.optimization_config
    parts = [
        f"{COORDINATE_CONFIG_NAME}{KV_DELIMITER}{name}",
        f"{COORDINATE_DATA_CONFIG_FEATURE_SHARD}{KV_DELIMITER}{cfg.data_config.feature_shard_id}",
        f"{COORDINATE_OPT_CONFIG_OPTIMIZER}{KV_DELIMITER}{oc.optimizer_config.optimizer_type.value}",
        f"{COORDINATE_OPT_CONFIG_MAX_ITER}{KV_DELIMITER}{oc.optimizer_config.max_iterations}",
        f"{COORDINATE_OPT_CONFIG_TOLERANCE}{KV_DELIMITER}{oc.optimizer_config.tolerance}",
    ]
    reg = oc.regularization_context
    if reg.regularization_type != RegularizationType.NONE:
        parts.append(
            f"{COORDINATE_OPT_CONFIG_REGULARIZATION}{KV_DELIMITER}{reg.regularization_type.value}"
        )
        if reg.regularization_type == RegularizationType.ELASTIC_NET:
            parts.append(f"{COORDINATE_OPT_CONFIG_REG_ALPHA}{KV_DELIMITER}{reg.elastic_net_alpha}")
    if cfg.reg_weights:
        weights = SECONDARY_LIST_DELIMITER.join(str(w) for w in cfg.reg_weights)
        parts.append(f"{COORDINATE_OPT_CONFIG_REG_WEIGHTS}{KV_DELIMITER}{weights}")
    dc = cfg.data_config
    if isinstance(dc, RandomEffectDataConfiguration):
        parts.insert(
            2, f"{COORDINATE_DATA_CONFIG_RANDOM_EFFECT_TYPE}{KV_DELIMITER}{dc.random_effect_type}"
        )
        if dc.active_data_lower_bound != 1:
            parts.append(
                f"{COORDINATE_DATA_CONFIG_ACTIVE_DATA_LOWER_BOUND}{KV_DELIMITER}{dc.active_data_lower_bound}"
            )
        if dc.active_data_upper_bound is not None:
            parts.append(
                f"{COORDINATE_DATA_CONFIG_ACTIVE_DATA_UPPER_BOUND}{KV_DELIMITER}{dc.active_data_upper_bound}"
            )
        if dc.projector is not None and dc.projector.projected_dim:
            parts.append(
                f"{COORDINATE_DATA_CONFIG_PROJECTED_DIM}{KV_DELIMITER}{dc.projector.projected_dim}"
            )
            if dc.projector.seed:
                parts.append(
                    f"{COORDINATE_DATA_CONFIG_PROJECTION_SEED}{KV_DELIMITER}{dc.projector.seed}"
                )
    elif cfg.down_sampling_rate != 1.0:
        parts.append(
            f"{COORDINATE_OPT_CONFIG_DOWN_SAMPLING_RATE}{KV_DELIMITER}{cfg.down_sampling_rate}"
        )
    return LIST_DELIMITER.join(parts)


def feature_shard_configuration_to_string(name: str, cfg: FeatureShardConfiguration) -> str:
    parts = [
        f"{FEATURE_SHARD_CONFIG_NAME}{KV_DELIMITER}{name}",
        f"{FEATURE_SHARD_CONFIG_FEATURE_BAGS}{KV_DELIMITER}"
        + SECONDARY_LIST_DELIMITER.join(cfg.feature_bags),
    ]
    if not cfg.has_intercept:
        parts.append(f"{FEATURE_SHARD_CONFIG_INTERCEPT}{KV_DELIMITER}false")
    return LIST_DELIMITER.join(parts)


def parse_evaluator_spec(spec: str):
    """"AUC" -> EvaluatorType.AUC; "AUC:userId" -> per-group multi evaluator;
    "PRECISION@5:userId" -> parameterized multi evaluator (the reference's
    MultiEvaluatorType grammar, e.g. PRECISION@K with an id column)."""
    from photon_ml_tpu.evaluation.evaluators import (
        EvaluatorType,
        MultiEvaluator,
        evaluator_for_type,
    )

    spec = spec.strip()
    id_tag: Optional[str] = None
    if ":" in spec:
        spec, _, id_tag = spec.partition(":")
    k = None
    if "@" in spec:
        spec, _, k_raw = spec.partition("@")
        k = int(k_raw)
    etype = EvaluatorType(spec.upper().replace("PRECISION", "PRECISION_AT_K") if k else spec.upper())
    base = evaluator_for_type(etype, k=k) if k else evaluator_for_type(etype)
    if id_tag:
        return MultiEvaluator(base, id_tag)
    return base


def args_to_command_line(namespace, parser) -> list[str]:
    """EXACT command-line round trip (ScoptParser.printForCommandLine,
    io/scopt/ScoptParser.scala:40): render a parsed namespace back to argv
    tokens such that ``parser.parse_args(tokens)`` reproduces the namespace
    verbatim. The reference prints its ParamMap this way so any run can be
    re-launched from its own recorded output; drivers write the result as a
    ``command-line.txt`` artifact."""
    import argparse

    tokens: list[str] = []
    for action in parser._actions:
        if isinstance(
            action,
            (argparse._HelpAction, argparse._VersionAction, argparse._SubParsersAction),
        ):
            continue
        if not action.option_strings:
            continue
        long_opts = [o for o in action.option_strings if o.startswith("--")]
        opt = long_opts[0] if long_opts else action.option_strings[0]
        value = getattr(namespace, action.dest, None)
        if isinstance(action, argparse._StoreTrueAction):
            if value is True:
                tokens.append(opt)
            continue
        if isinstance(action, argparse._StoreFalseAction):
            if value is False:
                tokens.append(opt)
            continue
        if value is None:
            continue
        if isinstance(action, argparse._AppendAction):
            for v in value:
                tokens += [opt, str(v)]
            continue
        tokens += [opt, str(value)]
    return tokens


def write_command_line_artifact(path: str, namespace, parser) -> None:
    """One shell-quoted re-launchable line (the reproducibility affordance)."""
    import shlex

    with open(path, "w") as f:
        f.write(shlex.join(args_to_command_line(namespace, parser)) + "\n")


def add_version_argument(p):
    """Uniform --version flag for every driver."""
    from photon_ml_tpu import __version__

    p.add_argument(
        "--version", action="version",
        version=f"photon-ml-tpu {__version__}",
    )
