"""Shared runtime configuration for the CLI drivers."""

from __future__ import annotations


def configure_compilation_cache(args) -> None:
    """Point JAX at a persistent on-disk compilation cache when the driver was
    given --compilation-cache-directory: repeated runs skip recompiling the
    optimizer programs (jit warm start across processes)."""
    cache_dir = getattr(args, "compilation_cache_directory", None)
    if not cache_dir:
        return
    enable_compilation_cache(cache_dir)


def enable_compilation_cache(cache_dir: str, min_compile_secs: float = 0.1) -> None:
    """The one place cache policy lives (CLI drivers, bench, test conftest)."""
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", min_compile_secs)
