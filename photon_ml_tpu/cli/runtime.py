"""Shared runtime configuration for the CLI drivers."""

from __future__ import annotations


def configure_compilation_cache(args) -> None:
    """Point JAX at a persistent on-disk compilation cache when the driver was
    given --compilation-cache-directory: repeated runs skip recompiling the
    optimizer programs (jit warm start across processes)."""
    cache_dir = getattr(args, "compilation_cache_directory", None)
    if not cache_dir:
        return
    enable_compilation_cache(cache_dir)


def enable_compilation_cache(cache_dir: str, min_compile_secs: float = 0.1) -> None:
    """The one place cache policy lives (CLI drivers, bench, test conftest)."""
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", min_compile_secs)


def add_ingest_arguments(parser) -> None:
    """The shared --ingest-* runtime flag of the training and scoring drivers
    (one definition so the drivers cannot drift)."""
    parser.add_argument(
        "--ingest-workers", type=int, default=None,
        help="Avro ingest decode threads: container framing stays sequential "
             "(deterministic row order) while inflate + native block decode + "
             "columnar extraction fan out over this many workers with a "
             "bounded in-flight window — results are bitwise identical "
             "across worker counts. 1 = the sequential legacy path; default "
             "auto = min(cores, 8). See docs/PERFORMANCE.md 'Ingest & "
             "time-to-first-update'",
    )


def add_serving_arguments(parser) -> None:
    """The shared --serving-* knob block (serving driver; any future online
    endpoint reuses the same contract — docs/ARCHITECTURE.md 'Serving
    front-end & SLOs')."""
    parser.add_argument(
        "--serving-max-batch", type=int, default=4096,
        help="Micro-batching cap: coalesced samples per engine dispatch "
             "(align with the engine bucket you want to saturate)",
    )
    parser.add_argument(
        "--serving-max-wait-ms", type=float, default=2.0,
        help="Longest the oldest queued request waits for coalescing company "
             "before dispatch (the latency cost of batching)",
    )
    parser.add_argument(
        "--serving-queue-depth", type=int, default=256,
        help="Bounded request queue; submissions beyond it shed with an "
             "explicit Overloaded instead of growing a latency tail",
    )
    parser.add_argument(
        "--serving-deadline-ms", type=float, default=None,
        help="Per-request deadline: requests that cannot meet it are shed "
             "BEFORE dispatch with an explicit DeadlineExceeded (default: "
             "no deadline)",
    )
    parser.add_argument(
        "--serving-request-batch", type=int, default=512,
        help="Replay chunk size: input rows per request submitted through "
             "the frontend",
    )
    parser.add_argument(
        "--hot-swap-watch", action="store_true",
        help="Poll the checkpoint root for new generations while serving and "
             "hot-swap to them with zero downtime (integrity-verified, "
             "warmed before the flip, automatic rollback)",
    )
    parser.add_argument(
        "--hot-swap-poll-seconds", type=float, default=2.0,
        help="Generation watcher poll interval for --hot-swap-watch",
    )
    parser.add_argument(
        "--fleet-replicas", type=int, default=0,
        help="Serve through a ReplicaSet of this many replicas behind the "
             "ModelRouter instead of one frontend (serving/fleet.py): "
             "round-robin routing with overload failover, and hot-swap "
             "becomes replica-at-a-time with a canary gate (0 = single-"
             "frontend mode, the default)",
    )
    parser.add_argument(
        "--fleet-http-port", type=int, default=None,
        help="With --fleet-replicas: also expose the fleet over HTTP on this "
             "port while replaying (serving/transport.py; 0 = an ephemeral "
             "port, reported in the stats JSON as http_endpoint)",
    )


def add_distributed_arguments(parser, purpose: str) -> None:
    """The shared --distributed-* flag contract of the training and scoring
    drivers (one definition so the two cannot drift)."""
    parser.add_argument(
        "--distributed-coordinator", default=None,
        help=f"host:port of process 0 (or 'auto') for {purpose}",
    )
    parser.add_argument("--distributed-num-processes", type=int, default=None)
    parser.add_argument("--distributed-process-id", type=int, default=None)
    parser.add_argument(
        "--distributed-init-timeout", type=float, default=None,
        help="Seconds each jax.distributed.initialize attempt may wait for "
             "the coordinator (default: jax's own, 300s). See "
             "docs/ARCHITECTURE.md 'Failure model & recovery'",
    )
    parser.add_argument(
        "--distributed-init-retries", type=int, default=2,
        help="Retries (exponential backoff + jitter) when joining the "
             "distributed runtime fails — a coordinator that is still "
             "starting is an incident, not a crash. 0 = fail fast",
    )


def prepare_output_root(root: str, override: bool, rank: int, nproc: int) -> None:
    """Single-writer output-root preparation shared by the CLI drivers.

    Process 0 owns the override/exists decision. Multi-process runs exchange
    a success flag through the distributed runtime (the collective doubles as
    the ordering barrier before any peer's first write — no marker files,
    which would go stale across runs), so a rank-0 failure fails EVERY rank
    promptly instead of leaving peers blocked until the peer-loss timeout."""
    import os
    import shutil

    failure = None
    if rank == 0:
        try:
            if os.path.exists(root):
                if override:
                    shutil.rmtree(root)
                elif os.listdir(root):
                    raise FileExistsError(
                        f"Output directory {root!r} exists; "
                        f"pass --override-output-directory"
                    )
            os.makedirs(root, exist_ok=True)
        except Exception as e:  # report through the collective before raising
            failure = e
    if nproc > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([0 if (rank != 0 or failure is None) else 1])
        )
        if int(np.asarray(flags).sum()) > 0:
            if failure is not None:
                raise failure
            raise RuntimeError(
                "process 0 failed to prepare the output root "
                "(see its error for the cause)"
            )
        os.makedirs(root, exist_ok=True)  # after the barrier: root is final
    elif failure is not None:
        raise failure


def initialize_distributed_from_args(args) -> tuple[int, int]:
    """Validate the --distributed-* flags and join the JAX distributed runtime.

    MUST run before every other JAX touch (a later ``jax.distributed
    .initialize`` either errors or silently leaves the mesh host-local).
    Returns (process_id, num_processes) — (0, 1) for single-process runs."""
    coordinator = getattr(args, "distributed_coordinator", None)
    if coordinator is None and (
        getattr(args, "distributed_num_processes", None) is not None
        or getattr(args, "distributed_process_id", None) is not None
    ):
        raise ValueError(
            "--distributed-num-processes/--distributed-process-id require "
            "--distributed-coordinator (or --distributed-coordinator=auto)"
        )
    if coordinator is None:
        return 0, 1
    from photon_ml_tpu.parallel import initialize_multi_host

    world = initialize_multi_host(
        coordinator_address=None if coordinator == "auto" else coordinator,
        num_processes=getattr(args, "distributed_num_processes", None),
        process_id=getattr(args, "distributed_process_id", None),
        auto=coordinator == "auto",
        initialization_timeout=getattr(args, "distributed_init_timeout", None),
        retries=getattr(args, "distributed_init_retries", 2) or 0,
    )
    return world["process_id"], world["num_processes"]


def arm_fault_plan_from_args(args) -> None:
    """Arm the deterministic fault-injection plan (resilience/faultpoints.py)
    from --fault-plan; without the flag the PHOTON_FAULT_PLAN env var still
    applies (lazily, at the first fault point)."""
    spec = getattr(args, "fault_plan", None)
    if spec:
        from photon_ml_tpu.resilience import arm

        arm(spec)
