"""Front-router CLI driver: stand the fault-tolerant routing tier up as its
own process.

The serving driver (cli/serving_driver.py ``--fleet-http-port``) puts ONE
replica process on the wire; this driver puts the tier in FRONT of N of
them: a :class:`~photon_ml_tpu.serving.FrontRouter` (probe/evict/re-admit
membership, bounded retries under a fleet-wide budget, per-replica circuit
breakers, priority + per-tenant admission) behind a
:class:`~photon_ml_tpu.serving.RouterHTTPServer` speaking the same endpoint
surface as the replicas — clients cannot tell one tier from N processes.

Topology is static by design (the backends are the processes an operator
started; membership HEALTH is the router's job, membership IDENTITY is the
operator's), so the full deployment is::

    photon-serving-driver --fleet-replicas 2 --fleet-http-port 7101 ... &
    photon-serving-driver --fleet-replicas 2 --fleet-http-port 7102 ... &
    python -m photon_ml_tpu.cli.fleet_router_driver \\
        --backend 127.0.0.1:7101 --backend 127.0.0.1:7102 \\
        --model default=interactive --http-port 7100

Runs until SIGTERM/SIGINT (or ``--duration-s``), then prints one JSON stats
line (membership transitions, retries, retry-budget spend, sheds by cause)
to stdout — the same observability contract as the bench drivers.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading

from photon_ml_tpu.cli.parsers import add_version_argument


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-fleet-router",
        description="Fault-tolerant front router over N replica processes.",
    )
    add_version_argument(p)
    p.add_argument("--backend", action="append", required=True,
                   metavar="HOST:PORT",
                   help="replica process endpoint (repeat for each replica)")
    p.add_argument("--http-port", type=int, default=0,
                   help="front endpoint port (0 = ephemeral, printed at start)")
    p.add_argument("--http-host", default="127.0.0.1")
    p.add_argument("--model", action="append", default=[],
                   metavar="NAME=PRIORITY",
                   help="admission policy: model NAME sheds at PRIORITY "
                        "(interactive|standard|batch); unregistered models "
                        "route at 'standard', unmetered")
    p.add_argument("--tenant-quota", action="append", default=[],
                   metavar="MODEL:TENANT:RATE:BURST",
                   help="per-tenant token bucket at the router (TENANT '*' "
                        "sets the model's default quota)")
    p.add_argument("--probe-interval-s", type=float, default=0.5)
    p.add_argument("--evict-after-failures", type=int, default=2)
    p.add_argument("--readmit-after-successes", type=int, default=2)
    p.add_argument("--connect-timeout-s", type=float, default=1.0)
    p.add_argument("--read-timeout-s", type=float, default=60.0)
    p.add_argument("--max-attempts", type=int, default=3)
    p.add_argument("--retry-budget-rate", type=float, default=10.0)
    p.add_argument("--retry-budget-burst", type=float, default=20.0)
    p.add_argument("--breaker-reset-s", type=float, default=1.0)
    p.add_argument("--fleet-budget-per-replica", type=int, default=None,
                   help="in-flight cap per replica IN ROTATION; a kill "
                        "shrinks admission so low-priority traffic sheds "
                        "first (default: no budget)")
    p.add_argument("--default-deadline-ms", type=float, default=None)
    p.add_argument("--duration-s", type=float, default=None,
                   help="exit after this long (default: run until signal)")
    return p


def _parse_backend(spec: str) -> tuple[str, int]:
    host, sep, port = spec.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(f"--backend wants HOST:PORT, got {spec!r}")
    return host, int(port)


def run(args: argparse.Namespace) -> dict:
    from photon_ml_tpu.serving import (
        FrontRouter,
        RouterConfig,
        RouterHTTPServer,
        TenantQuota,
    )

    config = RouterConfig(
        probe_interval_s=args.probe_interval_s,
        evict_after_failures=args.evict_after_failures,
        readmit_after_successes=args.readmit_after_successes,
        connect_timeout_s=args.connect_timeout_s,
        read_timeout_s=args.read_timeout_s,
        max_attempts=args.max_attempts,
        retry_budget_rate=args.retry_budget_rate,
        retry_budget_burst=args.retry_budget_burst,
        breaker_reset_s=args.breaker_reset_s,
        fleet_budget_per_replica=args.fleet_budget_per_replica,
        default_deadline_ms=args.default_deadline_ms,
    )
    router = FrontRouter([_parse_backend(b) for b in args.backend], config=config)

    policies: dict = {}
    for spec in args.model:
        name, sep, priority = spec.partition("=")
        if not sep:
            raise ValueError(f"--model wants NAME=PRIORITY, got {spec!r}")
        policies[name] = {"priority": priority, "default": None, "tenants": {}}
    for spec in args.tenant_quota:
        try:
            model, tenant, rate, burst = spec.split(":")
            quota = TenantQuota(rate=float(rate), burst=float(burst))
        except ValueError as e:
            raise ValueError(
                f"--tenant-quota wants MODEL:TENANT:RATE:BURST, got {spec!r}"
            ) from e
        entry = policies.setdefault(
            model, {"priority": "standard", "default": None, "tenants": {}}
        )
        if tenant == "*":
            entry["default"] = quota
        else:
            entry["tenants"][tenant] = quota
    for name, entry in policies.items():
        router.register_model(
            name,
            priority=entry["priority"],
            tenant_quota=entry["default"],
            tenant_quotas=entry["tenants"],
        )

    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    with router, RouterHTTPServer(router, host=args.http_host,
                                  port=args.http_port) as server:
        print(
            json.dumps({
                "listening": f"{server.host}:{server.port}",
                "backends": args.backend,
                "rotation": router.rotation(),
            }),
            flush=True,
        )
        done.wait(timeout=args.duration_s)
        stats = router.stats()
        stats["incidents"] = [i.to_dict() for i in router.incidents]
    print(json.dumps(stats), flush=True)
    return stats


def main(argv=None) -> int:
    run(build_arg_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
