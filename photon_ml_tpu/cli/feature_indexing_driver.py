"""Offline feature-index building.

Parity target: photon-client index/FeatureIndexingDriver.scala:41-320 — read
Avro data, collect the distinct (name, term) set per feature shard, and write
index stores consumed at train/score time. Three formats (``--format``):

- ``npz`` (default): this framework's compact store
  (data/index_map.IndexMap.load);
- ``paldb``: REAL partitioned PalDB v1 stores under the reference's own
  partition naming — byte-compatible with the reference's reader
  (PalDBIndexMapBuilder.scala:98 / PalDBIndexMap.scala:43-278), closing the
  interop round trip in both directions (data/paldb.py reads reference-built
  stores; this writes stores reference tooling can read);
- ``offheap``: the mmap store in data/offheap_index.py for feature spaces too
  large to materialize.
"""

from __future__ import annotations

import argparse
import os
import sys

from photon_ml_tpu.cli.parsers import add_version_argument, parse_feature_shard_configuration
from photon_ml_tpu.data import avro_io
from photon_ml_tpu.data.index_map import IndexMap, feature_key


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="feature-indexing-driver",
        description="Build per-shard feature index maps from Avro data.",
    )
    add_version_argument(p)
    p.add_argument("--input-data-directories", required=True)
    p.add_argument("--output-directory", required=True)
    p.add_argument("--feature-shard-configurations", action="append", required=True)
    p.add_argument(
        "--num-partitions", type=int, default=1,
        help="partition count for partitioned store formats (paldb/offheap)",
    )
    p.add_argument(
        "--format", choices=("npz", "paldb", "offheap"), default="npz",
        help="index store format: npz (this framework's compact store), "
        "paldb (real partitioned PalDB v1 stores, readable by the reference's "
        "own tooling), offheap (mmap store for very large feature spaces)",
    )
    return p


def run(args: argparse.Namespace) -> dict:
    shard_configs = dict(
        parse_feature_shard_configuration(a) for a in args.feature_shard_configurations
    )
    keys: dict[str, set] = {s: set() for s in shard_configs}
    for rec in avro_io.read_container_dir(args.input_data_directories):
        for shard, cfg in shard_configs.items():
            for bag in cfg.feature_bags:
                for f in rec.get(bag) or ():
                    keys[shard].add(feature_key(f["name"], f["term"]))
    os.makedirs(args.output_directory, exist_ok=True)
    fmt = getattr(args, "format", "npz")
    sizes = {}
    for shard, cfg in shard_configs.items():
        imap = IndexMap.build(keys[shard], add_intercept=cfg.has_intercept)
        if fmt == "paldb":
            # real PalDB v1 stores under the reference's own partition naming
            # (PalDBIndexMapBuilder.scala:98): reference tooling reads these,
            # and _load_index_maps picks them up at train/score time.
            from photon_ml_tpu.data import paldb

            paldb.write_paldb_index_map(
                args.output_directory, shard, imap.keys(), args.num_partitions
            )
        elif fmt == "offheap":
            from photon_ml_tpu.data.offheap_index import OffHeapIndexMapBuilder

            OffHeapIndexMapBuilder(
                os.path.join(args.output_directory, shard), args.num_partitions
            ).put_all(imap.keys()).build()
        else:
            imap.save(os.path.join(args.output_directory, shard))
        sizes[shard] = imap.size
    return {"sizes": sizes, "output_directory": args.output_directory, "format": fmt}


def main(argv=None) -> int:
    result = run(build_arg_parser().parse_args(argv))
    for shard, size in result["sizes"].items():
        print(f"{shard}: {size} features")
    return 0


if __name__ == "__main__":
    sys.exit(main())
