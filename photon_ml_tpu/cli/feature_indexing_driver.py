"""Offline feature-index building.

Parity target: photon-client index/FeatureIndexingDriver.scala:41-320 — read
Avro data, collect the distinct (name, term) set per feature shard, and write
index stores consumed at train/score time (the reference writes partitioned
PalDB files read per-executor off-heap; here one compact .npz per shard, loaded
via data/index_map.IndexMap.load, or the mmap store in data/offheap_index.py
for very large feature spaces).
"""

from __future__ import annotations

import argparse
import os
import sys

from photon_ml_tpu.cli.parsers import add_version_argument, parse_feature_shard_configuration
from photon_ml_tpu.data import avro_io
from photon_ml_tpu.data.index_map import IndexMap, feature_key


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="feature-indexing-driver",
        description="Build per-shard feature index maps from Avro data.",
    )
    add_version_argument(p)
    p.add_argument("--input-data-directories", required=True)
    p.add_argument("--output-directory", required=True)
    p.add_argument("--feature-shard-configurations", action="append", required=True)
    p.add_argument("--num-partitions", type=int, default=1, help=argparse.SUPPRESS)
    return p


def run(args: argparse.Namespace) -> dict:
    shard_configs = dict(
        parse_feature_shard_configuration(a) for a in args.feature_shard_configurations
    )
    keys: dict[str, set] = {s: set() for s in shard_configs}
    for rec in avro_io.read_container_dir(args.input_data_directories):
        for shard, cfg in shard_configs.items():
            for bag in cfg.feature_bags:
                for f in rec.get(bag) or ():
                    keys[shard].add(feature_key(f["name"], f["term"]))
    os.makedirs(args.output_directory, exist_ok=True)
    sizes = {}
    for shard, cfg in shard_configs.items():
        imap = IndexMap.build(keys[shard], add_intercept=cfg.has_intercept)
        imap.save(os.path.join(args.output_directory, shard))
        sizes[shard] = imap.size
    return {"sizes": sizes, "output_directory": args.output_directory}


def main(argv=None) -> int:
    result = run(build_arg_parser().parse_args(argv))
    for shard, size in result["sizes"].items():
        print(f"{shard}: {size} features")
    return 0


if __name__ == "__main__":
    sys.exit(main())
