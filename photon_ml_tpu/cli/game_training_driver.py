"""GAME training CLI driver.

Parity target: photon-client cli/game/training/GameTrainingDriver.scala:55-855 —
the end-to-end training pipeline: feature maps -> Avro read -> validation ->
stats/normalization -> coordinate-config grid -> GameEstimator.fit (warm-started
sweep) -> hyperparameter tuning -> model selection -> model + metadata save.
Flag names mirror the reference's scopt parser (param name with spaces ->
dashes), so reference invocations translate 1:1; Spark-only flags
(min.partitions, tree aggregate depth) are accepted and ignored.

Output layout (GameTrainingDriver.scala:71-73, 768-825):
    <root>/best/...            best model by validation metric (or last config)
    <root>/models/<i>/...      one dir per trained configuration (OUTPUT mode ALL)
    each model dir: model files (model_io layout) + model-spec.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

import numpy as np

from photon_ml_tpu.cli.parsers import (
    add_version_argument,
    ModelOutputMode,
    coordinate_configuration_to_string,
    parse_coordinate_configuration,
    parse_evaluator_spec,
    parse_feature_shard_configuration,
)
from photon_ml_tpu.data import avro_io
from photon_ml_tpu.data.index_map import IndexMap
from photon_ml_tpu.data.readers import read_merged_avro
from photon_ml_tpu.data.validators import DataValidationType, sanity_check_data
from photon_ml_tpu.estimators.config import RandomEffectDataConfiguration
from photon_ml_tpu.estimators.evaluation_function import GameEstimatorEvaluationFunction
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.hyperparameter.tuner import build_tuner
from photon_ml_tpu.io.model_io import load_game_model, save_game_model
from photon_ml_tpu.normalization import FeatureDataStatistics, NormalizationContext
from photon_ml_tpu.types import (
    HyperparameterTuningMode,
    NormalizationType,
    TaskType,
    VarianceComputationType,
)
from photon_ml_tpu.util import Event, EventEmitter, PhotonLogger, Timed
from photon_ml_tpu.util.date_range import resolve_input_paths

BEST_DIR = "best"
MODELS_DIR = "models"
MODEL_SPEC_FILE = "model-spec.json"
SUMMARY_FILE = "feature-summary.avro"


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game-training-driver",
        description="Train a GAME (GLMix) model on TPU.",
    )
    add_version_argument(p)
    # GameDriver shared params (GameDriver.scala:56-131)
    p.add_argument("--input-data-directories", required=True,
                   help="Comma-separated training data paths (Avro files/dirs)")
    p.add_argument("--validation-data-directories", default=None)
    p.add_argument("--input-data-date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd inclusive; expands each input dir to "
                        "its <dir>/yyyy/MM/dd day partitions")
    p.add_argument("--input-data-days-range", default=None,
                   help="START-END in days ago (START >= END), e.g. 90-1")
    p.add_argument("--validation-data-date-range", default=None)
    p.add_argument("--validation-data-days-range", default=None)
    p.add_argument("--off-heap-index-map-directory", default=None,
                   help="Directory of per-shard saved index maps (<shard>.npz)")
    p.add_argument("--model-input-directory", default=None,
                   help="Warm-start / partial-retrain model directory")
    p.add_argument("--evaluators", default=None,
                   help="Comma-separated evaluators, e.g. AUC,RMSE,PRECISION@5:userId")
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--override-output-directory", action="store_true")
    p.add_argument("--feature-shard-configurations", action="append", required=True,
                   help='e.g. "name=shardA,feature.bags=features,intercept=true"')
    p.add_argument("--data-validation", default="VALIDATE_DISABLED",
                   choices=[m.value for m in DataValidationType])
    p.add_argument("--log-level", default="INFO")
    p.add_argument("--application-name", default="game-training")
    # GameTrainingDriver params (GameTrainingDriver.scala:82-173)
    p.add_argument("--training-task", required=True,
                   choices=[t.value for t in TaskType])
    p.add_argument("--coordinate-configurations", action="append", required=True)
    p.add_argument("--coordinate-update-sequence", required=True,
                   help="Comma-separated coordinate names, update order")
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--partial-retrain-locked-coordinates", default=None)
    p.add_argument("--normalization", default="NONE",
                   choices=[n.value for n in NormalizationType])
    p.add_argument("--data-summary-directory", default=None)
    p.add_argument("--output-mode", default="BEST",
                   choices=[m.value for m in ModelOutputMode])
    p.add_argument("--hyper-parameter-tuner", default="ATLAS")
    p.add_argument("--hyper-parameter-tuning", default="NONE",
                   choices=[m.value for m in HyperparameterTuningMode])
    p.add_argument("--hyper-parameter-tuning-iterations", type=int, default=10)
    p.add_argument("--variance-computation-type", default="NONE",
                   choices=[v.value for v in VarianceComputationType])
    p.add_argument("--model-sparsity-threshold", type=float, default=0.0)
    p.add_argument("--ignore-threshold-for-new-models", action="store_true")
    p.add_argument("--coefficient-box-constraints", default=None,
                   help='JSON array of {"name","term","lowerBound","upperBound"} '
                        "maps; wildcard '*' in term (or name+term) supported. "
                        "Applies to fixed-effect coordinates.")
    p.add_argument("--compute-backend", default="host",
                   choices=["host", "mesh", "fused"],
                   help="'mesh' places datasets/models over a jax.sharding.Mesh "
                        "so the coordinate-descent pass runs as sharded SPMD "
                        "programs (the reference's distributed path); 'fused' "
                        "runs each coordinate-descent pass as ONE jitted SPMD "
                        "program (eligible configurations only — L2, no "
                        "normalization/constraints/down-sampling; validation "
                        "tracked per pass), optionally over --mesh-devices")
    p.add_argument("--mesh-devices", type=int, default=None,
                   help="Device count for --compute-backend=mesh/fused "
                        "(default: all)")
    from photon_ml_tpu.cli.runtime import add_distributed_arguments, add_ingest_arguments

    add_distributed_arguments(
        p, "multi-host training (jax.distributed runtime init)"
    )
    add_ingest_arguments(p)
    p.add_argument("--mesh-model-devices", type=int, default=1,
                   help="Shard the dense fixed-effect FEATURE axis over this many "
                        "devices (2-D data x model mesh; coefficients and optimizer "
                        "state live distributed). 1 = pure data/entity parallelism")
    p.add_argument("--checkpoint-directory", default=None,
                   help="Enable iteration-level checkpoint/resume: coordinate "
                        "descent saves models here after each iteration and a "
                        "rerun with the same directory resumes from the last "
                        "completed iteration")
    p.add_argument("--checkpoint-interval", type=int, default=1,
                   help="Save every k-th coordinate-descent iteration")
    p.add_argument("--checkpoint-keep-generations", type=int, default=3,
                   help="Checkpoint generations retained for integrity "
                        "rollback: restore verifies checksums and falls back "
                        "to the newest valid generation")
    p.add_argument("--fault-plan", default=None,
                   help="Deterministic fault injection plan, e.g. "
                        "'checkpoint.write.manifest:crash:2' (also via the "
                        "PHOTON_FAULT_PLAN env var; resilience/faultpoints.py)")
    p.add_argument("--compilation-cache-directory", default=None,
                   help="Persistent XLA compilation cache: repeated runs skip "
                        "recompiling the optimizer programs (jit warm start "
                        "across processes)")
    p.add_argument("--fe-storage-dtype", default=None, choices=["bf16"],
                   help="Store dense fixed-effect features in bfloat16 (half "
                        "the HBM traffic; f32 accumulation on the MXU). "
                        "Validate metric parity for your workload first")
    p.add_argument("--re-storage-dtype", default=None, choices=["bf16"],
                   help="Store random-effect bucket blocks + scoring values "
                        "in bfloat16 on the fused pass (the profiled hot "
                        "loops; coefficients and accumulation stay f32)")
    p.add_argument("--profile-output-directory", default=None,
                   help="Capture an XLA/TPU profiler trace of the training "
                        "phase (open with TensorBoard or xprof) — the "
                        "TPU-native analog of the reference's Timed sections")
    # Spark-isms accepted for 1:1 invocation compatibility (no-ops here)
    p.add_argument("--min-validation-partitions", type=int, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--tree-aggregate-depth", type=int, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--timezone", default=None, help=argparse.SUPPRESS)
    return p


def _load_index_maps(directory: Optional[str], shard_ids) -> dict:
    """Per-shard saved index maps (GameDriver.prepareFeatureMapsDefault:
    185-205), trying each store format the feature-indexing driver can emit:
    this framework's <dir>/<shard>.npz, the mmap off-heap store
    (<dir>/<shard>/meta, data/offheap_index.py), or partitioned PalDB stores
    (paldb-partition-<shard>-<i>.dat) — including reference-built ones,
    decoded natively by data/paldb.py so reference index directories work
    unchanged."""
    if directory is None:
        return {}
    from photon_ml_tpu.data import paldb
    from photon_ml_tpu.data.offheap_index import OffHeapIndexMap

    out = {}
    for shard in shard_ids:
        path = os.path.join(directory, f"{shard}.npz")
        if os.path.exists(path):
            out[shard] = IndexMap.load(path)
        elif os.path.exists(os.path.join(directory, shard, "meta")):
            out[shard] = OffHeapIndexMap(os.path.join(directory, shard))
        else:
            partitions = paldb.discover_partitions(directory, shard)
            if partitions:
                out[shard] = paldb.load_paldb_index_map(directory, shard, partitions)
    return out


def _write_feature_summary(path: str, shard_id: str, imap: IndexMap,
                           stats: FeatureDataStatistics) -> None:
    """FeatureSummarizationResultAvro records per feature
    (ModelProcessingUtils.writeBasicStatistics:516-606)."""
    from photon_ml_tpu.io.model_io import _split_key

    def records():
        for j in range(len(stats.mean)):
            name, term = _split_key(imap.get_feature_name(j) or str(j))
            yield {
                "featureName": name,
                "featureTerm": term,
                "metrics": {
                    "count": float(stats.count),
                    "mean": float(stats.mean[j]),
                    "variance": float(stats.variance[j]),
                    "min": float(stats.min[j]),
                    "max": float(stats.max[j]),
                    "numNonzeros": float(stats.num_nonzeros[j]),
                    "meanAbs": float(stats.mean_abs[j]),
                },
            }

    os.makedirs(os.path.dirname(path), exist_ok=True)
    avro_io.write_container(path, avro_io.FEATURE_SUMMARIZATION_SCHEMA, records())


def _save_result(out_dir: str, result, index_maps_by_coord, coord_configs,
                 sparsity_threshold, logger):
    import dataclasses as _dc

    os.makedirs(out_dir, exist_ok=True)
    save_game_model(
        out_dir,
        result.best_model,
        index_maps_by_coord,
        sparsity_threshold=sparsity_threshold,
        extra_metadata={
            "evaluations": result.evaluations,
            "bestMetric": result.best_metric,
        },
    )
    # model-spec records the EXPANDED config actually trained, keeping each
    # coordinate's REAL data configuration (shard, random-effect type, bounds)
    # so the recorded spec round-trips through the parser
    spec = {
        cid: coordinate_configuration_to_string(
            cid,
            _dc.replace(
                coord_configs[cid],
                optimization_config=result.configuration[cid],
                reg_weights=(result.configuration[cid].regularization_weight,)
                if result.configuration[cid].regularization_weight
                else (),
            ),
        )
        for cid in result.configuration
    }
    with open(os.path.join(out_dir, MODEL_SPEC_FILE), "w") as f:
        json.dump(spec, f, indent=2)
    logger.info("saved model to %s", out_dir)


def run(args: argparse.Namespace, emitter: Optional[EventEmitter] = None) -> dict:
    """Full training pipeline (GameTrainingDriver.run:346-482). Returns a summary
    dict {"results": [...], "best_index": i, "output_directory": ...}."""
    # Cross-flag validation BEFORE any expensive work (ingest, model load):
    # only the fused pass consumes the RE storage dtype.
    if (
        getattr(args, "re_storage_dtype", None)
        and getattr(args, "compute_backend", "host") != "fused"
    ):
        raise SystemExit(
            "--re-storage-dtype requires --compute-backend fused "
            "(the host/mesh paths do not consume it)"
        )
    # Multi-host init must precede EVERY other JAX touch (model loading,
    # data placement): jax.distributed.initialize after backend init either
    # errors or silently leaves the "global" mesh host-local.
    from photon_ml_tpu.cli.runtime import (
        arm_fault_plan_from_args,
        configure_compilation_cache,
        initialize_distributed_from_args,
        prepare_output_root,
    )

    # fault plan first: distributed.init is itself an injectable fault point
    arm_fault_plan_from_args(args)
    rank, nproc = initialize_distributed_from_args(args)
    configure_compilation_cache(args)
    emitter = emitter or EventEmitter()
    root = args.root_output_directory
    prepare_output_root(root, args.override_output_directory, rank, nproc)
    logger = PhotonLogger(
        os.path.join(
            root, "logs", "photon.log" if nproc == 1 else f"photon-r{rank}.log"
        ),
        level=args.log_level,
    )
    emitter.send_event(Event("PhotonSetupEvent", {"applicationName": args.application_name}))
    if rank == 0:
        # printForCommandLine parity (ScoptParser.scala:40): the run's exact
        # re-launchable command line, recorded next to its outputs
        from photon_ml_tpu.cli.parsers import write_command_line_artifact

        write_command_line_artifact(
            os.path.join(root, "command-line.txt"), args, build_arg_parser()
        )

    try:
        task = TaskType(args.training_task)

        shard_configs = dict(
            parse_feature_shard_configuration(a) for a in args.feature_shard_configurations
        )
        coord_configs = dict(
            parse_coordinate_configuration(a) for a in args.coordinate_configurations
        )
        update_sequence = [c for c in args.coordinate_update_sequence.split(",") if c]
        unknown = set(update_sequence) - set(coord_configs)
        if unknown:
            raise ValueError(f"Update sequence references unknown coordinates: {sorted(unknown)}")
        # estimator trains in coordinate_configurations insertion order = sequence
        coord_configs = {c: coord_configs[c] for c in update_sequence}
        # parse evaluator specs ONCE (reused for the suite below); per-group
        # evaluators' id tags must be read from the VALIDATION data even for
        # fixed-effect-only configs (AUC:userId needs the userId column) —
        # but only there: training data doesn't need them
        from photon_ml_tpu.evaluation.evaluators import MultiEvaluator

        evaluator_specs = (
            [parse_evaluator_spec(e) for e in args.evaluators.split(",") if e.strip()]
            if args.evaluators
            else []
        )
        evaluator_tags = sorted({
            ev.id_tag for ev in evaluator_specs if isinstance(ev, MultiEvaluator)
        })
        id_tags = sorted(
            {
                cfg.data_config.random_effect_type
                for cfg in coord_configs.values()
                if isinstance(cfg.data_config, RandomEffectDataConfiguration)
            }
        )

        index_maps = _load_index_maps(args.off_heap_index_map_directory, shard_configs)

        if nproc > 1:
            # multi-process training: fixed-effect-only configs run
            # per-process sharded ingest + global collectives; GAME configs
            # route through the entity exchange (docs/DISTRIBUTED.md) —
            # anything either path cannot reproduce fails loudly with reasons
            from photon_ml_tpu.cli.distributed_training import (
                run_multiprocess_fixed_effect,
                run_multiprocess_game,
            )

            has_re = any(
                isinstance(c.data_config, RandomEffectDataConfiguration)
                for c in coord_configs.values()
            )
            runner = run_multiprocess_game if has_re else run_multiprocess_fixed_effect
            emitter.send_event(Event("TrainingStartEvent"))
            summary = runner(
                args, rank, nproc, logger, root,
                task, coord_configs, shard_configs, index_maps,
            )
            emitter.send_event(
                Event("TrainingFinishEvent", {"bestIndex": summary["best_index"]})
            )
            return summary

        # date-partitioned inputs (GameDriver inputDataDateRange/DaysRange params;
        # IOUtils.getInputPathsWithinDateRange path expansion)
        train_paths = resolve_input_paths(
            args.input_data_directories,
            getattr(args, "input_data_date_range", None),
            getattr(args, "input_data_days_range", None),
        )

        # XLA backend init + pilot compile on a background thread: that
        # latency hides behind the host-side ingest below instead of adding
        # to time-to-first-update (estimator warm-up hook, data/pipeline.py)
        GameEstimator.warm_up_backend()
        ingest_workers = getattr(args, "ingest_workers", None)
        with Timed("read training data", logger):
            train_input, index_maps, _uids = read_merged_avro(
                train_paths, shard_configs, index_maps, id_tags,
                ingest_workers=ingest_workers,
            )
        logger.info("training data: %d samples, shards %s",
                    train_input.n, {s: m.shape[1] for s, m in train_input.features.items()})

        validation_input = None
        if args.validation_data_directories:
            validation_paths = resolve_input_paths(
                args.validation_data_directories,
                getattr(args, "validation_data_date_range", None),
                getattr(args, "validation_data_days_range", None),
            )
            with Timed("read validation data", logger):
                validation_input, _, _ = read_merged_avro(
                    validation_paths, shard_configs, index_maps,
                    sorted(set(id_tags) | set(evaluator_tags)),
                    ingest_workers=ingest_workers,
                )

        with Timed("data validation", logger):
            sanity_check_data(
                task,
                train_input.labels,
                offsets=train_input.offsets,
                weights=train_input.weights,
                feature_shards=train_input.features,
                validation_type=DataValidationType(args.data_validation),
            )

        # -- statistics + normalization (GameTrainingDriver.run:430-436) --------
        normalization_contexts = None
        norm_type = NormalizationType(args.normalization)
        if norm_type != NormalizationType.NONE or args.data_summary_directory:
            normalization_contexts = {}
            for shard, X in train_input.features.items():
                icpt = index_maps[shard].intercept_index
                with Timed(f"feature statistics [{shard}]", logger):
                    stats = FeatureDataStatistics.compute(X, intercept_index=icpt)
                if args.data_summary_directory:
                    _write_feature_summary(
                        os.path.join(args.data_summary_directory, f"{shard}-{SUMMARY_FILE}"),
                        shard, index_maps[shard], stats,
                    )
                if norm_type != NormalizationType.NONE:
                    normalization_contexts[shard] = NormalizationContext.build(norm_type, stats)
            if norm_type == NormalizationType.NONE:
                normalization_contexts = None

        # -- per-feature box constraints (COEFFICIENT_BOX_CONSTRAINTS param;
        # GLMSuite.createConstraintFeatureMap -> optimizer-native bounds) -------
        if args.coefficient_box_constraints:
            import dataclasses as _dc

            from photon_ml_tpu.estimators.config import FixedEffectDataConfiguration
            from photon_ml_tpu.optimization.constraints import build_bound_vectors

            coord_configs = {
                cid: (
                    _dc.replace(
                        cfg,
                        box_constraints=build_bound_vectors(
                            args.coefficient_box_constraints,
                            index_maps[cfg.data_config.feature_shard_id],
                        ),
                    )
                    if isinstance(cfg.data_config, FixedEffectDataConfiguration)
                    else cfg
                )
                for cid, cfg in coord_configs.items()
            }

        # -- warm start / partial retrain (GameTrainingDriver.scala:370-409) ----
        initial_model = None
        index_maps_by_coord = {
            cid: index_maps[cfg.data_config.feature_shard_id]
            for cid, cfg in coord_configs.items()
        }
        if args.model_input_directory:
            with Timed("load initial model", logger):
                initial_model = load_game_model(args.model_input_directory, index_maps_by_coord)
        locked = (
            [c for c in args.partial_retrain_locked_coordinates.split(",") if c]
            if args.partial_retrain_locked_coordinates
            else []
        )


        fe_storage_dtype = re_storage_dtype = None
        if getattr(args, "fe_storage_dtype", None) == "bf16":
            import jax.numpy as jnp

            fe_storage_dtype = jnp.bfloat16
        if getattr(args, "re_storage_dtype", None) == "bf16":
            import jax.numpy as jnp

            re_storage_dtype = jnp.bfloat16

        mesh = None
        backend = getattr(args, "compute_backend", "host")
        if backend == "mesh":
            n_model = getattr(args, "mesh_model_devices", 1) or 1
            if n_model > 1:
                import jax

                from photon_ml_tpu.parallel import make_mesh2

                total = args.mesh_devices or len(jax.devices())
                if total % n_model:
                    raise ValueError(
                        f"--mesh-model-devices={n_model} must divide the device "
                        f"count {total}"
                    )
                mesh = make_mesh2(total // n_model, n_model)
            else:
                from photon_ml_tpu.parallel.mesh import make_mesh

                mesh = make_mesh(args.mesh_devices)

        if backend == "fused":
            n_model = getattr(args, "mesh_model_devices", 1) or 1
            if n_model > 1:
                # build the 2-D mesh so the fused eligibility check rejects it
                # with its own reason instead of silently dropping the
                # feature-axis sharding
                import jax

                from photon_ml_tpu.parallel import make_mesh2

                total = args.mesh_devices or len(jax.devices())
                mesh = make_mesh2(total // n_model, n_model)
            else:
                from photon_ml_tpu.parallel.mesh import make_mesh

                # default all devices, same as --compute-backend=mesh
                mesh = make_mesh(args.mesh_devices)

        estimator = GameEstimator(
            task=task,
            coordinate_configurations=coord_configs,
            n_iterations=args.coordinate_descent_iterations,
            normalization_contexts=normalization_contexts,
            variance_computation=VarianceComputationType(args.variance_computation_type),
            validation_evaluators=evaluator_specs,
            partial_retrain_locked_coordinates=locked,
            mesh=mesh,
            checkpoint_directory=args.checkpoint_directory,
            checkpoint_interval=args.checkpoint_interval,
            checkpoint_keep_generations=getattr(
                args, "checkpoint_keep_generations", 3
            ),
            fe_storage_dtype=fe_storage_dtype,
            re_storage_dtype=re_storage_dtype,
            fused_pass=backend == "fused",
        )

        emitter.send_event(Event("TrainingStartEvent"))
        import contextlib

        profile_dir = getattr(args, "profile_output_directory", None)
        if profile_dir:
            import jax

            profiler_cm = jax.profiler.trace(profile_dir)
        else:
            profiler_cm = contextlib.nullcontext()
        with profiler_cm:
            with Timed("train", logger):
                results = estimator.fit(
                    train_input, validation_data=validation_input, initial_model=initial_model
                )

        # -- hyperparameter tuning (GameTrainingDriver.runHyperparameterTuning) --
        tuning_mode = HyperparameterTuningMode(args.hyper_parameter_tuning)
        tuned_results = []
        if tuning_mode != HyperparameterTuningMode.NONE:
            if validation_input is None:
                raise ValueError("Hyperparameter tuning requires validation data")
            base_configs = results[-1].configuration
            primary = estimator.prepare_evaluation_suite(validation_input).evaluators[0]
            is_max = getattr(primary, "larger_is_better", True)
            fn = GameEstimatorEvaluationFunction(
                estimator=estimator,
                base_configs=base_configs,
                data=train_input,
                validation_data=validation_input,
                is_opt_max=is_max,
            )
            observations = fn.convert_observations(results)
            tuner = build_tuner(args.hyper_parameter_tuner)
            with Timed("hyperparameter tuning", logger):
                tuned_results = tuner.search(
                    args.hyper_parameter_tuning_iterations,
                    fn.num_params,
                    tuning_mode,
                    fn,
                    observations,
                )
            results = results + list(tuned_results)

        # -- model selection (GameTrainingDriver.selectBestModel:683-748) -------
        evaluated = [i for i, r in enumerate(results) if r.best_metric is not None]
        if evaluated:
            primary = estimator.prepare_evaluation_suite(validation_input).evaluators[0]
            bigger_better = getattr(primary, "larger_is_better", True)
            pick = max if bigger_better else min
            best_index = int(pick(evaluated, key=lambda i: results[i].best_metric))
        else:
            best_index = len(results) - 1  # no validation: last trained config
        logger.info("selected model %d of %d", best_index, len(results))

        # -- save (GameTrainingDriver.scala:759-826) -----------------------------
        output_mode = ModelOutputMode(args.output_mode)
        if output_mode != ModelOutputMode.NONE:
            _save_result(
                os.path.join(root, BEST_DIR), results[best_index], index_maps_by_coord,
                coord_configs, args.model_sparsity_threshold, logger,
            )
            if output_mode in (ModelOutputMode.ALL, ModelOutputMode.EXPLICIT, ModelOutputMode.TUNED):
                to_save = (
                    range(len(results))
                    if output_mode == ModelOutputMode.ALL
                    else range(len(results) - len(tuned_results), len(results))
                    if output_mode == ModelOutputMode.TUNED
                    else range(len(results) - len(tuned_results))
                )
                for i in to_save:
                    _save_result(
                        os.path.join(root, MODELS_DIR, str(i)), results[i],
                        index_maps_by_coord, coord_configs,
                        args.model_sparsity_threshold, logger,
                    )
            # persist index maps next to the models for scoring-time reuse
            for shard, imap in index_maps.items():
                imap.save(os.path.join(root, "index-maps", shard))

        # -- incident report: survived failures (rejected divergent updates,
        # checkpoint rollbacks) are an artifact, not just log lines ----------
        incidents = [
            inc.to_dict()
            for r in results
            if getattr(r, "descent", None) is not None
            for inc in getattr(r.descent, "incidents", [])
        ]
        if incidents:
            for inc in incidents:
                logger.warning("incident: %s", inc)
            with open(os.path.join(root, "incidents.json"), "w") as f:
                json.dump(incidents, f, indent=2)

        emitter.send_event(Event("TrainingFinishEvent", {"bestIndex": best_index}))
        return {
            "results": results,
            "best_index": best_index,
            "output_directory": root,
            "incidents": incidents,
        }
    finally:
        logger.close()


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    run(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
