"""Multi-process FIXED-EFFECT training for the CLI driver.

Each process reads its round-robin slice of the input part files, pads its
block to the common per-process row count with weight-0 rows, and assembles
GLOBAL batch-sharded arrays (``host_local_to_global``) over a mesh spanning
every process's devices — gradient reductions then cross processes as real
collectives, the reference's executor/treeAggregate topology with XLA
collectives in place of Spark (ValueAndGradientAggregator.scala:240-255).

Scope: single fixed-effect coordinate, NONE/L2/L1/elastic regularization
sweep with warm starts, optional validation AUC selection. Random-effect
coordinates need the cross-process entity exchange designed in
docs/DISTRIBUTED.md — configurations containing them fail loudly with that
pointer. The feature space must come from PREBUILT index maps
(``--off-heap-index-map-directory`` / feature-indexing driver output):
per-process maps built from data slices would diverge.

The parity bar (enforced by tests/test_multiprocess.py): an N-process run
must match the single-process driver's model numerically.
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from photon_ml_tpu.types import NormalizationType, TaskType

MULTIPROC_DESIGN_POINTER = (
    "multi-process training currently covers a single fixed-effect "
    "coordinate; random-effect coordinates need the cross-process entity "
    "exchange designed in docs/DISTRIBUTED.md"
)


def multiprocess_fe_ineligibilities(args, coord_configs, index_maps) -> list[str]:
    """Why this configuration cannot train multi-process. Empty = eligible."""
    from photon_ml_tpu.estimators.config import FixedEffectDataConfiguration

    reasons: list[str] = []
    if len(coord_configs) != 1:
        reasons.append(MULTIPROC_DESIGN_POINTER)
    for cid, cfg in coord_configs.items():
        if not isinstance(cfg.data_config, FixedEffectDataConfiguration):
            reasons.append(MULTIPROC_DESIGN_POINTER)
            break
        if 0.0 < cfg.down_sampling_rate < 1.0:
            reasons.append(f"coordinate {cid!r}: down-sampling")
        if cfg.box_constraints is not None:
            reasons.append(f"coordinate {cid!r}: box constraints")
        if cfg.data_config.feature_shard_id not in index_maps:
            reasons.append(
                f"shard {cfg.data_config.feature_shard_id!r}: multi-process "
                "training requires PREBUILT index maps "
                "(--off-heap-index-map-directory; per-process maps built from "
                "data slices would diverge)"
            )
    if NormalizationType(args.normalization) != NormalizationType.NONE:
        reasons.append("normalization (needs global feature statistics)")
    if args.hyper_parameter_tuning not in (None, "NONE"):
        reasons.append("hyperparameter tuning")
    if getattr(args, "model_input_directory", None):
        reasons.append("warm start / partial retrain from a model directory")
    if getattr(args, "checkpoint_directory", None):
        reasons.append("iteration checkpointing")
    if getattr(args, "compute_backend", "host") != "host":
        reasons.append("--compute-backend (the multi-process mesh is implicit)")
    if getattr(args, "coefficient_box_constraints", None):
        reasons.append("--coefficient-box-constraints")
    if getattr(args, "output_mode", "BEST") != "BEST":
        reasons.append("--output-mode (only the best model is written)")
    if getattr(args, "variance_computation_type", "NONE") != "NONE":
        reasons.append("coefficient variances")
    if getattr(args, "data_summary_directory", None):
        reasons.append("--data-summary-directory")
    evaluators = getattr(args, "evaluators", None)
    if evaluators and evaluators.strip().upper() != "AUC":
        reasons.append(
            "evaluators other than AUC (multi-process model selection "
            "currently computes the gathered weighted AUC only)"
        )
    if (
        getattr(args, "validation_data_directories", None)
        and not TaskType(args.training_task).is_classification
    ):
        # the single-process path would select by the task's default metric
        # (e.g. min RMSE); silently ranking by AUC over continuous labels
        # would save a different, wrong model
        reasons.append(
            "validation-based selection for non-classification tasks "
            "(multi-process selection computes AUC only)"
        )
    return reasons


def run_multiprocess_fixed_effect(
    args, rank: int, nproc: int, logger, root: str,
    task, coord_configs, shard_configs, index_maps,
) -> dict:
    """The multi-process fixed-effect training flow. Returns the driver's
    summary dict; only process 0 writes output."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.cli.game_training_driver import _save_result
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.data.readers import read_merged_avro
    from photon_ml_tpu.estimators.game_estimator import GameResult
    from photon_ml_tpu.models.game import FixedEffectModel, GameModel
    from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.util.date_range import resolve_input_paths
    from photon_ml_tpu.util.timed import Timed

    reasons = multiprocess_fe_ineligibilities(args, coord_configs, index_maps)
    if reasons:
        raise NotImplementedError(
            "configuration not eligible for multi-process training: "
            + "; ".join(sorted(set(reasons)))
        )
    (cid, cfg), = coord_configs.items()
    shard = cfg.data_config.feature_shard_id

    def read_slice(directories, date_range, days_range, what):
        paths = resolve_input_paths(directories, date_range, days_range)
        all_files = avro_io.container_files(paths)
        mine = all_files[rank::nproc]
        logger.info(
            "process %d/%d reading %d of %d %s part files",
            rank, nproc, len(mine), len(all_files), what,
        )
        if not mine:
            from photon_ml_tpu.data.game_data import GameInput
            import scipy.sparse as sp

            return GameInput(
                features={shard: sp.csr_matrix((0, index_maps[shard].size))},
                labels=np.zeros(0), id_columns={},
            )
        data, _, _ = read_merged_avro(mine, shard_configs, index_maps)
        return data

    with Timed("read training data", logger):
        train = read_slice(
            args.input_data_directories,
            getattr(args, "input_data_date_range", None),
            getattr(args, "input_data_days_range", None),
            "training",
        )
    from photon_ml_tpu.data.validators import DataValidationType, sanity_check_data

    if train.n:  # per-sample checks are slice-local: each process checks its rows
        with Timed("data validation", logger):
            sanity_check_data(
                task,
                train.labels,
                offsets=train.offsets,
                weights=train.weights,
                feature_shards=train.features,
                validation_type=DataValidationType(args.data_validation),
            )
    val = None
    if args.validation_data_directories:
        with Timed("read validation data", logger):
            val = read_slice(
                args.validation_data_directories,
                getattr(args, "validation_data_date_range", None),
                getattr(args, "validation_data_days_range", None),
                "validation",
            )

    mesh = make_mesh(len(jax.devices()))
    train_data, _ = _assemble_global(train, shard, mesh, logger)
    val_data = None
    if val is not None:
        val_data, _ = _assemble_global(val, shard, mesh, logger)

    from photon_ml_tpu.parallel import train_glm_sharded

    results = []
    warm = None
    sweep = cfg.expand()
    for opt_cfg in sweep:
        with Timed(f"train lambda={opt_cfg.regularization_weight}", logger):
            coeffs, opt_res = train_glm_sharded(
                train_data, task, opt_cfg, mesh, initial_coefficients=warm
            )
        warm = coeffs
        auc = None
        if val_data is not None:
            auc = _validation_auc(val_data, coeffs)
            logger.info(
                "lambda=%s validation AUC=%.6f",
                opt_cfg.regularization_weight, auc,
            )
        results.append((opt_cfg, np.asarray(coeffs), auc))

    best_i = (
        int(np.argmax([r[2] for r in results]))
        if val_data is not None
        else len(results) - 1
    )
    logger.info("selected model %d of %d", best_i, len(results))

    # NOTE: the multi-process summary carries plain dicts (JSON-serializable,
    # written to <root>/summary.json), not the single-process path's
    # GameResult objects — the "multiprocess" key marks the shape
    summary = {
        "multiprocess": True,
        "results": [
            {"regularization_weight": c.regularization_weight, "auc": a}
            for c, _, a in results
        ],
        "best_index": best_i,
        "output_directory": root,
        "num_processes": nproc,
    }
    if rank == 0:
        best_cfg, best_coeffs, best_auc = results[best_i]
        glm = GeneralizedLinearModel(
            Coefficients(jnp.asarray(best_coeffs)), TaskType(task)
        )
        model = GameModel(
            models={cid: FixedEffectModel(model=glm, feature_shard_id=shard)}
        )
        result = GameResult(
            model=model,
            best_model=model,
            configuration={cid: best_cfg},
            evaluations={"AUC": best_auc} if best_auc is not None else None,
            best_metric=best_auc,
            descent=None,
        )
        _save_result(
            os.path.join(root, "best"), result, {cid: index_maps[shard]},
            coord_configs, args.model_sparsity_threshold, logger,
        )
        os.makedirs(os.path.join(root, "index-maps"), exist_ok=True)
        index_maps[shard].save(os.path.join(root, "index-maps", f"{shard}.npz"))
        with open(os.path.join(root, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
    from jax.experimental import multihost_utils

    # rank 0's writes complete before any process exits (a prompt exit would
    # tear down the distributed runtime under rank 0's collectives)
    multihost_utils.sync_global_devices("photon-multiproc-train-done")
    return summary


def _assemble_global(data, shard: str, mesh, logger):
    """Per-process GameInput slice -> global batch-sharded LabeledData.

    Blocks are padded to a common per-process row count with weight-0 rows
    (inert in every objective reduction) so the global row count divides
    evenly over the mesh. Sparse feature slices stay sparse: the COO triples
    (row indices rebased to GLOBAL sample ids) are padded per process to a
    common nnz count with zero-value entries (inert under scatter-add) and
    sharded over the nnz axis — the billion-feature regime of
    parallel/glm.py, assembled across processes.

    Returns (LabeledData, (n_local_real, pad_rows))."""
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp

    from jax.experimental import multihost_utils
    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.data.matrix import DenseDesignMatrix, SparseDesignMatrix
    from photon_ml_tpu.parallel.distributed import host_local_to_global

    nproc = jax.process_count()
    X = data.shard(shard)
    n_local = data.n
    counts = np.asarray(
        multihost_utils.process_allgather(np.asarray([n_local]))
    ).ravel()
    devices_per_process = max(1, len(jax.local_devices()))
    dev_counts = np.asarray(
        multihost_utils.process_allgather(np.asarray([devices_per_process]))
    ).ravel()
    if len(set(int(c) for c in dev_counts)) != 1:
        # the padding target below must be computed identically everywhere;
        # heterogeneous local device counts would give processes conflicting
        # global shapes (a hang or shape-mismatch deep in array assembly)
        raise ValueError(
            f"multi-process training requires the same local device count on "
            f"every process, got {dev_counts.tolist()}"
        )
    per_process = -(-int(counts.max()) // devices_per_process) * devices_per_process
    pad = per_process - n_local
    global_rows = per_process * nproc
    logger.info(
        "global assembly: local %d rows (+%d pad), %d processes x %d rows",
        n_local, pad, nproc, per_process,
    )

    def assemble_vec(v, fill=0.0):
        out = np.full(per_process, fill, dtype=np.float32)
        out[:n_local] = np.asarray(v, dtype=np.float32)
        return host_local_to_global(out, mesh, global_rows=global_rows)

    if sp.issparse(X):
        coo = X.tocoo()
        nnz_counts = np.asarray(
            multihost_utils.process_allgather(np.asarray([coo.nnz]))
        ).ravel()
        per_nnz = -(-int(nnz_counts.max()) // devices_per_process) * devices_per_process
        base = jax.process_index() * per_process
        rows = np.zeros(per_nnz, dtype=np.int32)
        cols = np.zeros(per_nnz, dtype=np.int32)
        vals = np.zeros(per_nnz, dtype=np.float32)
        rows[: coo.nnz] = coo.row.astype(np.int32) + base
        cols[: coo.nnz] = coo.col.astype(np.int32)
        vals[: coo.nnz] = coo.data.astype(np.float32)
        global_nnz = per_nnz * nproc
        Xg = SparseDesignMatrix(
            rows=host_local_to_global(rows, mesh, global_rows=global_nnz),
            cols=host_local_to_global(cols, mesh, global_rows=global_nnz),
            vals=host_local_to_global(vals, mesh, global_rows=global_nnz),
            n_rows=global_rows,
            n_cols=X.shape[1],
        )
        logger.info(
            "sparse assembly: local nnz %d (+%d pad) over %d columns",
            coo.nnz, per_nnz - coo.nnz, X.shape[1],
        )
    else:
        dense = np.asarray(X, dtype=np.float32)
        Xp = np.zeros((per_process, dense.shape[1]), dtype=np.float32)
        Xp[:n_local] = dense
        Xg = DenseDesignMatrix(
            host_local_to_global(Xp, mesh, global_rows=global_rows)
        )

    return (
        LabeledData(
            X=Xg,
            labels=assemble_vec(data.labels if data.has_labels else np.zeros(n_local)),
            offsets=assemble_vec(data.offsets),
            weights=assemble_vec(data.weights),
        ),
        (n_local, pad),
    )


def _validation_auc(val_data, coeffs) -> float:
    """Weighted AUC over the global validation set: every process scores its
    own addressable block and the (score, label, weight) triples are
    allgathered host-side — pad rows carry weight 0 and drop out of the
    weighted pair statistic."""
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import multihost_utils

    from photon_ml_tpu.evaluation.evaluators import auc_roc

    scores = val_data.X.matvec(jnp.asarray(coeffs)) + val_data.offsets

    def local_block(arr):
        return np.concatenate(
            [np.asarray(s.data) for s in arr.addressable_shards]
        )

    local = (
        local_block(scores),
        local_block(val_data.labels),
        local_block(val_data.weights),
    )
    s, l, w = (np.asarray(x).reshape(-1) for x in multihost_utils.process_allgather(local))
    return float(auc_roc(s, l, weights=w))
