"""Multi-process FIXED-EFFECT training for the CLI driver.

Each process reads its round-robin slice of the input part files, pads its
block to the common per-process row count with weight-0 rows, and assembles
GLOBAL batch-sharded arrays (``host_local_to_global``) over a mesh spanning
every process's devices — gradient reductions then cross processes as real
collectives, the reference's executor/treeAggregate topology with XLA
collectives in place of Spark (ValueAndGradientAggregator.scala:240-255).

Two runners live here. ``run_multiprocess_fixed_effect``: single
fixed-effect coordinate — regularization sweeps with warm starts,
validation selection, down-sampling, box constraints, variances,
normalization, warm start, per-config checkpoint/resume, and
RANDOM/BAYESIAN hyperparameter tuning. ``run_multiprocess_game``: [fixed,
random...] coordinate sequences through the cross-process entity exchange
of docs/DISTRIBUTED.md. Both require PREBUILT index maps
(``--off-heap-index-map-directory`` / feature-indexing driver output):
per-process maps built from data slices would diverge.

The parity bar (enforced by tests/test_multiprocess.py): an N-process run
must match the single-process driver's model numerically.
"""

from __future__ import annotations

import dataclasses as _dc
import json
import os
from typing import Optional

import numpy as np

from photon_ml_tpu.types import NormalizationType, TaskType

MULTIPROC_DESIGN_POINTER = (
    "the fixed-effect-only multi-process runner covers exactly ONE "
    "fixed-effect coordinate (configurations with random effects route to "
    "the GAME runner's entity exchange; MULTIPLE fixed-effect coordinates "
    "have no multi-process path — docs/DISTRIBUTED.md)"
)


def multiprocess_fe_ineligibilities(args, coord_configs, index_maps) -> list[str]:
    """Why this configuration cannot train multi-process. Empty = eligible."""
    from photon_ml_tpu.estimators.config import FixedEffectDataConfiguration

    reasons: list[str] = []
    if len(coord_configs) != 1:
        reasons.append(MULTIPROC_DESIGN_POINTER)
    for cid, cfg in coord_configs.items():
        if not isinstance(cfg.data_config, FixedEffectDataConfiguration):
            reasons.append(MULTIPROC_DESIGN_POINTER)
            break
        if cfg.data_config.feature_shard_id not in index_maps:
            reasons.append(
                f"shard {cfg.data_config.feature_shard_id!r}: multi-process "
                "training requires PREBUILT index maps "
                "(--off-heap-index-map-directory; per-process maps built from "
                "data slices would diverge)"
            )
    if getattr(args, "partial_retrain_locked_coordinates", None):
        reasons.append("partial retrain with locked coordinates")
    if getattr(args, "compute_backend", "host") != "host":
        reasons.append("--compute-backend (the multi-process mesh is implicit)")
    if getattr(args, "evaluators", None):
        try:
            _resolve_validation_evaluators(args, args.training_task)
        except Exception as e:  # unknown spec, bad @K, ...
            reasons.append(f"unparseable --evaluators: {e}")
    return reasons



import functools


@functools.lru_cache(maxsize=None)
def _fe_variance_solver(task, vtype, mesh):
    """Jitted variance pass with REPLICATED output shardings (like
    sharded_glm_solver: propagation could otherwise leave the [D] result
    sharded across processes, making the host fetch fail on every rank).
    l2 and the normalization vectors are traced arguments, so a reg-weight
    sweep reuses one executable."""
    import jax

    from photon_ml_tpu.function.losses import loss_for_task
    from photon_ml_tpu.function.objective import GLMObjective
    from photon_ml_tpu.optimization.solver_cache import compute_variances
    from photon_ml_tpu.parallel.mesh import replicated_sharding

    loss = loss_for_task(TaskType(task))

    def solve(data, w_t, l2, norm):
        obj = GLMObjective(loss, norm, allow_fused=False)
        return compute_variances(obj, data, w_t, l2, vtype, w_t.dtype)

    return jax.jit(solve, out_shardings=replicated_sharding(mesh))


def _sharded_fe_variances(args, train_data, coeffs, opt_cfg, task, norm_ctx, mesh):
    """Coefficient variances for one fixed-effect result over the SHARDED
    data (DistributedOptimizationProblem.computeVariances:84-108): one jitted
    Hessian pass whose data reductions psum across the mesh. With
    normalization the Hessian is taken at the transformed-space optimum and
    the diagonal scales by factor^2 (the delta method, as in
    GLMOptimizationProblem.run). Returns None when variances are off."""
    from photon_ml_tpu.types import VarianceComputationType

    vtype = VarianceComputationType(
        getattr(args, "variance_computation_type", "NONE")
    )
    if vtype == VarianceComputationType.NONE:
        return None
    import jax.numpy as jnp

    from photon_ml_tpu.normalization import NO_NORMALIZATION

    norm = NO_NORMALIZATION if norm_ctx is None else norm_ctx
    w = jnp.asarray(coeffs)
    if not norm.is_identity:
        w = norm.to_transformed_space_device(w)

    solve = _fe_variance_solver(TaskType(task), vtype, mesh)
    variances = solve(
        train_data, w, jnp.asarray(opt_cfg.l2_weight, dtype=w.dtype), norm
    )
    if not norm.is_identity and norm.factors is not None:
        variances = variances * jnp.asarray(
            np.asarray(norm.factors), dtype=variances.dtype
        ) ** 2
    return np.asarray(variances)


def _mp_ckpt_fingerprint(args, nproc, coord_configs) -> str:
    """Run-configuration fingerprint: a resumed run must be the SAME run
    (data, configs, process topology) or the checkpoint is ignored."""
    import hashlib

    from photon_ml_tpu.cli.parsers import coordinate_configuration_to_string

    payload = json.dumps({
        "inputs": args.input_data_directories,
        "input_date_range": getattr(args, "input_data_date_range", None),
        "input_days_range": getattr(args, "input_data_days_range", None),
        "validation": getattr(args, "validation_data_directories", None),
        "validation_date_range": getattr(args, "validation_data_date_range", None),
        "validation_days_range": getattr(args, "validation_data_days_range", None),
        "model_input": getattr(args, "model_input_directory", None),
        "variances": getattr(args, "variance_computation_type", "NONE"),
        "evaluators": getattr(args, "evaluators", None),
        "tuning": getattr(args, "hyper_parameter_tuning", "NONE"),
        "tuning_iterations": getattr(args, "hyper_parameter_tuning_iterations", 0),
        "tuner": getattr(args, "hyper_parameter_tuner", None),
        "task": args.training_task,
        "nproc": nproc,
        "n_iter": args.coordinate_descent_iterations,
        "normalization": args.normalization,
        # bounds change the trained optimum: a resume across a changed
        # constraint map must be rejected, not silently mixed
        "box_constraints": getattr(args, "coefficient_box_constraints", None),
        "locked": sorted(_locked_coordinates(args)),
        "configs": {
            c: coordinate_configuration_to_string(c, cfg)
            for c, cfg in coord_configs.items()
        },
    }, sort_keys=True)
    return hashlib.blake2b(payload.encode(), digest_size=16).hexdigest()


def _mp_ckpt_fingerprint_of(path):
    """The fingerprint stored in one mp checkpoint file, or None when the
    file is absent/torn (then it is simply not a resume candidate — only a
    READABLE file with a DIFFERENT fingerprint warrants the explicit
    'fingerprint mismatch, restarting' operator message)."""
    try:
        with np.load(path, allow_pickle=False) as z:
            return str(z["fingerprint"][0])
    except Exception:
        return None


def _mp_ckpt_paths(directory, rank):
    base = os.path.join(directory, f"mp-game-r{rank:05d}")
    return base + ".npz", base + "-prev.npz"


def _mp_ckpt_write(path, out, logger, rotate_to=None):
    """Atomic (tmp + replace), RETRIED rank-local checkpoint write shared by
    the multi-process checkpointers: transient shared-filesystem OSErrors get
    bounded backoff+jitter (resilience/retry.py) instead of killing every
    rank of the job. ``rotate_to`` keeps one older generation: an existing
    ``path`` moves there before the new file lands (safe across retries — a
    re-attempt after the rotation simply finds no current file)."""
    from photon_ml_tpu.resilience import Retry

    def _attempt():
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **out)
        if rotate_to is not None and os.path.exists(path):
            os.replace(path, rotate_to)
        os.replace(tmp, path)

    Retry(max_attempts=3, base_delay=0.1, max_delay=2.0).call(
        _attempt, description=f"checkpoint write {os.path.basename(path)}"
    )


def _mp_clean_stale_tmp(directory, rank, logger):
    """Drop this rank's leaked ``*.tmp`` staging files (a crash mid-write
    leaves them next to the live checkpoint forever otherwise). Rank-scoped:
    peers' staging files may be live concurrent writes."""
    marker = f"-r{rank:05d}.npz.tmp"
    for name in sorted(os.listdir(directory)):
        if name.endswith(marker):
            logger.info("removing stale checkpoint staging file %s", name)
            try:
                os.remove(os.path.join(directory, name))
            except OSError:
                pass


class _MpFeCheckpointer:
    """Per-configuration checkpointing for the fixed-effect-only sweep: each
    completed configuration writes ONE immutable rank-local file (atomic
    tmp+replace); resume counts the consecutive fingerprint-matched files
    every rank can serve and skips that many configs, warm-starting from the
    last saved coefficients. No rotating live state is needed — the sweep's
    only cross-config state IS the last config's coefficients."""

    def __init__(self, directory, args, rank, nproc, coord_configs, logger):
        self.directory = directory
        self.rank, self.nproc = rank, nproc
        self.logger = logger
        self.fingerprint = _mp_ckpt_fingerprint(args, nproc, coord_configs)
        os.makedirs(directory, exist_ok=True)
        _mp_clean_stale_tmp(directory, rank, logger)

    def _path(self, j, rank=None):
        r = self.rank if rank is None else rank
        return os.path.join(self.directory, f"mp-fe-cfg{j:04d}-r{r:05d}.npz")

    def save(self, j, coeffs, variances, evals):
        out = {
            "fingerprint": np.asarray([self.fingerprint], dtype=str),
            "coeffs": np.asarray(coeffs),
            "vars": np.asarray(variances) if variances is not None else np.zeros(0),
            "meta": np.asarray([json.dumps(evals)], dtype=str),
        }
        _mp_ckpt_write(self._path(j), out, self.logger)
        self.logger.info("checkpointed config %d", j)

    def _valid(self, path):
        # torn/corrupt/absent files read as None, which never matches
        return _mp_ckpt_fingerprint_of(path) == self.fingerprint

    def resume_count(self, n_configs) -> int:
        """Consecutive leading configs EVERY rank has a valid file for —
        deterministic from the shared filesystem on every rank."""
        n = 0
        while n < n_configs and all(
            self._valid(self._path(n, r)) for r in range(self.nproc)
        ):
            n += 1
        # operators must be able to tell an INTENTIONAL invalidation (the
        # fingerprint now covers a changed config key, e.g. box_constraints)
        # from a lost checkpoint directory: files that exist but carry a
        # different fingerprint get an explicit restart message
        if n < n_configs:
            for r in range(self.nproc):
                path = self._path(n, r)
                fp = _mp_ckpt_fingerprint_of(path)
                if fp is not None and fp != self.fingerprint:
                    self.logger.warning(
                        "checkpoint fingerprint mismatch, restarting: %s was "
                        "written by a different run configuration (or an older "
                        "fingerprint schema) and is ignored", path,
                    )
                    break
        return n

    def load(self, j):
        with np.load(self._path(j), allow_pickle=False) as z:
            coeffs = np.asarray(z["coeffs"])
            variances = np.asarray(z["vars"]) if z["vars"].size else None
            evals = json.loads(str(z["meta"][0]))
        return coeffs, variances, evals


class _MpGameCheckpointer:
    """Rank-local checkpoint/resume for the multi-process GAME sweep.

    Every rank writes its own state atomically (tmp + os.replace) and keeps
    ONE previous generation. Ranks can be one pass apart when a job dies
    (the pass loop's exchanges keep them in lockstep otherwise), so resume
    picks the LATEST cursor for which EVERY rank has a state file (current
    or previous) — a deterministic decision every rank reaches identically
    from the shared filesystem. A fingerprint mismatch (different data,
    configs, nproc, ...) ignores the checkpoint and starts fresh.
    """

    def __init__(self, directory, args, rank, nproc, coord_configs, re_cids, logger):
        self.directory = directory
        self.rank, self.nproc = rank, nproc
        self.re_cids = list(re_cids)
        self.logger = logger
        self.interval = max(1, getattr(args, "checkpoint_interval", 1) or 1)
        # rank-independent (the rank lives in the FILENAME): every rank can
        # validate every peer file against the same expected value
        self.fingerprint = _mp_ckpt_fingerprint(args, nproc, coord_configs)
        os.makedirs(directory, exist_ok=True)
        _mp_clean_stale_tmp(directory, rank, logger)

    # ---- serialization ----------------------------------------------------
    def _pack_model(self, out, prefix, m):
        out[f"{prefix}:ids"] = np.asarray(m.entity_ids, dtype=str)
        out[f"{prefix}:coeffs"] = np.asarray(m.coeffs)
        out[f"{prefix}:proj"] = np.asarray(m.proj_indices)
        out[f"{prefix}:vars"] = (
            np.asarray(m.variances) if m.variances is not None else np.zeros((0, 0))
        )

    def _unpack_model(self, z, prefix, cid, coord_configs, task, projector):
        from photon_ml_tpu.models.game import RandomEffectModel

        import jax.numpy as jnp

        dc = coord_configs[cid].data_config
        var = z[f"{prefix}:vars"]
        return RandomEffectModel(
            re_type=dc.random_effect_type,
            feature_shard_id=dc.feature_shard_id,
            task=TaskType(task),
            entity_ids=tuple(str(x) for x in z[f"{prefix}:ids"]),
            coeffs=jnp.asarray(z[f"{prefix}:coeffs"]),
            proj_indices=jnp.asarray(z[f"{prefix}:proj"]),
            variances=jnp.asarray(var) if var.size else None,
            projector=projector,
        )

    def _cfg_path(self, j):
        return os.path.join(
            self.directory, f"mp-game-cfg{j:04d}-r{self.rank:05d}.npz"
        )

    def save_config(self, j, entry):
        """One IMMUTABLE snapshot per completed configuration — completed
        configs never change, so per-pass checkpoints need not re-serialize
        them (checkpoint I/O stays O(live state), not O(sweep length))."""
        out = {
            "fingerprint": np.asarray([self.fingerprint], dtype=str),
            "fe": np.asarray(entry["fe"]),
            "fe_vars": (
                np.asarray(entry["fe_vars"])
                if entry.get("fe_vars") is not None else np.zeros(0)
            ),
            "meta": np.asarray([json.dumps({
                "metric": entry["metric"],
                "value": entry["value"],
                "evaluations": entry["evaluations"],
                "auc": entry["auc"],
                # enough to reconstruct the entry's optimization configs on
                # resume (tuned candidates are NOT derivable from the sweep)
                "weights": {
                    c: cfg_.regularization_weight
                    for c, cfg_ in entry["configs"].items()
                },
                "alphas": {
                    c: cfg_.regularization_context.elastic_net_alpha
                    for c, cfg_ in entry["configs"].items()
                },
            })], dtype=str),
        }
        for cid in self.re_cids:
            if entry["re"].get(cid) is not None:
                self._pack_model(out, f"re:{cid}", entry["re"][cid])
        _mp_ckpt_write(self._cfg_path(j), out, self.logger)

    def save(self, i, p, fe_coeffs, fe_vars, re_models, re_scores_home,
             track, n_completed_configs):
        out = {
            "cursor": np.asarray([i, p], dtype=np.int64),
            "fingerprint": np.asarray([self.fingerprint], dtype=str),
            "n_configs": np.asarray([n_completed_configs], dtype=np.int64),
            "fe": np.asarray(fe_coeffs),
            "fe_vars": np.asarray(fe_vars) if fe_vars is not None else np.zeros(0),
            "meta": np.asarray([json.dumps({
                "track": {
                    "value": track["value"],
                    "metric": track["metric"],
                    "evaluations": track["evaluations"],
                },
            })], dtype=str),
        }
        for cid in self.re_cids:
            if re_models[cid] is not None:
                self._pack_model(out, f"re:{cid}", re_models[cid])
            out[f"sc:{cid}"] = np.asarray(re_scores_home[cid])
        if track["fe"] is not None:
            out["track:fe"] = np.asarray(track["fe"])
            out["track:fe_vars"] = (
                np.asarray(track["fe_vars"])
                if track["fe_vars"] is not None else np.zeros(0)
            )
            for cid in self.re_cids:
                if track["re"] and track["re"].get(cid) is not None:
                    self._pack_model(out, f"track:re:{cid}", track["re"][cid])
        cur, prev = _mp_ckpt_paths(self.directory, self.rank)
        _mp_ckpt_write(cur, out, self.logger, rotate_to=prev)
        self.logger.info("checkpointed config %d pass %d", i, p)

    # ---- resume -----------------------------------------------------------
    def _cursor_of(self, path):
        try:
            with np.load(path, allow_pickle=False) as z:
                fp = str(z["fingerprint"][0])
                i, p = (int(x) for x in z["cursor"])
            return (i, p), fp
        except Exception:  # torn/corrupt file: not a resume candidate
            return None, None

    def resume_cursor(self):
        """The latest (i, p) every rank can serve, or None. Deterministic:
        every rank scans the same shared files."""
        per_rank = []
        mismatched = None
        for r in range(self.nproc):
            cur, prev = _mp_ckpt_paths(self.directory, r)
            entries = {}
            for path in (cur, prev):
                if os.path.exists(path):
                    cursor, fp = self._cursor_of(path)
                    if cursor is not None and fp == self.fingerprint:
                        entries[cursor] = path
                    elif fp is not None and fp != self.fingerprint:
                        mismatched = path
            per_rank.append(entries)
        if not per_rank or any(not e for e in per_rank):
            if mismatched is not None:
                # distinguish an intentional invalidation (config/data change
                # reflected in the fingerprint) from a lost checkpoint dir
                self.logger.warning(
                    "checkpoint fingerprint mismatch, restarting: %s was "
                    "written by a different run configuration (or an older "
                    "fingerprint schema) and is ignored", mismatched,
                )
            return None
        common = set(per_rank[0])
        for e in per_rank[1:]:
            common &= set(e)
        if not common:
            return None
        return max(common)

    def load(self, cursor, coord_configs, task, coords):
        import jax.numpy as jnp

        cur, prev = _mp_ckpt_paths(self.directory, self.rank)
        path = None
        for cand in (cur, prev):
            if os.path.exists(cand):
                c, fp = self._cursor_of(cand)
                # fingerprint re-checked here: another run sharing the
                # directory could have rotated a same-cursor file into place
                if c == cursor and fp == self.fingerprint:
                    path = cand
                    break
        assert path is not None
        with np.load(path, allow_pickle=False) as z:
            keys = set(z.files)
            meta = json.loads(str(z["meta"][0]))
            n_configs = int(z["n_configs"][0])
            fe_coeffs = jnp.asarray(z["fe"])
            fe_vars = np.asarray(z["fe_vars"]) if z["fe_vars"].size else None
            re_models = {}
            re_scores_home = {}
            for cid in self.re_cids:
                projector = coords[cid].projector
                re_models[cid] = (
                    self._unpack_model(z, f"re:{cid}", cid, coord_configs, task, projector)
                    if f"re:{cid}:coeffs" in keys else None
                )
                re_scores_home[cid] = np.asarray(z[f"sc:{cid}"])
            track = {
                "value": meta["track"]["value"],
                "metric": meta["track"]["metric"],
                "evaluations": meta["track"]["evaluations"],
                "fe": np.asarray(z["track:fe"]) if "track:fe" in keys else None,
                "fe_vars": (
                    np.asarray(z["track:fe_vars"])
                    if "track:fe_vars" in keys and z["track:fe_vars"].size
                    else None
                ),
                "re": {
                    cid: self._unpack_model(
                        z, f"track:re:{cid}", cid, coord_configs, task,
                        coords[cid].projector,
                    )
                    for cid in self.re_cids
                    if f"track:re:{cid}:coeffs" in keys
                } if "track:fe" in keys else None,
            }
        per_config = []
        for j in range(n_configs):
            with np.load(self._cfg_path(j), allow_pickle=False) as z:
                assert str(z["fingerprint"][0]) == self.fingerprint
                ckeys = set(z.files)
                m = json.loads(str(z["meta"][0]))
                if "weights" not in m:
                    raise ValueError(
                        f"checkpoint config snapshot {self._cfg_path(j)} "
                        "predates per-config weight metadata; clear the "
                        "checkpoint directory to restart this run"
                    )
                configs = {}
                for c, base in coord_configs.items():
                    oc = base.optimization_config.with_weight(
                        float(m["weights"][c])
                    )
                    alpha = m.get("alphas", {}).get(c)
                    if alpha is not None:
                        oc = _dc.replace(
                            oc,
                            regularization_context=_dc.replace(
                                oc.regularization_context,
                                elastic_net_alpha=float(alpha),
                            ),
                        )
                    configs[c] = oc
                per_config.append({
                    "configs": configs,
                    "fe": np.asarray(z["fe"]),
                    "fe_vars": (
                        np.asarray(z["fe_vars"]) if z["fe_vars"].size else None
                    ),
                    "re": {
                        cid: self._unpack_model(
                            z, f"re:{cid}", cid, coord_configs, task,
                            coords[cid].projector,
                        )
                        for cid in self.re_cids
                        if f"re:{cid}:coeffs" in ckeys
                    },
                    "metric": m["metric"],
                    "value": m["value"],
                    "evaluations": m["evaluations"],
                    "auc": m["auc"],
                })
        return fe_coeffs, fe_vars, re_models, re_scores_home, track, per_config


def _locked_coordinates(args) -> set:
    """Locked-coordinate names from the CLI flag (whitespace-tolerant) — the
    ONE parse shared by eligibility and the runners."""
    raw = getattr(args, "partial_retrain_locked_coordinates", "") or ""
    return {c.strip() for c in raw.split(",") if c.strip()}


def _ranked_part_files(directories, date_range, days_range, rank, nproc):
    """THE multi-process file-assignment convention, in exactly one place:
    sorted container part files, round-robin sliced by rank. Both ingest
    (:func:`_read_file_slice`) and the down-sampling draw-key computation
    (:func:`_concat_order_ids`) derive from this — they MUST agree on which
    rows a rank holds, or the masks silently diverge from single-process.
    Returns (all_files, this rank's indices into all_files)."""
    from photon_ml_tpu.data import avro_io
    from photon_ml_tpu.util.date_range import resolve_input_paths

    paths = resolve_input_paths(directories, date_range, days_range)
    all_files = avro_io.container_files(paths)
    return all_files, list(range(len(all_files)))[rank::nproc]


def _read_file_slice(
    directories, date_range, days_range, what,
    shard_configs, index_maps, id_tags, rank, nproc, logger,
    ingest_workers=None,
):
    """Round-robin file-slice ingest shared by the multi-process paths.

    Returns ``(data, all_files, mine_idx)`` — the listing the ingest ACTUALLY
    used, so the down-sampling draw-key computation (:func:`_concat_order_ids`)
    can derive from the identical file set instead of re-listing the
    directory (a concurrent writer between two listings would silently shift
    every draw key)."""
    from photon_ml_tpu.data.game_data import GameInput
    from photon_ml_tpu.data.readers import read_merged_avro
    import scipy.sparse as sp

    all_files, mine_idx = _ranked_part_files(
        directories, date_range, days_range, rank, nproc
    )
    mine = [all_files[i] for i in mine_idx]
    logger.info(
        "process %d/%d reading %d of %d %s part files",
        rank, nproc, len(mine), len(all_files), what,
    )
    if not mine:
        shards = {s for s in index_maps}
        return GameInput(
            features={s: sp.csr_matrix((0, index_maps[s].size)) for s in shards},
            labels=np.zeros(0),
            id_columns={t: np.zeros(0, dtype=object) for t in id_tags},
        ), all_files, mine_idx
    data, _, _ = read_merged_avro(
        mine, shard_configs, index_maps, id_tags, ingest_workers=ingest_workers
    )
    return data, all_files, mine_idx


def _concat_order_ids(all_files, mine):
    """Each LOCAL row's position in the single-process concatenated row order
    — the down-sampling draw key (sampling/down_sampler.per_sample_uniform).

    ``(all_files, mine)`` is the listing the TRAINING ingest returned
    (:func:`_read_file_slice`), so rows and draw keys agree by construction —
    no second directory listing that a concurrent writer could shift.
    Every rank counts every part file from the container block framing alone
    (avro_io.container_row_count: O(blocks) seeks, no payload reads), so the
    global offsets are computed identically everywhere with no exchange."""
    from photon_ml_tpu.data import avro_io

    counts = np.asarray(
        [avro_io.container_row_count(f) for f in all_files], dtype=np.int64
    )
    offsets = np.zeros(len(all_files), dtype=np.int64)
    if len(all_files):
        offsets[1:] = np.cumsum(counts)[:-1]
    if not mine:
        return np.zeros(0, dtype=np.int64)
    return np.concatenate(
        [offsets[i] + np.arange(counts[i], dtype=np.int64) for i in mine]
    )


def _fe_down_sampler(cfg, task):
    """The fixed-effect coordinate's down-sampler, or None — the estimator's
    construction (game_estimator.build_coordinate) with the driver's fixed
    seed, built fresh per swept configuration exactly as the single-process
    sweep does."""
    from photon_ml_tpu.sampling.down_sampler import down_sampler_for_task

    if not (0.0 < cfg.down_sampling_rate < 1.0):
        return None
    return down_sampler_for_task(TaskType(task), cfg.down_sampling_rate, 0)


def _downsampled_weights_global(
    sampler, call, train, dsids_local, per_process, mesh, global_rows
):
    """One down-sampling pass over the HOME rows, assembled to the global
    batch-sharded weights vector. The draws are keyed by each row's position
    in the single-process concatenated order (``dsids_local``), so the global
    mask equals the single-process pass's mask exactly; pad rows keep weight
    0 (inert either way)."""
    import jax.numpy as jnp

    from photon_ml_tpu.parallel.distributed import host_local_to_global

    n_local = train.n
    w_new = np.zeros(per_process, dtype=np.float32)
    if n_local:
        w_new[:n_local] = np.asarray(
            sampler.reweight(
                jnp.asarray(np.asarray(train.labels), dtype=jnp.float32),
                jnp.asarray(np.asarray(train.weights), dtype=jnp.float32),
                jnp.asarray(dsids_local, dtype=jnp.uint32),
                call,
            )
        )
    return host_local_to_global(w_new, mesh, global_rows=global_rows)


def _fe_box_bounds(args, cfg, index_map, norm_ctx):
    """Per-feature (lower, upper) bound vectors for the fixed-effect solve,
    or None: coordinate-level bounds win, else the driver-level
    --coefficient-box-constraints map builds them against the shard's index
    map — the single-process driver's replacement
    (game_training_driver.py:425-436, GLMSuite.createConstraintFeatureMap).
    Bounds + normalization is rejected exactly like the single-process
    coordinate (Params.scala:211-214)."""
    bounds = cfg.box_constraints
    if bounds is None and getattr(args, "coefficient_box_constraints", None):
        from photon_ml_tpu.optimization.constraints import build_bound_vectors

        bounds = build_bound_vectors(
            args.coefficient_box_constraints, index_map
        )
    if bounds is None:
        return None
    if norm_ctx is not None and not norm_ctx.is_identity:
        raise ValueError("Box constraints and normalization cannot be combined")
    return bounds


def run_multiprocess_fixed_effect(
    args, rank: int, nproc: int, logger, root: str,
    task, coord_configs, shard_configs, index_maps,
) -> dict:
    """The multi-process fixed-effect training flow. Returns the driver's
    summary dict; only process 0 writes output."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.cli.game_training_driver import _save_result
    from photon_ml_tpu.estimators.game_estimator import GameResult
    from photon_ml_tpu.models.game import FixedEffectModel, GameModel
    from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_ml_tpu.parallel import make_mesh
    from photon_ml_tpu.util.timed import Timed

    reasons = multiprocess_fe_ineligibilities(args, coord_configs, index_maps)
    if reasons:
        raise NotImplementedError(
            "configuration not eligible for multi-process training: "
            + "; ".join(sorted(set(reasons)))
        )
    (cid, cfg), = coord_configs.items()
    shard = cfg.data_config.feature_shard_id
    evaluators = _resolve_validation_evaluators(args, args.training_task)
    from photon_ml_tpu.evaluation.evaluators import MultiEvaluator

    eval_tags = tuple(
        dict.fromkeys(
            ev.id_tag for ev in evaluators if isinstance(ev, MultiEvaluator)
        )
    )

    def read_slice(directories, date_range, days_range, what):
        return _read_file_slice(
            directories, date_range, days_range, what,
            shard_configs, index_maps,
            # per-group evaluator tags are consumed from VALIDATION rows only
            eval_tags if what == "validation" else (),
            rank, nproc, logger,
            ingest_workers=getattr(args, "ingest_workers", None),
        )

    from photon_ml_tpu.types import HyperparameterTuningMode

    tuning_mode = HyperparameterTuningMode(
        getattr(args, "hyper_parameter_tuning", "NONE") or "NONE"
    )
    if tuning_mode != HyperparameterTuningMode.NONE and not getattr(
        args, "validation_data_directories", None
    ):
        # the single-process driver's check, verbatim
        raise ValueError("Hyperparameter tuning requires validation data")

    # checkpoint resume decided BEFORE ingest: a fully-resumed sweep (every
    # config checkpointed, including tuned ones) never reads the training
    # data at all
    sweep = cfg.expand()
    n_total = len(sweep)
    if tuning_mode != HyperparameterTuningMode.NONE:
        n_total += args.hyper_parameter_tuning_iterations
    ckpt = None
    n_resumed = 0
    if getattr(args, "checkpoint_directory", None):
        ckpt = _MpFeCheckpointer(
            args.checkpoint_directory, args, rank, nproc, coord_configs, logger
        )
        n_resumed = ckpt.resume_count(n_total)
        if n_resumed:
            logger.info("resuming from checkpoint: %d configs done", n_resumed)
    fully_resumed = n_resumed == n_total
    # the data-summary artifact is recomputed every run (single-process
    # semantics): a FULLY-resumed summary-writing run still reads the
    # training slice and runs the stats pass, but skips everything else
    # (validation read, device assembly — zero configs will train)
    summary_only = fully_resumed and bool(
        getattr(args, "data_summary_directory", None)
    )

    train = train_data = norm_ctx = None
    val = None
    train_listing = ([], [])
    mesh = make_mesh(len(jax.devices()))
    if not fully_resumed or summary_only:
        with Timed("read training data", logger):
            train, *train_listing = read_slice(
                args.input_data_directories,
                getattr(args, "input_data_date_range", None),
                getattr(args, "input_data_days_range", None),
                "training",
            )
        from photon_ml_tpu.data.validators import DataValidationType, sanity_check_data

        if train.n:  # per-sample checks are slice-local per process
            with Timed("data validation", logger):
                sanity_check_data(
                    task,
                    train.labels,
                    offsets=train.offsets,
                    weights=train.weights,
                    feature_shards=train.features,
                    validation_type=DataValidationType(args.data_validation),
                )
        if args.validation_data_directories and not fully_resumed:
            with Timed("read validation data", logger):
                val, _, _ = read_slice(
                    args.validation_data_directories,
                    getattr(args, "validation_data_date_range", None),
                    getattr(args, "validation_data_days_range", None),
                    "validation",
                )
        if not fully_resumed:
            train_data, _ = _assemble_global(train, shard, mesh, logger)

        # global statistics -> transformed-space solves with original-space
        # coefficients in/out, exactly the single-process contract (+ the
        # --data-summary-directory artifact from the same stats pass)
        norm_ctx = _build_norm_contexts(
            args, train, [shard], index_maps, logger, rank
        ).get(shard)

    from photon_ml_tpu.parallel import train_glm_sharded

    results = []
    warm = None
    if getattr(args, "model_input_directory", None):
        # every rank loads the same model from the shared filesystem —
        # warm start needs no exchange (GameTrainingDriver.scala:370-409)
        from photon_ml_tpu.io.model_io import load_game_model

        with Timed("load initial model", logger):
            init = load_game_model(
                args.model_input_directory, {cid: index_maps[shard]}
            )
        fe_init = init.get_model(cid)
        # a saved model without this coordinate cold-starts it, matching the
        # single-process driver (game_estimator passes init=None through)
        warm = (
            np.asarray(fe_init.model.coefficients.means)
            if fe_init is not None
            else None
        )
    # selection identity comes from the evaluator list, independent of
    # whether validation was (re-)read this run: a FULLY-resumed sweep skips
    # the validation read but its checkpointed entries still carry values
    metric_name = evaluators[0].name
    larger = evaluators[0].larger_is_better

    def _restored_cfg(j, r_meta):
        """The optimization config a checkpointed entry was trained with:
        grid entries come from the sweep, tuned entries reconstruct from the
        checkpointed weight/alpha (not derivable from the sweep)."""
        if j < len(sweep):
            return sweep[j]
        if r_meta.get("weight") is None:
            raise ValueError(
                f"checkpoint config {j} is a tuned candidate but predates "
                "per-config weight metadata; clear the checkpoint directory "
                "to restart this run"
            )
        oc = cfg.optimization_config.with_weight(float(r_meta["weight"]))
        if r_meta.get("alpha") is not None:
            oc = _dc.replace(
                oc,
                regularization_context=_dc.replace(
                    oc.regularization_context,
                    elastic_net_alpha=float(r_meta["alpha"]),
                ),
            )
        return oc

    if ckpt is not None:
        for j in range(n_resumed):
            r_coeffs, r_vars, r_meta = ckpt.load(j)
            results.append((
                _restored_cfg(j, r_meta), r_coeffs, r_meta.get("value"), r_vars,
                r_meta.get("evaluations"),
            ))
            warm = r_coeffs

    sampler_rate_active = 0.0 < cfg.down_sampling_rate < 1.0
    n_iter = args.coordinate_descent_iterations
    bounds = lower = upper = None
    dsids_local = None
    if not fully_resumed:
        bounds = _fe_box_bounds(args, cfg, index_maps[shard], norm_ctx)
        if bounds is not None:
            lower, upper = bounds
        if sampler_rate_active:
            # keyed off the SAME listing the training ingest used
            dsids_local = _concat_order_ids(*train_listing)

    def evaluate(coeffs):
        if val is None:
            return None, None
        scores = _host_scores(val, shard, coeffs) + np.asarray(
            val.offsets, dtype=np.float64
        )
        evals = _gathered_evaluations(
            evaluators, scores,
            np.asarray(val.labels, dtype=np.float64),
            np.asarray(val.weights, dtype=np.float64),
            val.ids,
        )
        return evals[metric_name], evals

    def train_one(opt_cfg, warm_coeffs):
        """Train ONE configuration; returns (coeffs, value, variances, evals).

        Without down-sampling, one converged solve equals the single-process
        descent's n identical passes over one coordinate. With it, each CD
        pass draws a FRESH mask (DownSampler.down_sample per update), so the
        passes are emulated one by one — draw p's weights, warm-started
        solve, per-update validation tracking (every update is a selection
        candidate, CoordinateDescent.scala:256-289)."""
        if not sampler_rate_active:
            coeffs, _ = train_glm_sharded(
                train_data, task, opt_cfg, mesh,
                initial_coefficients=warm_coeffs, normalization=norm_ctx,
                lower_bounds=lower, upper_bounds=upper,
            )
            value, evals = evaluate(coeffs)
            variances = _sharded_fe_variances(
                args, train_data, coeffs, opt_cfg, task, norm_ctx, mesh
            )
            return np.asarray(coeffs), value, variances, evals

        sampler = _fe_down_sampler(cfg, task)
        global_rows = train_data.labels.shape[0]
        per_proc_rows = global_rows // nproc
        coeffs = warm_coeffs
        best = None  # (value, coeffs, call, evals)
        data_p = train_data
        for p in range(n_iter):
            w_p = _downsampled_weights_global(
                sampler, p, train, dsids_local, per_proc_rows, mesh, global_rows
            )
            data_p = _dc.replace(train_data, weights=w_p)
            coeffs, _ = train_glm_sharded(
                data_p, task, opt_cfg, mesh,
                initial_coefficients=coeffs, normalization=norm_ctx,
                lower_bounds=lower, upper_bounds=upper,
            )
            value, evals = evaluate(coeffs)
            if value is not None and (
                best is None
                or (value > best[0] if larger else value < best[0])
            ):
                best = (value, np.asarray(coeffs).copy(), p, evals)
        if best is not None:
            value, out_coeffs, best_p, evals = best
            if best_p != n_iter - 1:
                # variances belong to the pass that produced the snapshot:
                # rebuild its (deterministic) weights for the Hessian pass
                data_p = _dc.replace(
                    train_data,
                    weights=_downsampled_weights_global(
                        _fe_down_sampler(cfg, task), best_p, train,
                        dsids_local, per_proc_rows, mesh, global_rows,
                    ),
                )
        else:
            value, out_coeffs, evals = None, np.asarray(coeffs), None
        variances = _sharded_fe_variances(
            args, data_p, jnp.asarray(out_coeffs), opt_cfg, task, norm_ctx, mesh
        )
        return out_coeffs, value, variances, evals

    def _ckpt_meta(opt_cfg, value, evals):
        return {
            "value": value,
            "evaluations": evals,
            "weight": opt_cfg.regularization_weight,
            "alpha": opt_cfg.regularization_context.elastic_net_alpha,
        }

    for j, opt_cfg in enumerate(sweep):
        if j < n_resumed:
            continue
        with Timed(f"train lambda={opt_cfg.regularization_weight}", logger):
            coeffs, metric_value, variances, evals = train_one(opt_cfg, warm)
        warm = coeffs
        if evals is not None:
            logger.info(
                "lambda=%s validation %s",
                opt_cfg.regularization_weight,
                " ".join(f"{k}={v:.6f}" for k, v in evals.items()),
            )
        results.append((opt_cfg, coeffs, metric_value, variances, evals))
        if ckpt is not None:
            ckpt.save(j, coeffs, variances, _ckpt_meta(opt_cfg, metric_value, evals))

    # -- hyperparameter tuning (GameTrainingDriver.runHyperparameterTuning):
    # proposals are deterministic functions of the gathered observations, so
    # every rank trains identical candidates in lockstep (the GAME runner's
    # design); candidates COLD-start, as the single-process evaluation
    # function's fresh fits do
    tuned_start = len(sweep)
    if tuning_mode != HyperparameterTuningMode.NONE:
        from photon_ml_tpu.estimators.evaluation_function import (
            GameEstimatorEvaluationFunction,
        )
        from photon_ml_tpu.hyperparameter.tuner import build_tuner

        fn = GameEstimatorEvaluationFunction(
            estimator=None, data=None, validation_data=None,
            base_configs={cid: cfg.optimization_config},
            is_opt_max=larger,
        )
        observations = [
            (
                fn._scale_forward(fn.configuration_to_vector({cid: r_cfg})),
                (-v if larger else v),
            )
            for (r_cfg, _, v, _, _) in results
            if v is not None
        ]

        def mp_eval(candidate):
            configs = fn.vector_to_configuration(fn._scale_backward(candidate))
            opt_cfg = configs[cid]
            j = len(results)
            with Timed(f"tune lambda={opt_cfg.regularization_weight}", logger):
                coeffs, metric_value, variances, evals = train_one(opt_cfg, None)
            results.append((opt_cfg, coeffs, metric_value, variances, evals))
            if ckpt is not None:
                ckpt.save(
                    j, coeffs, variances, _ckpt_meta(opt_cfg, metric_value, evals)
                )
            return ((-metric_value if larger else metric_value), results[-1])

        n_restored_tuned = max(0, len(results) - tuned_start)
        remaining = args.hyper_parameter_tuning_iterations - n_restored_tuned
        if remaining > 0:
            tuner = build_tuner(getattr(args, "hyper_parameter_tuner", "ATLAS"))
            with Timed("hyperparameter tuning", logger):
                tuner.search(
                    remaining, fn.num_params, tuning_mode, mp_eval, observations,
                    # checkpoint-restored tuned candidates already consumed
                    # their Sobol draws; fast-forward past them
                    resumed=n_restored_tuned,
                )

    values = [r[2] for r in results]
    if results and all(v is not None for v in values):
        best_i = int(np.argmax(values) if larger else np.argmin(values))
    else:
        best_i = len(results) - 1  # no validation: last (weakest-reg) config
    logger.info("selected model %d of %d", best_i, len(results))

    # NOTE: the multi-process summary carries plain dicts (JSON-serializable,
    # written to <root>/summary.json), not the single-process path's
    # GameResult objects — the "multiprocess" key marks the shape
    summary = {
        "multiprocess": True,
        "results": [
            {
                "regularization_weight": c.regularization_weight,
                "auc": a if (a is not None and metric_name == "AUC") else None,
                "metric": metric_name if a is not None else None,
                "value": a,
                "evaluations": _e,
            }
            for c, _, a, _v, _e in results
        ],
        "best_index": best_i,
        "output_directory": root,
        "num_processes": nproc,
    }
    if rank == 0:
        from photon_ml_tpu.cli.parsers import ModelOutputMode

        def fe_result(entry):
            r_cfg, r_coeffs, r_value, r_vars, r_evals = entry
            glm = GeneralizedLinearModel(
                Coefficients(
                    jnp.asarray(r_coeffs),
                    None if r_vars is None else jnp.asarray(r_vars),
                ),
                TaskType(task),
            )
            model = GameModel(
                models={cid: FixedEffectModel(model=glm, feature_shard_id=shard)}
            )
            return GameResult(
                model=model,
                best_model=model,
                configuration={cid: r_cfg},
                evaluations=r_evals if r_evals else None,
                best_metric=r_value,
                descent=None,
            )

        output_mode = ModelOutputMode(args.output_mode)
        if output_mode != ModelOutputMode.NONE:
            _save_result(
                os.path.join(root, "best"), fe_result(results[best_i]),
                {cid: index_maps[shard]},
                coord_configs, args.model_sparsity_threshold, logger,
            )
            # models/<i>/ ranges follow the single-process driver
            # (GameTrainingDriver.scala:759-826): ALL saves everything,
            # EXPLICIT excludes tuned results, TUNED saves only them
            if output_mode == ModelOutputMode.ALL:
                save_range = range(len(results))
            elif output_mode == ModelOutputMode.EXPLICIT:
                save_range = range(tuned_start)
            elif output_mode == ModelOutputMode.TUNED:
                save_range = range(tuned_start, len(results))
            else:
                save_range = range(0)
            for i in save_range:
                _save_result(
                    os.path.join(root, "models", str(i)), fe_result(results[i]),
                    {cid: index_maps[shard]},
                    coord_configs, args.model_sparsity_threshold, logger,
                )
            os.makedirs(os.path.join(root, "index-maps"), exist_ok=True)
            index_maps[shard].save(os.path.join(root, "index-maps", f"{shard}.npz"))
        with open(os.path.join(root, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
    from jax.experimental import multihost_utils

    # rank 0's writes complete before any process exits (a prompt exit would
    # tear down the distributed runtime under rank 0's collectives)
    multihost_utils.sync_global_devices("photon-multiproc-train-done")
    return summary


def _assemble_global(data, shard: str, mesh, logger):
    """Per-process GameInput slice -> global batch-sharded LabeledData.

    Blocks are padded to a common per-process row count with weight-0 rows
    (inert in every objective reduction) so the global row count divides
    evenly over the mesh. Sparse feature slices stay sparse: the COO triples
    (row indices rebased to GLOBAL sample ids) are padded per process to a
    common nnz count with zero-value entries (inert under scatter-add) and
    sharded over the nnz axis — the billion-feature regime of
    parallel/glm.py, assembled across processes.

    Returns (LabeledData, (n_local_real, pad_rows))."""
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp

    from jax.experimental import multihost_utils
    from photon_ml_tpu.data.dataset import LabeledData
    from photon_ml_tpu.data.matrix import DenseDesignMatrix, SparseDesignMatrix
    from photon_ml_tpu.parallel.distributed import host_local_to_global

    nproc = jax.process_count()
    X = data.shard(shard)
    n_local = data.n
    counts = np.asarray(
        multihost_utils.process_allgather(np.asarray([n_local]))
    ).ravel()
    devices_per_process = max(1, len(jax.local_devices()))
    dev_counts = np.asarray(
        multihost_utils.process_allgather(np.asarray([devices_per_process]))
    ).ravel()
    if len(set(int(c) for c in dev_counts)) != 1:
        # the padding target below must be computed identically everywhere;
        # heterogeneous local device counts would give processes conflicting
        # global shapes (a hang or shape-mismatch deep in array assembly)
        raise ValueError(
            f"multi-process training requires the same local device count on "
            f"every process, got {dev_counts.tolist()}"
        )
    per_process = -(-int(counts.max()) // devices_per_process) * devices_per_process
    pad = per_process - n_local
    global_rows = per_process * nproc
    logger.info(
        "global assembly: local %d rows (+%d pad), %d processes x %d rows",
        n_local, pad, nproc, per_process,
    )

    def assemble_vec(v, fill=0.0):
        out = np.full(per_process, fill, dtype=np.float32)
        out[:n_local] = np.asarray(v, dtype=np.float32)
        return host_local_to_global(out, mesh, global_rows=global_rows)

    if sp.issparse(X):
        coo = X.tocoo()
        nnz_counts = np.asarray(
            multihost_utils.process_allgather(np.asarray([coo.nnz]))
        ).ravel()
        per_nnz = -(-int(nnz_counts.max()) // devices_per_process) * devices_per_process
        base = jax.process_index() * per_process
        rows = np.zeros(per_nnz, dtype=np.int32)
        cols = np.zeros(per_nnz, dtype=np.int32)
        vals = np.zeros(per_nnz, dtype=np.float32)
        rows[: coo.nnz] = coo.row.astype(np.int32) + base
        cols[: coo.nnz] = coo.col.astype(np.int32)
        vals[: coo.nnz] = coo.data.astype(np.float32)
        global_nnz = per_nnz * nproc
        Xg = SparseDesignMatrix(
            rows=host_local_to_global(rows, mesh, global_rows=global_nnz),
            cols=host_local_to_global(cols, mesh, global_rows=global_nnz),
            vals=host_local_to_global(vals, mesh, global_rows=global_nnz),
            n_rows=global_rows,
            n_cols=X.shape[1],
        )
        logger.info(
            "sparse assembly: local nnz %d (+%d pad) over %d columns",
            coo.nnz, per_nnz - coo.nnz, X.shape[1],
        )
    else:
        dense = np.asarray(X, dtype=np.float32)
        Xp = np.zeros((per_process, dense.shape[1]), dtype=np.float32)
        Xp[:n_local] = dense
        Xg = DenseDesignMatrix(
            host_local_to_global(Xp, mesh, global_rows=global_rows)
        )

    return (
        LabeledData(
            X=Xg,
            labels=assemble_vec(data.labels if data.has_labels else np.zeros(n_local)),
            offsets=assemble_vec(data.offsets),
            weights=assemble_vec(data.weights),
        ),
        (n_local, pad),
    )


def multiprocess_game_ineligibilities(args, coord_configs, index_maps) -> list[str]:
    """Why this GAME configuration cannot train multi-process. Empty = OK.

    The GAME flow adds random-effect coordinates to the fixed-effect path:
    samples route to entity OWNER processes through the filesystem shuffle
    (parallel/shuffle.py), owners solve their entities locally, and residual
    scores travel home per coordinate update — the reference's per-iteration
    score-exchange joins (CoordinateDescent.scala:197-204) over the shared
    filesystem instead of Spark."""
    from photon_ml_tpu.estimators.config import (
        FixedEffectDataConfiguration,
        RandomEffectDataConfiguration,
    )

    reasons: list[str] = []
    ids = list(coord_configs)
    if not ids or not isinstance(
        coord_configs[ids[0]].data_config, FixedEffectDataConfiguration
    ):
        reasons.append("the first coordinate must be the fixed effect")
    for cid in ids[1:]:
        dc = coord_configs[cid].data_config
        if not isinstance(dc, RandomEffectDataConfiguration):
            reasons.append(f"coordinate {cid!r}: only [fixed, random...] sequences")
            continue
        pw = coord_configs[cid].per_entity_reg_weights
        if pw is not None and not isinstance(pw, dict):
            # the array form binds to a dataset's entity ORDER; owners hold
            # arbitrary entity subsets, so no global order exists to align to
            reasons.append(
                f"coordinate {cid!r}: per-entity reg weights must be a "
                "{entity_id: weight} dict for multi-process training "
                "(the [E]-array form has no global entity order to bind to)"
            )
    for cid, cfg in coord_configs.items():
        if cfg.data_config.feature_shard_id not in index_maps:
            reasons.append(
                f"shard {cfg.data_config.feature_shard_id!r}: multi-process "
                "training requires PREBUILT index maps"
            )
    locked = _locked_coordinates(args)
    if locked:
        if not getattr(args, "model_input_directory", None):
            reasons.append(
                "locked coordinates require --model-input-directory "
                "(the locked models must come from somewhere)"
            )
        unknown = set(locked) - set(ids)
        if unknown:
            reasons.append(
                f"locked coordinates not in the update sequence: {sorted(unknown)}"
            )
        if set(locked) >= set(ids):
            reasons.append("every coordinate is locked: nothing to train")
    # the flag-level restrictions are identical to the fixed-effect path
    # (minus partial retrain, which the GAME path handles)
    fe_only = {ids[0]: coord_configs[ids[0]]} if ids else {}
    for r in multiprocess_fe_ineligibilities(args, fe_only, index_maps):
        if (
            r not in reasons
            and r != MULTIPROC_DESIGN_POINTER
            and not r.startswith("partial retrain")
        ):
            reasons.append(r)
    if (
        getattr(args, "hyper_parameter_tuning", "NONE") not in (None, "NONE")
        and not getattr(args, "validation_data_directories", None)
    ):
        reasons.append("hyperparameter tuning requires validation data")
    return reasons


def _spill_re_rows_sparse(
    spill, tag, X_re, owner_of_local, home_ids, gids_local, labels, weights,
    rank, nproc, extra_cols=None,
):
    """Spill one coordinate's rows toward their entity owners: per-sample
    metadata on ``tag`` and the feature matrix as COO triples on ``tag``-x.
    Exchange volume is O(nnz), independent of shard width."""
    import scipy.sparse as sp

    from photon_ml_tpu.parallel.shuffle import exchange_rows

    coo = (X_re if sp.issparse(X_re) else sp.coo_matrix(np.asarray(X_re))).tocoo()
    n_entries = len(coo.data)
    entry_owner = (
        owner_of_local[coo.row] if n_entries else np.zeros(0, dtype=np.int64)
    )
    exchange_rows(
        spill, f"{tag}-x", entry_owner, np.zeros(n_entries, dtype=object),
        {
            "gid": gids_local[coo.row] if n_entries else np.zeros(0, np.int64),
            "col": coo.col.astype(np.int64),
            "val": coo.data.astype(np.float64),
        },
        rank, nproc,
    )
    cols = {"gid": gids_local, "label": labels, "weight": weights}
    cols.update(extra_cols or {})
    exchange_rows(spill, tag, owner_of_local, home_ids, cols, rank, nproc)


def _collect_re_rows_sparse(spill, tag, width, rank, nproc):
    """Collect both halves of :func:`_spill_re_rows_sparse` (after the
    barrier): returns (entity_ids, gids, X csr [n, width], metadata cols)."""
    import scipy.sparse as sp

    from photon_ml_tpu.parallel.shuffle import collect_exchanged_rows

    own_ids, own = collect_exchanged_rows(os.path.join(spill, tag), rank, nproc)
    _, ent = collect_exchanged_rows(os.path.join(spill, f"{tag}-x"), rank, nproc)
    gids = own["gid"].astype(np.int64)
    order = np.argsort(gids, kind="stable")
    ent_gid = ent["gid"].astype(np.int64)
    rowpos = (
        order[np.searchsorted(gids[order], ent_gid)]
        if len(ent_gid)
        else np.zeros(0, dtype=np.int64)
    )
    X = sp.csr_matrix(
        (ent["val"], (rowpos, ent["col"].astype(np.int64))),
        shape=(len(own_ids), width),
    )
    return own_ids, gids, X, own


def _re_score_rows(model, X_rows, entity_ids) -> np.ndarray:
    """Score arbitrary CSR rows against a RandomEffectModel on the host:
    per-entity coefficients scatter into a sparse [E+1, width] matrix (last
    row = zeros for entities without a model), then score = rowwise
    elementwise-product sum. O(nnz) — used for per-update validation scoring
    on entity owners."""
    import scipy.sparse as sp

    n, width = X_rows.shape
    if n == 0:
        return np.zeros(0)
    coeffs = np.asarray(model.coeffs, dtype=np.float64)
    proj = np.asarray(model.proj_indices)
    E = coeffs.shape[0]
    er, slot = np.nonzero(proj >= 0)
    M = sp.csr_matrix(
        (coeffs[er, slot], (er, proj[er, slot].astype(np.int64))),
        shape=(E + 1, width),
    )
    rows_idx = np.asarray(
        [model.row_for_entity(e) for e in entity_ids], dtype=np.int64
    )
    sel = np.where(rows_idx >= 0, rows_idx, E)
    return np.asarray(X_rows.multiply(M[sel]).sum(axis=1)).ravel()


def run_multiprocess_game(
    args, rank: int, nproc: int, logger, root: str,
    task, coord_configs, shard_configs, index_maps,
) -> dict:
    """Multi-process GAME training: sharded fixed-effect solves + owner-local
    random-effect solves + per-update residual score exchanges."""
    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp

    from photon_ml_tpu.algorithm.random_effect import train_random_effect
    from photon_ml_tpu.cli.game_training_driver import _save_result
    from photon_ml_tpu.data.random_effect import build_random_effect_dataset
    from photon_ml_tpu.data.validators import DataValidationType, sanity_check_data
    from photon_ml_tpu.estimators.config import RandomEffectDataConfiguration
    from photon_ml_tpu.estimators.game_estimator import GameResult
    from photon_ml_tpu.estimators.config import expand_game_configurations
    from photon_ml_tpu.models.game import FixedEffectModel, GameModel, RandomEffectModel
    from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel
    from photon_ml_tpu.parallel import make_mesh, train_glm_sharded
    from photon_ml_tpu.parallel.shuffle import (
        collect_exchanged_rows,
        entity_owner_hash,
        exchange_rows,
        shuffle_barrier,
    )
    from photon_ml_tpu.util.timed import Timed

    reasons = multiprocess_game_ineligibilities(args, coord_configs, index_maps)
    if reasons:
        raise NotImplementedError(
            "configuration not eligible for multi-process GAME training: "
            + "; ".join(sorted(set(reasons)))
        )
    from photon_ml_tpu.types import VarianceComputationType

    vtype = VarianceComputationType(
        getattr(args, "variance_computation_type", "NONE")
    )
    coord_ids = list(coord_configs)
    fe_cid, re_cids = coord_ids[0], coord_ids[1:]
    # partial retrain (CoordinateDescent.scala:45 ModelCoordinate semantics):
    # locked coordinates contribute scores every pass, are never re-optimized,
    # and carry their loaded models into the saved result
    locked = _locked_coordinates(args)
    fe_shard = coord_configs[fe_cid].data_config.feature_shard_id
    evaluators = _resolve_validation_evaluators(args, args.training_task)
    from photon_ml_tpu.evaluation.evaluators import MultiEvaluator

    id_tags = sorted(
        {coord_configs[c].data_config.random_effect_type for c in re_cids}
        | {ev.id_tag for ev in evaluators if isinstance(ev, MultiEvaluator)}
    )
    spill = os.path.join(root, "_shuffle")


    def read_slice(directories, date_range, days_range, what):
        return _read_file_slice(
            directories, date_range, days_range, what,
            shard_configs, index_maps, id_tags, rank, nproc, logger,
            ingest_workers=getattr(args, "ingest_workers", None),
        )

    with Timed("read training data", logger):
        train, *train_listing = read_slice(
            args.input_data_directories,
            getattr(args, "input_data_date_range", None),
            getattr(args, "input_data_days_range", None),
            "training",
        )
    if train.n:
        with Timed("data validation", logger):
            sanity_check_data(
                task, train.labels, offsets=train.offsets, weights=train.weights,
                feature_shards=train.features,
                validation_type=DataValidationType(args.data_validation),
            )
    # one global NormalizationContext per DISTINCT shard (FE + RE): statistics
    # reduce over each process's HOME rows, so the union covers every sample
    # exactly once regardless of the entity exchange that follows (+ the
    # --data-summary-directory artifact from the same stats pass)
    norm_ctxs = _build_norm_contexts(
        args, train,
        sorted({coord_configs[c].data_config.feature_shard_id for c in coord_ids}),
        index_maps, logger, rank,
    )
    mesh = make_mesh(len(jax.devices()))
    fe_train, layout = _assemble_global(train, fe_shard, mesh, logger)
    n_local, _pad = layout
    per_process = fe_train.labels.shape[0] // nproc
    gid_base = rank * per_process
    gids_local = np.arange(n_local, dtype=np.int64) + gid_base

    # fixed-effect down-sampling + box constraints (both FE-coordinate-only,
    # exactly as the single-process estimator applies them)
    fe_cfg = coord_configs[fe_cid]
    fe_bounds = _fe_box_bounds(
        args, fe_cfg, index_maps[fe_shard], norm_ctxs.get(fe_shard)
    )
    fe_lower, fe_upper = fe_bounds if fe_bounds is not None else (None, None)
    fe_sampler = _fe_down_sampler(fe_cfg, task)
    # keyed off the SAME listing the training ingest used
    dsids_local = (
        _concat_order_ids(*train_listing) if fe_sampler is not None else None
    )

    # ---- per-coordinate entity exchange (ingest; once) ------------------------
    class RECoord:
        pass

    coords: dict[str, RECoord] = {}
    for cid in re_cids:
        dc: RandomEffectDataConfiguration = coord_configs[cid].data_config
        c = RECoord()
        c.shard = dc.feature_shard_id
        c.home_ids = np.asarray(train.ids(dc.random_effect_type), dtype=object)
        c.owner_of_local = (
            entity_owner_hash(c.home_ids) % np.uint64(nproc)
        ).astype(np.int64) if n_local else np.zeros(0, dtype=np.int64)
        # RE feature rows travel as COO triples, never dense: the exchange
        # volume is O(nnz) regardless of shard width, so arbitrarily wide
        # sparse shards work (RandomEffectDataset.scala:46-508's shuffle is
        # likewise sparse-record-shaped). Triples ride their own exchange tag
        # keyed by global sample id; the owner reassembles CSR rows.
        _spill_re_rows_sparse(
            spill, f"{cid}-ingest", train.shard(c.shard), c.owner_of_local,
            c.home_ids, gids_local,
            np.asarray(train.labels, dtype=np.float64) if train.has_labels else np.zeros(n_local),
            np.asarray(train.weights, dtype=np.float64),
            rank, nproc,
        )
        coords[cid] = c
    shuffle_barrier("ingest")

    for cid, c in coords.items():
        own_ids, c.gids_own, X_own, own = _collect_re_rows_sparse(
            spill, f"{cid}-ingest", index_maps[c.shard].size, rank, nproc
        )
        dc = coord_configs[cid].data_config
        # shared random projection: the matrix is a pure function of
        # (config seed, dim), so every process builds the identical
        # projector with no cross-process state (game_estimator._projector_for)
        from photon_ml_tpu.data.projector import make_projector

        c.norm = norm_ctxs.get(c.shard)
        # with a projector, normalization rides ON the projector so training
        # and scoring datasets agree on the projected space (the estimator's
        # _projector_for discipline)
        c.projector = make_projector(
            dc.projector, index_maps[c.shard].size,
            normalization=c.norm,
        ) if dc.projector is not None else None
        with Timed(f"build RE dataset {cid} ({len(own_ids)} rows)", logger):
            c.ds = build_random_effect_dataset(
                X_own,
                own_ids,
                dc.random_effect_type,
                feature_shard_id=dc.feature_shard_id,
                active_data_upper_bound=dc.active_data_upper_bound,
                active_data_lower_bound=dc.active_data_lower_bound,
                features_max=dc.features_max,
                labels=own["label"],
                weights=own["weight"],
                intercept_index=(
                    c.norm.intercept_index
                    if c.norm is not None and c.projector is None
                    else None
                ),
                normalization=c.norm if c.projector is None else None,
                dtype=jnp.float32,
                projector=c.projector,
            )
        c.home_of_own = c.gids_own // per_process

    # ---- sweep: warm-started coordinate descent -------------------------------
    def send_scores(tag, gids, scores, home_of, n_dest_local, dest_base):
        """Owner -> home score return; gives the home-aligned [n] array."""
        exchange_rows(
            spill, tag, home_of, np.zeros(len(gids), dtype=object),
            {"gid": gids, "s": np.asarray(scores, dtype=np.float64)},
            rank, nproc,
        )
        shuffle_barrier(tag)
        _, got = collect_exchanged_rows(os.path.join(spill, tag), rank, nproc)
        out = np.zeros(n_dest_local)
        out[got["gid"].astype(np.int64) - dest_base] = got["s"]
        return out

    def send_offsets(tag, c, partial_home):
        """Home -> owner residual offsets, aligned to the owner's dataset rows."""
        exchange_rows(
            spill, tag, c.owner_of_local, c.home_ids,
            {"gid": gids_local, "o": np.asarray(partial_home, dtype=np.float64)},
            rank, nproc,
        )
        shuffle_barrier(tag)
        _, got = collect_exchanged_rows(os.path.join(spill, tag), rank, nproc)
        aligned = np.zeros(len(c.gids_own))
        order = np.argsort(c.gids_own)
        pos = order[np.searchsorted(c.gids_own[order], got["gid"].astype(np.int64))]
        aligned[pos] = got["o"]
        return aligned

    # ---- validation ingest (per-update selection, CoordinateDescent.scala:256-289)
    has_val = bool(getattr(args, "validation_data_directories", None))
    val_coords: dict[str, RECoord] = {}
    if has_val:
        with Timed("read validation data", logger):
            val, _, _ = read_slice(
                args.validation_data_directories,
                getattr(args, "validation_data_date_range", None),
                getattr(args, "validation_data_days_range", None),
                "validation",
            )
        # validation rows never ride the device mesh here (scoring is
        # host-side, _host_scores); only the common padded per-process row
        # count is needed for the gid space
        from jax.experimental import multihost_utils

        n_val_local = val.n
        val_counts = np.asarray(
            multihost_utils.process_allgather(np.asarray([n_val_local]))
        ).ravel()
        block = int(val_counts.max())
        per_process_val = ((block + mesh.devices.size - 1) // mesh.devices.size) * mesh.devices.size
        vgid_base = rank * per_process_val
        vgids_local = np.arange(n_val_local, dtype=np.int64) + vgid_base
        for cid in re_cids:
            dcv = coord_configs[cid].data_config
            vc = RECoord()
            vc.shard = dcv.feature_shard_id
            vc.home_ids = np.asarray(val.ids(dcv.random_effect_type), dtype=object)
            vc.owner_of_local = (
                entity_owner_hash(vc.home_ids) % np.uint64(nproc)
            ).astype(np.int64) if n_val_local else np.zeros(0, dtype=np.int64)
            _spill_re_rows_sparse(
                spill, f"{cid}-val", val.shard(vc.shard), vc.owner_of_local,
                vc.home_ids, vgids_local,
                np.zeros(n_val_local), np.zeros(n_val_local), rank, nproc,
            )
            val_coords[cid] = vc
        shuffle_barrier("val-ingest")
        for cid, vc in val_coords.items():
            vc.ids_own, vc.gids_own, vc.X_own, _ = _collect_re_rows_sparse(
                spill, f"{cid}-val", index_maps[vc.shard].size, rank, nproc
            )
            vc.home_of_own = vc.gids_own // per_process_val
        val_base_off = np.asarray(val.offsets, dtype=np.float64)
        val_labels = np.asarray(val.labels, dtype=np.float64)
        val_weights = np.asarray(val.weights, dtype=np.float64)

    base_off_home = np.asarray(train.offsets, dtype=np.float64)
    sweep = expand_game_configurations(coord_configs)
    n_iter = args.coordinate_descent_iterations
    fe_coeffs = None
    fe_vars = None
    last_fe_data = None
    re_models = {cid: None for cid in re_cids}
    re_scores_home = {cid: np.zeros(n_local) for cid in re_cids}

    imaps_by_coord = {
        c: index_maps[coord_configs[c].data_config.feature_shard_id]
        for c in coord_ids
    }
    ckpt = None
    resume_cursor = None
    if getattr(args, "checkpoint_directory", None):
        ckpt = _MpGameCheckpointer(
            args.checkpoint_directory, args, rank, nproc, coord_configs,
            re_cids, logger,
        )
        resume_cursor = ckpt.resume_cursor()
        if resume_cursor is not None:
            logger.info(
                "resuming from checkpoint: config %d pass %d", *resume_cursor
            )
    # resume overwrites everything the warm-start block would compute (and
    # its exchanges are all-rank, so the skip is rank-consistent: the resume
    # decision is deterministic from the shared files)
    if resume_cursor is None and getattr(args, "model_input_directory", None):
        # warm start (GameTrainingDriver.scala:370-409): every rank loads the
        # same saved model; each owner keeps ONLY its own entities' rows
        # (aligned_to its dataset — a full model on every rank would put each
        # entity into nproc model parts at save), and the warm models' scores
        # seed the first fixed-effect residual as in single-process descent.
        # Coordinates absent from the saved model cold-start, matching the
        # single-process driver.
        from photon_ml_tpu.io.model_io import load_game_model

        with Timed("load initial model", logger):
            init_model = load_game_model(
                args.model_input_directory, imaps_by_coord
            )
        fe_init = init_model.get_model(fe_cid)
        if fe_init is None and fe_cid in locked:
            raise ValueError(
                f"locked coordinate {fe_cid!r} is missing from the input model"
            )
        if fe_init is not None:
            fe_coeffs = jnp.asarray(
                np.asarray(fe_init.model.coefficients.means), dtype=jnp.float32
            )
            if fe_init.model.coefficients.variances is not None:
                fe_vars = np.asarray(fe_init.model.coefficients.variances)
        for cid in re_cids:
            c = coords[cid]
            warm_re = init_model.get_model(cid)
            if warm_re is None:
                if cid in locked:
                    raise ValueError(
                        f"locked coordinate {cid!r} is missing from the input model"
                    )
                continue
            if warm_re.projector is None and c.projector is not None:
                raise ValueError(
                    f"coordinate {cid!r}: cannot warm-start a random-"
                    "projection coordinate from an original-space model"
                )
            if cid in locked:
                # LOCKED: the model passes through VERBATIM (ModelCoordinate
                # semantics) — entities absent from the retrain data must
                # survive in the save. score_dataset aligns transiently.
                re_models[cid] = warm_re
                own_scores = np.asarray(warm_re.score_dataset(c.ds))
            else:
                # plain warm start: each owner keeps only ITS entities' rows
                # (a full copy per rank would save each entity nproc times
                # through tracked snapshots)
                re_models[cid] = warm_re.aligned_to(c.ds)
                own_scores = np.asarray(re_models[cid].score_dataset(c.ds))
            re_scores_home[cid] = send_scores(
                f"warm{cid}-sc", c.gids_own, own_scores,
                c.home_of_own, n_local, gid_base,
            )

    _origin_cache: dict = {}

    def _validation_metric_now(tagbase):
        """Full-model validation evaluations (the run's evaluator list,
        FIRST = primary, direction-aware) with the CURRENT coefficients:
        fixed effect scored locally on each process's validation block,
        random effects scored on their entity owners and sent home (unseen
        entities score 0 — the reference's behavior)."""
        fe_val_home = _host_scores(val, fe_shard, fe_coeffs)
        total = val_base_off + fe_val_home
        for vcid in re_cids:
            vc = val_coords[vcid]
            vmodel = re_models[vcid]
            if vmodel is not None and vmodel.projector is not None:
                # _re_score_rows scatters per-entity coefficients by GLOBAL
                # column id; a projected model's slots index the projected
                # space, so score via its exact back-projection — computed
                # once per trained model, not once per tracked update
                cached = _origin_cache.get(vcid)
                if cached is None or cached[0] is not vmodel:
                    _origin_cache[vcid] = (vmodel, vmodel.to_original_space())
                vmodel = _origin_cache[vcid][1]
            own_scores = (
                _re_score_rows(vmodel, vc.X_own, vc.ids_own)
                if vmodel is not None
                else np.zeros(len(vc.gids_own))
            )
            total = total + send_scores(
                f"{tagbase}{vcid}-vs", vc.gids_own, own_scores,
                vc.home_of_own, n_val_local, vgid_base,
            )
        evals = _gathered_evaluations(
            evaluators, total, val_labels, val_weights, val.ids
        )
        primary = evaluators[0]
        return primary.name, evals[primary.name], primary.larger_is_better, evals

    per_config = []
    resumed_track = None
    if resume_cursor is not None:
        (fe_coeffs, fe_vars, re_models, re_scores_home, resumed_track,
         per_config) = ckpt.load(resume_cursor, coord_configs, task, coords)

    # a locked fixed effect never changes: score its contribution once
    # (AFTER any resume load — the locked coefficients come from there when
    # the warm-start block was skipped)
    fe_home_locked = (
        _host_scores(train, fe_shard, fe_coeffs) if fe_cid in locked else None
    )
    def _train_config(i, opt_configs, track):
        """Train ONE configuration (all CD passes, per-update tracking,
        checkpointing) and append its per_config entry — shared by the grid
        sweep and the hyperparameter-tuning loop."""
        nonlocal fe_coeffs, fe_vars, last_fe_data

        def _track(tagbase):
            if not has_val:
                return
            if any(re_models[c_] is None for c_ in re_cids):
                # a snapshot before every coordinate has trained once is not
                # a saveable GAME model; candidates start at the first update
                # that completes the coordinate set
                return
            name, value, larger, evals = _validation_metric_now(tagbase)
            logger.debug("update %s validation %s=%.6f", tagbase, name, value)
            better = (
                track["value"] is None
                or (value > track["value"] if larger else value < track["value"])
            )
            if better:
                track.update(
                    value=value,
                    metric=name,
                    evaluations=evals,
                    fe=np.asarray(fe_coeffs).copy(),
                    fe_vars=None if fe_vars is None else np.asarray(fe_vars).copy(),
                    re={c_: re_models[c_] for c_ in re_cids},
                )

        for p in range(n_iter):
            if (
                resume_cursor is not None
                and i == resume_cursor[0]
                and p <= resume_cursor[1]
            ):
                continue  # pass completed before the checkpoint
            if fe_cid not in locked:
                # fixed effect: residual = base + sum of RE scores
                off_home = base_off_home + sum(re_scores_home.values())
                off_pad = np.zeros(per_process)
                off_pad[:n_local] = off_home
                from photon_ml_tpu.parallel.distributed import host_local_to_global

                fe_data = dataclasses_replace_offsets(fe_train, host_local_to_global(
                    off_pad.astype(np.float32), mesh,
                    global_rows=fe_train.labels.shape[0],
                ))
                if fe_sampler is not None:
                    # fresh mask per CD pass (call index = p; the single-
                    # process sampler is rebuilt per config, so its counter
                    # is the pass index), keyed by concat-order sample
                    # positions — the multi-process masks equal the single-
                    # process run's exactly
                    fe_data = _dc.replace(
                        fe_data,
                        weights=_downsampled_weights_global(
                            fe_sampler, p, train, dsids_local, per_process,
                            mesh, fe_train.labels.shape[0],
                        ),
                    )
                with Timed(f"cfg{i} pass{p} fixed-effect solve", logger):
                    fe_coeffs, _ = train_glm_sharded(
                        fe_data, task, opt_configs[fe_cid], mesh,
                        initial_coefficients=fe_coeffs,
                        normalization=norm_ctxs.get(fe_shard),
                        lower_bounds=fe_lower, upper_bounds=fe_upper,
                    )
                if has_val:
                    # per-update variances ride the update, as in the single-
                    # process coordinate (the saved snapshot keeps its own);
                    # without validation only the config-final model is saved,
                    # so per-update Hessian passes would be thrown away
                    fe_vars = _sharded_fe_variances(
                        args, fe_data, fe_coeffs, opt_configs[fe_cid], task,
                        norm_ctxs.get(fe_shard), mesh,
                    )
                _track(f"c{i}p{p}fe-")
                last_fe_data = fe_data
            if fe_home_locked is None:
                fe_home = _host_scores(train, fe_shard, fe_coeffs)
            else:
                fe_home = fe_home_locked
            for cid in re_cids:
                if cid in locked:
                    # scored (re_scores_home keeps the warm contribution),
                    # never re-optimized
                    continue
                c = coords[cid]
                partial = base_off_home + fe_home + sum(
                    s for k, s in re_scores_home.items() if k != cid
                )
                off_own = send_offsets(f"c{i}p{p}{cid}-off", c, partial)
                with Timed(f"cfg{i} pass{p} {cid} solve", logger):
                    model, _tracker = train_random_effect(
                        c.ds, task, opt_configs[cid], jnp.asarray(off_own, jnp.float32),
                        initial_model=re_models[cid], dtype=jnp.float32,
                        variance_computation=vtype,
                        # normalization folds per bucket; models stay in
                        # original space (the projector carries it instead
                        # for projected coordinates)
                        normalization=c.norm if c.projector is None else None,
                        # dict entries resolve against the owner's own entity
                        # set; absent entities keep the config weight
                        per_entity_reg_weights=coord_configs[cid].per_entity_reg_weights,
                    )
                re_models[cid] = model
                own_scores = np.asarray(model.score_dataset(c.ds))
                re_scores_home[cid] = send_scores(
                    f"c{i}p{p}{cid}-sc", c.gids_own, own_scores,
                    c.home_of_own, n_local, gid_base,
                )
                _track(f"c{i}p{p}{cid}-")
            if (
                not has_val
                and p + 1 == n_iter
                and fe_cid not in locked
                and last_fe_data is not None
            ):
                # config-final variances (the only saved model on the no-
                # validation branch) — computed BEFORE the config-end
                # checkpoint so a resume lands with the right values
                fe_vars = _sharded_fe_variances(
                    args, last_fe_data, fe_coeffs, opt_configs[fe_cid], task,
                    norm_ctxs.get(fe_shard), mesh,
                )
            if ckpt is not None and (
                (p + 1) % ckpt.interval == 0 or p + 1 == n_iter
            ):
                ckpt.save(
                    i, p, fe_coeffs, fe_vars, re_models, re_scores_home,
                    track, len(per_config),
                )
        if has_val:
            logger.info(
                "cfg%d best per-update validation %s=%.6f",
                i, track["metric"], track["value"],
            )
            per_config.append({
                "configs": opt_configs,
                "fe": track["fe"],
                "fe_vars": track["fe_vars"],
                "re": track["re"],
                "metric": track["metric"],
                "value": track["value"],
                "evaluations": track["evaluations"],
                "auc": track["value"] if track["metric"] == "AUC" else None,
            })
        else:
            per_config.append({
                "configs": opt_configs,
                "fe": np.asarray(fe_coeffs),
                "fe_vars": None if fe_vars is None else np.asarray(fe_vars),
                "re": {cid: re_models[cid] for cid in re_cids},
                "metric": None,
                "value": None,
                "evaluations": None,
                "auc": None,
            })
        if ckpt is not None:
            ckpt.save_config(len(per_config) - 1, per_config[-1])

    for i, opt_configs in enumerate(sweep):
        if resume_cursor is not None and i < len(per_config):
            continue  # config fully finished before the checkpoint
        # per-update best-snapshot tracking within this configuration — the
        # single-process CoordinateDescent's selection semantics
        # (CoordinateDescent.scala:256-289): every coordinate update is a
        # selection candidate, not just the configuration's final state
        if resumed_track is not None and resume_cursor is not None and i == resume_cursor[0]:
            track = resumed_track
            resumed_track = None
        else:
            track = {
                "value": None, "metric": None, "evaluations": None, "fe": None,
                "fe_vars": None, "re": None,
            }
        _train_config(i, opt_configs, track)

    # -- hyperparameter tuning (GameTrainingDriver.runHyperparameterTuning) --
    # The GP/random proposals are deterministic functions of (observations,
    # seed), and every rank observes IDENTICAL gathered metric values, so all
    # ranks propose and train the same candidates in lockstep — no extra
    # coordination needed beyond the training exchanges themselves.
    from photon_ml_tpu.types import HyperparameterTuningMode

    tuned_start = len(sweep)
    tuning_mode = HyperparameterTuningMode(
        getattr(args, "hyper_parameter_tuning", "NONE") or "NONE"
    )
    if tuning_mode != HyperparameterTuningMode.NONE and has_val:
        from photon_ml_tpu.estimators.evaluation_function import (
            GameEstimatorEvaluationFunction,
        )
        from photon_ml_tpu.hyperparameter.tuner import build_tuner

        is_max = evaluators[0].larger_is_better
        fn = GameEstimatorEvaluationFunction(
            estimator=None, data=None, validation_data=None,
            base_configs={c: coord_configs[c].optimization_config
                          for c in coord_ids},
            is_opt_max=is_max,
        )
        observations = [
            (
                fn._scale_forward(fn.configuration_to_vector(e["configs"])),
                (-e["value"] if is_max else e["value"]),
            )
            for e in per_config
            if e["value"] is not None
        ]

        def mp_eval(candidate):
            nonlocal resumed_track, fe_coeffs, fe_vars
            configs = fn.vector_to_configuration(fn._scale_backward(candidate))
            j = len(per_config)
            if (
                resumed_track is not None
                and resume_cursor is not None
                and j == resume_cursor[0]
            ):
                # the job died mid-tuned-config; the GP re-proposed the same
                # candidate (identical observations), so its per-update best
                # snapshot resumes exactly like a grid config's would (the
                # cold-start below already happened before the checkpoint)
                track_j = resumed_track
                resumed_track = None
            else:
                track_j = {
                    "value": None, "metric": None, "evaluations": None,
                    "fe": None, "fe_vars": None, "re": None,
                }
                # tuned candidates COLD-start (locked coordinates keep their
                # loaded models): the single-process evaluation function runs
                # a fresh fit per candidate, not a warm continuation
                # (estimators/evaluation_function.py _fit_with)
                if fe_cid not in locked:
                    fe_coeffs = None
                    fe_vars = None
                for cid_ in re_cids:
                    if cid_ not in locked:
                        re_models[cid_] = None
                        re_scores_home[cid_] = np.zeros(n_local)
            _train_config(j, configs, track_j)
            entry = per_config[-1]
            return (
                (-entry["value"] if is_max else entry["value"]),
                entry,
            )

        # a resume that restored finished tuned entries runs only the
        # REMAINING iterations (the restored entries already feed the GP
        # through `observations`, and the tuner fast-forwards its Sobol
        # stream past the draws they consumed)
        n_restored_tuned = max(0, len(per_config) - tuned_start)
        remaining = args.hyper_parameter_tuning_iterations - n_restored_tuned
        tuner = build_tuner(getattr(args, "hyper_parameter_tuner", "ATLAS"))
        if remaining > 0:
            with Timed("hyperparameter tuning", logger):
                tuner.search(
                    remaining,
                    fn.num_params,
                    tuning_mode,
                    mp_eval,
                    observations,
                    resumed=n_restored_tuned,
                )

    if has_val:
        values = [r["value"] for r in per_config]
        larger = evaluators[0].larger_is_better
        best_i = int(np.argmax(values) if larger else np.argmin(values))
    else:
        best_i = len(per_config) - 1  # no validation: last (weakest-reg) config
    logger.info("selected model %d of %d", best_i, len(per_config))
    summary = {
        "multiprocess": True,
        "results": [
            {
                "regularization_weight": {
                    cid: r["configs"][cid].regularization_weight for cid in coord_ids
                },
                "auc": r["auc"],
                "metric": r["metric"],
                "value": r["value"],
                "evaluations": r["evaluations"],
            }
            for r in per_config
        ],
        "best_index": best_i,
        "output_directory": root,
        "num_processes": nproc,
    }

    # ---- assemble + save models (rank 0) --------------------------------------
    # ModelOutputMode (GameTrainingDriver.scala:759-826): BEST writes best/
    # only; ALL additionally writes models/<i>/ per trained configuration,
    # EXPLICIT excludes tuned results, TUNED saves only them; NONE writes no
    # model (summary.json still lands).
    from photon_ml_tpu.cli.parsers import ModelOutputMode

    output_mode = ModelOutputMode(args.output_mode)
    save_tuned = output_mode == ModelOutputMode.TUNED
    model_dir = os.path.join(spill, "model-parts")
    os.makedirs(model_dir, exist_ok=True)
    # (tag, config index, output dirs): parts are written once per config
    # tag — best/ reuses its own config's parts rather than serializing the
    # same (possibly millions-of-entities) tables twice
    to_save: list = []
    if output_mode != ModelOutputMode.NONE:
        if output_mode == ModelOutputMode.ALL:
            save_indices = list(range(len(per_config)))
        elif output_mode == ModelOutputMode.EXPLICIT:
            # EXPLICIT deliberately EXCLUDES tuned results, as single-process
            # (GameTrainingDriver.scala:759-826 save semantics)
            save_indices = sorted({*range(tuned_start), best_i})
        elif save_tuned:
            save_indices = sorted({*range(tuned_start, len(per_config)), best_i})
        else:
            save_indices = [best_i]
        for i in save_indices:
            dirs = []
            if i == best_i:
                dirs.append(os.path.join(root, "best"))
            if (
                output_mode == ModelOutputMode.ALL
                or (output_mode == ModelOutputMode.EXPLICIT and i < tuned_start)
                or (save_tuned and i >= tuned_start)
            ):
                dirs.append(os.path.join(root, "models", str(i)))
            to_save.append((f"cfg{i}", i, dirs))
    for tag, idx, _ in to_save:
        for cid in re_cids:
            if cid in locked:
                continue  # identical verbatim model on every rank: no parts
            m = per_config[idx]["re"][cid]
            np.savez(
                os.path.join(model_dir, f"{cid}-{tag}-part{rank:05d}.npz"),
                entity_ids=np.asarray(m.entity_ids, dtype=str),
                coeffs=np.asarray(m.coeffs),
                proj=np.asarray(m.proj_indices),
                variances=np.asarray(m.variances)
                if m.variances is not None
                else np.zeros((0, 0)),
            )
    shuffle_barrier("model-parts")

    def _assemble_result(tag, entry) -> "GameResult":
        glm = GeneralizedLinearModel(
            Coefficients(
                jnp.asarray(entry["fe"]),
                None if entry.get("fe_vars") is None
                else jnp.asarray(entry["fe_vars"]),
            ),
            TaskType(task),
        )
        models = {fe_cid: FixedEffectModel(model=glm, feature_shard_id=fe_shard)}
        for cid in re_cids:
            if cid in locked:
                # verbatim pass-through of the loaded locked model
                models[cid] = entry["re"][cid]
                continue
            parts = []
            for r in range(nproc):
                with np.load(
                    os.path.join(model_dir, f"{cid}-{tag}-part{r:05d}.npz")
                ) as z:
                    parts.append({k: z[k] for k in z.files})
            k_max = max(int(p["coeffs"].shape[1]) if p["coeffs"].size else 1 for p in parts)
            has_vars = any(p["variances"].size for p in parts)
            ids_all, coeff_rows, proj_rows, var_rows = [], [], [], []
            for part in parts:
                e = len(part["entity_ids"])
                ids_all.extend(str(x) for x in part["entity_ids"])
                cpad = np.zeros((e, k_max), dtype=np.float32)
                ppad = np.full((e, k_max), -1, dtype=np.int32)
                vpad = np.zeros((e, k_max), dtype=np.float32)
                if e:
                    k = part["coeffs"].shape[1]
                    cpad[:, :k] = part["coeffs"]
                    ppad[:, :k] = part["proj"]
                    if part["variances"].size:
                        vpad[:, :k] = part["variances"]
                coeff_rows.append(cpad)
                proj_rows.append(ppad)
                var_rows.append(vpad)
            dc = coord_configs[cid].data_config
            models[cid] = RandomEffectModel(
                re_type=dc.random_effect_type,
                feature_shard_id=dc.feature_shard_id,
                task=TaskType(task),
                entity_ids=tuple(ids_all),
                coeffs=jnp.asarray(np.concatenate(coeff_rows) if ids_all else np.zeros((0, 1))),
                proj_indices=jnp.asarray(
                    np.concatenate(proj_rows) if ids_all else np.full((0, 1), -1, np.int32)
                ),
                variances=jnp.asarray(np.concatenate(var_rows))
                if has_vars and ids_all
                else None,
                # the ONE projector instance training used (built at ingest)
                projector=coords[cid].projector,
            )
        game_model = GameModel(models={c: models[c] for c in coord_ids})
        return GameResult(
            model=game_model, best_model=game_model,
            configuration=entry["configs"],
            evaluations=entry.get("evaluations")
            or ({entry["metric"]: entry["value"]} if entry["value"] is not None else None),
            best_metric=entry["value"], descent=None,
        )

    if rank == 0:
        for tag, idx, out_dirs in to_save:
            result = _assemble_result(tag, per_config[idx])
            for out_dir in out_dirs:
                _save_result(
                    out_dir, result, imaps_by_coord,
                    coord_configs, args.model_sparsity_threshold, logger,
                )
        if to_save:
            os.makedirs(os.path.join(root, "index-maps"), exist_ok=True)
            for shard in {c.data_config.feature_shard_id for c in coord_configs.values()}:
                index_maps[shard].save(os.path.join(root, "index-maps", f"{shard}.npz"))
        with open(os.path.join(root, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
    shuffle_barrier("train-done")
    if rank == 0:
        # every rank is past its last read (the barrier above): the spills
        # are scratch, not output
        import shutil

        shutil.rmtree(spill, ignore_errors=True)
    return summary


def dataclasses_replace_offsets(data, offsets):
    return _dc.replace(data, offsets=offsets)


def _build_norm_contexts(args, train, shard_ids, index_maps, logger, rank=0) -> dict:
    """{shard: NormalizationContext} from GLOBAL statistics for each shard —
    the one construction both multi-process runners share. Empty when
    normalization is off.

    ``--data-summary-directory`` rides the same pass: each needed shard's
    statistics are reduced ONCE (per-rank column sums meeting in a host
    allgather) and feed both the normalization context and the per-shard
    FeatureSummarizationResultAvro (game_training_driver.py:407-417 /
    ModelProcessingUtils.writeBasicStatistics:516-606; rank 0 writes).
    The shard iteration order is deterministic (sorted) — EVERY rank must
    execute the collectives identically."""
    norm_type = NormalizationType(args.normalization)
    summary_dir = getattr(args, "data_summary_directory", None)
    if norm_type == NormalizationType.NONE and not summary_dir:
        return {}
    from photon_ml_tpu.normalization import NormalizationContext
    from photon_ml_tpu.util.timed import Timed

    norm_shards = set(shard_ids) if norm_type != NormalizationType.NONE else set()
    # the summary covers every configured shard, as single-process does
    shards = sorted(norm_shards | (set(train.features) if summary_dir else set()))
    out = {}
    for shard_id in shards:
        with Timed(f"global feature statistics [{shard_id}]", logger):
            stats = _global_feature_stats(
                train, shard_id, index_maps[shard_id].intercept_index
            )
        if summary_dir and rank == 0:
            from photon_ml_tpu.cli.game_training_driver import (
                SUMMARY_FILE,
                _write_feature_summary,
            )

            _write_feature_summary(
                os.path.join(summary_dir, f"{shard_id}-{SUMMARY_FILE}"),
                shard_id, index_maps[shard_id], stats,
            )
        if shard_id in norm_shards:
            out[shard_id] = NormalizationContext.build(norm_type, stats)
    return out


def _global_feature_stats(game_input, shard: str, intercept_index):
    """FeatureDataStatistics over the GLOBAL dataset from per-process slices:
    each process reduces its own rows to per-column sums (sparse-safe, zeros
    contribute implicitly) and the sums meet in a host allgather — the
    multi-process form of MultivariateOnlineSummarizer. Matches
    FeatureDataStatistics.compute on the concatenated data exactly (sample
    variance, ddof=1)."""
    import scipy.sparse as sp

    from jax.experimental import multihost_utils
    from photon_ml_tpu.normalization import FeatureDataStatistics

    X = game_input.shard(shard)
    n_local, d = X.shape
    if sp.issparse(X):
        Xc = X.tocsc()
        if Xc.dtype != np.float64:
            # squares and sums in float64: the variance cancellation
            # s2 - n*mean^2 goes catastrophically wrong in f32 when
            # |mean| >> std (and f32 squares already lose digits at ~1e4)
            Xc = Xc.astype(np.float64)
        s1 = np.asarray(Xc.sum(axis=0)).ravel()
        s2 = np.asarray(Xc.multiply(Xc).sum(axis=0)).ravel()
        sabs = np.asarray(abs(Xc).sum(axis=0)).ravel()
        nnz = np.diff(Xc.indptr).astype(np.float64)
        # vectorized per-column min/max over stored values — the same
        # reduceat-with-empty-column-guard as FeatureDataStatistics._compute_sparse
        mins = np.zeros(d)
        maxs = np.zeros(d)
        if n_local:
            nonempty = nnz > 0
            if Xc.nnz:
                safe_starts = np.minimum(Xc.indptr[:-1], Xc.nnz - 1)
                col_min = np.minimum.reduceat(Xc.data, safe_starts)
                col_max = np.maximum.reduceat(Xc.data, safe_starts)
                mins[nonempty] = col_min[nonempty]
                maxs[nonempty] = col_max[nonempty]
            has_implicit_zero = nnz < n_local
            mins = np.where(has_implicit_zero, np.minimum(mins, 0.0), mins)
            maxs = np.where(has_implicit_zero, np.maximum(maxs, 0.0), maxs)
    else:
        Xd = np.asarray(X, dtype=np.float64)
        s1 = Xd.sum(axis=0)
        s2 = (Xd * Xd).sum(axis=0)
        sabs = np.abs(Xd).sum(axis=0)
        nnz = (Xd != 0).sum(axis=0).astype(np.float64)
        mins = Xd.min(axis=0) if n_local else np.zeros(d)
        maxs = Xd.max(axis=0) if n_local else np.zeros(d)
    if n_local == 0:
        # inert aggregands; min/max use infinities so empty slices never win
        mins = np.full(d, np.inf)
        maxs = np.full(d, -np.inf)
    parts = multihost_utils.process_allgather(
        (np.asarray([float(n_local)]), s1, s2, sabs, nnz, mins, maxs)
    )
    # some jax versions return single-process allgathers WITHOUT the leading
    # process axis; normalize every part to [P, ...] so the axis-0 reductions
    # below reduce over processes, never over features
    counts, s1g, s2g, sabsg, nnzg, minsg, maxsg = (
        np.asarray(x).reshape(-1, *ref.shape)
        for x, ref in zip(parts, (np.empty(1), s1, s2, sabs, nnz, mins, maxs))
    )
    n = float(counts.sum())
    if n < 1:
        raise ValueError("Cannot compute feature statistics over zero samples")
    mean = s1g.sum(axis=0) / n
    var = (
        (s2g.sum(axis=0) - n * mean**2) / (n - 1.0)
        if n > 1
        else np.zeros(d)
    )
    return FeatureDataStatistics(
        count=int(n),
        mean=mean,
        variance=np.maximum(var, 0.0),
        min=minsg.min(axis=0),
        max=maxsg.max(axis=0),
        num_nonzeros=nnzg.sum(axis=0),
        mean_abs=sabsg.sum(axis=0) / n,
        intercept_index=intercept_index,
    )


def _host_scores(game_input, shard: str, coeffs) -> np.ndarray:
    """This process's rows of X @ coeffs, computed HOST-SIDE from its own
    file slice.

    Never slice ``addressable_shards`` of a distributed matvec for this: if
    XLA returns the result replicated (it may, and did), every process's
    "local block" aliases the TOP of the global array — rank r>0 silently
    reads rank 0's rows. Caught by the GAME parity tests once their
    random-effect features became non-trivial: every rank's residual offsets
    paired other ranks' fixed-effect scores with its own labels."""
    X = game_input.shard(shard)
    w = np.asarray(coeffs, dtype=np.float64)
    return np.asarray(X @ w).ravel()


def _gather_blocks(*arrays):
    """Host-allgather variable-length per-process blocks, padded with
    weight-0 rows (inert in every weighted statistic). Dtypes are
    preserved (group-key arrays ride along with the float triples)."""
    from jax.experimental import multihost_utils

    n = np.asarray([len(arrays[0])])
    counts = np.asarray(multihost_utils.process_allgather(n)).ravel()
    m = int(counts.max()) if len(counts) else 0

    def pad(v):
        v = np.asarray(v)
        out = np.zeros(m, dtype=v.dtype if v.dtype.kind in "if" else np.float64)
        out[: len(v)] = v
        return out

    # the gather pads each process block to the max length; DROP the padding
    # rows afterwards (their positions are known exactly from the counts) —
    # sentinel values would corrupt ranking metrics (a padding score in a
    # PRECISION@K top-K) or weighted ones (0 * inf = NaN in RMSE)
    keep = np.concatenate([
        np.arange(m, dtype=np.int64) < c for c in counts
    ]) if m else np.zeros(0, dtype=bool)
    return tuple(
        np.asarray(x).reshape(-1)[keep]
        for x in multihost_utils.process_allgather(tuple(pad(v) for v in arrays))
    )


def _resolve_validation_evaluators(args, task):
    """The validation evaluator list, FIRST = primary (the single-process
    suite's convention): parsed --evaluators specs, or the task's default."""
    from photon_ml_tpu.cli.parsers import parse_evaluator_spec
    from photon_ml_tpu.estimators.game_estimator import default_evaluator_type
    from photon_ml_tpu.evaluation.evaluators import evaluator_for_type

    raw = getattr(args, "evaluators", None)
    if raw:
        specs = [parse_evaluator_spec(e) for e in raw.split(",") if e.strip()]
        if not specs:
            raise ValueError(f"--evaluators {raw!r} names no evaluators")
        return specs
    return [evaluator_for_type(default_evaluator_type(TaskType(task)))]


def _group_keys(ids) -> np.ndarray:
    """Entity-id strings -> int32 group keys for the gathered per-group
    evaluators. Only group EQUALITY matters; blake2-derived 31-bit keys make
    collisions negligible at realistic group counts and stay exact through
    the x64-disabled allgather."""
    from photon_ml_tpu.parallel.shuffle import entity_owner_hash

    if len(ids) == 0:
        return np.zeros(0, dtype=np.int32)
    return (entity_owner_hash(ids) % np.uint64(2**31)).astype(np.int32)


def _gathered_evaluations(evaluators, scores, labels, weights, id_lookup):
    """{evaluator name: value} over the gathered validation set. Per-group
    evaluators (MultiEvaluator, e.g. AUC:userId / PRECISION@K:id) gather
    their group keys alongside the score triples; padding rows carry weight
    0 and their all-padding groups evaluate to NaN, which evaluate_grouped
    skips."""
    from photon_ml_tpu.evaluation.evaluators import MultiEvaluator

    tags = []
    for ev in evaluators:
        if isinstance(ev, MultiEvaluator) and ev.id_tag not in tags:
            tags.append(ev.id_tag)
    arrays = [scores, labels, weights]
    arrays += [_group_keys(id_lookup(tag)) for tag in tags]
    gathered = _gather_blocks(*arrays)
    sg, lg, wg = gathered[:3]
    groups = dict(zip(tags, gathered[3:]))
    out = {}
    for ev in evaluators:
        if isinstance(ev, MultiEvaluator):
            out[ev.name] = float(
                ev.evaluate_grouped(sg, lg, wg, groups[ev.id_tag])
            )
        else:
            out[ev.name] = float(ev.evaluate(sg, lg, wg))
    return out
