"""Continuous-training CLI driver: the unattended ingest→train→serve loop.

The process form of :class:`photon_ml_tpu.continuous.trainer.ContinuousTrainer`:
point it at corpus directories that GROW by part files and a checkpoint root,
and it polls for new data, runs active-set delta passes warm-started from the
last committed generation, and commits each pass as a new ``gen-<n>/``
checkpoint — which a serving replica's ``--hot-swap-watch``
(cli/serving_driver.py, PR 6) picks up with zero downtime. Restarting the
process resumes from the newest committed generation (the corpus manifest and
frozen index maps ride inside it), so the loop is crash-safe end to end.

Flags mirror the training driver's where they overlap; there is no sweep —
continuous mode drives exactly one optimization configuration.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from photon_ml_tpu.cli.parsers import (
    add_version_argument,
    parse_coordinate_configuration,
    parse_feature_shard_configuration,
)
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.util import PhotonLogger, Timed

GENERATIONS_FILE = "generations.json"  # bounded summary, rewritten per commit
GENERATIONS_LOG = "generations.jsonl"  # full history, one record APPENDED per commit


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="continuous-training-driver",
        description="Continuously retrain a GAME (GLMix) model on corpus deltas.",
    )
    add_version_argument(p)
    p.add_argument("--input-data-directories", required=True,
                   help="Comma-separated corpus paths; part files APPEND over "
                        "time (append-only contract, verified)")
    p.add_argument("--checkpoint-directory", required=True,
                   help="Generational checkpoint root: each delta pass commits "
                        "gen-<n>/ here; restarts resume from the newest valid "
                        "generation; serving hot-swap watches this directory")
    p.add_argument("--root-output-directory", default=None,
                   help="Logs + per-generation summary (default: "
                        "<checkpoint-directory>/continuous-out)")
    p.add_argument("--export-directory", default=None,
                   help="Also export each generation as reference-compatible "
                        "model Avro under <dir>/gen-<n>/ (byte-deterministic)")
    p.add_argument("--feature-shard-configurations", action="append", required=True)
    p.add_argument("--coordinate-configurations", action="append", required=True)
    p.add_argument("--coordinate-update-sequence", required=True)
    p.add_argument("--training-task", required=True,
                   choices=[t.value for t in TaskType])
    p.add_argument("--delta-iterations", type=int, default=1,
                   help="Coordinate-descent iterations per delta pass")
    p.add_argument("--initial-iterations", type=int, default=1,
                   help="Iterations for the bootstrap full train (generation 1)")
    p.add_argument("--gradient-threshold", type=float, default=None,
                   help="Also re-solve entities whose subproblem gradient norm "
                        "exceeds this, even without new rows (the active-set "
                        "catch-up rule; default: off)")
    p.add_argument("--fe-reservoir", type=int, default=None,
                   help="Fixed-effect refresh reservoir: old rows keeping "
                        "nonzero weight per delta pass (seeded, unbiased "
                        "re-weighting; default: all old rows)")
    p.add_argument("--compact-every", type=int, default=None,
                   help="Fold the corpus into a new cold-tier generation and "
                        "truncate the manifest's per-file history every N "
                        "committed generations (continuous/store.py; default: "
                        "never — RAM and restart cost then grow with history)")
    p.add_argument("--evict-idle-generations", type=int, default=None,
                   help="Archive random-effect entities with no rows in the "
                        "last G generations and drop them from the device "
                        "tables; serving degrades to the missing-entity "
                        "score-0 contract, reappearance re-admits warm from "
                        "the archive (default: never evict)")
    p.add_argument("--window-mode", default="full",
                   choices=["full", "sliding", "decay"],
                   help="Row aging: 'full' trains every accumulated row; "
                        "'sliding' drops rows older than --window-generations "
                        "from the training view (bounded RAM, steady shapes); "
                        "'decay' also down-weights in-view rows by "
                        "2^(-age/half-life), derived in-trace from row-age "
                        "metadata so crash-replay stays bit-identical")
    p.add_argument("--window-generations", type=int, default=None,
                   help="Sliding-window width in generations (required for "
                        "--window-mode sliding; optional RAM bound for decay)")
    p.add_argument("--decay-half-life", type=float, default=None,
                   help="Age (in generations) at which a row's weight halves "
                        "(required for --window-mode decay)")
    p.add_argument("--cold-block-rows", type=int, default=8192,
                   help="Rows per cold-tier block (power of two)")
    p.add_argument("--max-row-age-generations", type=int, default=None,
                   help="Cold-tier retention: at each compaction DELETE rows "
                        "older than this many generations (must cover "
                        "--window-generations, so deletion only reaches rows "
                        "whose training weight is already zero; expired "
                        "blocks drop whole, the seam block rewrites sliced, "
                        "the rest reuse; default: preserve full history)")
    p.add_argument("--max-cold-rows", type=int, default=None,
                   help="Best-effort cap on cold-tier rows, enforced at "
                        "block granularity at each compaction (oldest blocks "
                        "drop first; in-window blocks never drop)")
    p.add_argument("--archive-max-age-generations", type=int, default=None,
                   help="Age out evicted-coefficient archive entries older "
                        "than this many generations at each compaction (a "
                        "that-old reappearing entity re-solves from zero; "
                        "default: archive forever)")
    p.add_argument("--max-files-per-pass", type=int, default=None,
                   help="Ingest at most this many part files per pass: a "
                        "fresh start against a deep corpus streams the "
                        "backlog through bounded windowed delta passes "
                        "(resident bytes O(window + delta)) instead of one "
                        "O(corpus) bootstrap (default: ingest everything "
                        "the scan finds)")
    p.add_argument("--poll-interval-seconds", type=float, default=10.0)
    p.add_argument("--max-generations", type=int, default=None,
                   help="Exit after committing this many generations (tests/"
                        "benches; default: run forever)")
    p.add_argument("--max-idle-polls", type=int, default=None,
                   help="Exit after this many consecutive empty scans "
                        "(default: keep polling)")
    p.add_argument("--once", action="store_true",
                   help="Process at most one pending delta and exit")
    p.add_argument("--checkpoint-keep-generations", type=int, default=8)
    p.add_argument("--seed", type=int, default=0,
                   help="Reservoir/SELECTION seed (per-generation draws fold "
                        "the generation number in)")
    p.add_argument("--log-level", default="INFO")
    p.add_argument("--fault-plan", default=None,
                   help="Deterministic fault injection plan "
                        "(resilience/faultpoints.py; also PHOTON_FAULT_PLAN)")
    from photon_ml_tpu.cli.runtime import add_ingest_arguments

    add_ingest_arguments(p)
    return p


def trainer_from_args(args: argparse.Namespace):
    from photon_ml_tpu.continuous import ContinuousTrainer, ContinuousTrainerConfig

    shard_configs = dict(
        parse_feature_shard_configuration(a)
        for a in args.feature_shard_configurations
    )
    coord_configs = dict(
        parse_coordinate_configuration(a) for a in args.coordinate_configurations
    )
    update_sequence = [c for c in args.coordinate_update_sequence.split(",") if c]
    unknown = set(update_sequence) - set(coord_configs)
    if unknown:
        raise ValueError(
            f"Update sequence references unknown coordinates: {sorted(unknown)}"
        )
    coord_configs = {c: coord_configs[c] for c in update_sequence}
    config = ContinuousTrainerConfig(
        corpus_paths=[p for p in args.input_data_directories.split(",") if p],
        checkpoint_directory=args.checkpoint_directory,
        task=TaskType(args.training_task),
        coordinate_configurations=coord_configs,
        shard_configurations=shard_configs,
        delta_iterations=args.delta_iterations,
        initial_iterations=args.initial_iterations,
        gradient_threshold=args.gradient_threshold,
        fe_reservoir=args.fe_reservoir,
        export_directory=args.export_directory,
        ingest_workers=getattr(args, "ingest_workers", None),
        keep_generations=args.checkpoint_keep_generations,
        seed=args.seed,
        compact_every=args.compact_every,
        evict_idle_generations=args.evict_idle_generations,
        window_mode=args.window_mode,
        window_generations=args.window_generations,
        decay_half_life=args.decay_half_life,
        cold_block_rows=args.cold_block_rows,
        max_row_age_gens=args.max_row_age_generations,
        max_cold_rows=args.max_cold_rows,
        archive_max_age_gens=args.archive_max_age_generations,
        max_files_per_pass=args.max_files_per_pass,
    )
    return ContinuousTrainer(config)


def run(args: argparse.Namespace) -> dict:
    from photon_ml_tpu.cli.runtime import arm_fault_plan_from_args

    arm_fault_plan_from_args(args)
    out_root = args.root_output_directory or os.path.join(
        args.checkpoint_directory, "continuous-out"
    )
    os.makedirs(out_root, exist_ok=True)
    logger = PhotonLogger(
        os.path.join(out_root, "logs", "continuous.log"), level=args.log_level
    )
    try:
        with Timed("restore continuous state", logger):
            trainer = trainer_from_args(args)

        # both files land as each generation COMMITS, not on loop exit: the
        # default unattended mode never exits, and an operator killing the
        # process must still find every committed generation's record on
        # disk. The full history APPENDS to generations.jsonl (O(1) memory
        # and I/O per commit over a process lifetime of months); the
        # rewritten generations.json keeps only a bounded summary.
        committed = 0
        last_record: Optional[dict] = None

        def summarize() -> dict:
            return {
                "final_generation": trainer.generation,
                "generations_committed": committed,
                "last_generation": last_record,
                "generations_log": os.path.join(out_root, GENERATIONS_LOG),
                "checkpoint_directory": args.checkpoint_directory,
            }

        def on_generation(r) -> None:
            nonlocal committed, last_record
            committed += 1
            last_record = {
                "generation": r.generation,
                "kind": r.kind,
                "n_rows": r.n_rows,
                "view_rows": r.view_rows,
                "compacted": r.compacted,
                "n_new_rows": r.n_new_rows,
                "active_fraction": r.active_fraction,
                "active": r.active,
                "incidents": r.incidents,
                "timings": r.timings,
                "cold_stats": r.cold_stats,
            }
            with open(os.path.join(out_root, GENERATIONS_LOG), "a") as f:
                f.write(json.dumps(last_record) + "\n")
            with open(os.path.join(out_root, GENERATIONS_FILE), "w") as f:
                json.dump(summarize(), f, indent=2)
            logger.info(
                "generation %d (%s): +%d rows, active fraction %.3f",
                r.generation, r.kind, r.n_new_rows, r.active_fraction,
            )

        if args.once:
            result = trainer.poll_once()
            if result is not None:
                on_generation(result)
        else:
            trainer.run(
                poll_interval_s=args.poll_interval_seconds,
                max_generations=args.max_generations,
                max_idle_polls=args.max_idle_polls,
                on_generation=on_generation,
            )
        # idle runs (no commits) still leave a summary file behind
        with open(os.path.join(out_root, GENERATIONS_FILE), "w") as f:
            json.dump(summarize(), f, indent=2)
        return summarize()
    finally:
        logger.close()


def main(argv: Optional[list] = None) -> int:
    args = build_arg_parser().parse_args(argv)
    run(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
