"""Model-selection sweep CLI driver.

Drives ``photon_ml_tpu/sweep``: ingest the training + validation data once,
then run the batched Bayesian hyperparameter sweep — every round trains a
POPULATION of candidate settings as one vmapped coordinate-descent run over
the shared device-resident datasets, scores them on the validation data, and
feeds the results to the GP + Expected Improvement search. The winner commits
as a generational checkpoint (``--checkpoint-directory``) the serving
hot-swap watcher can pick up directly, plus a reference-format model export
under the output root.

Axis grammar (``--sweep-axis``, repeatable)::

    coordinate=global,parameter=l2,min=0.01,max=100,transform=LOG
    coordinate=per-user,parameter=l2,min=0.001,max=10,transform=LOG
    coordinate=global,parameter=down_sampling_rate,min=0.2,max=0.9

Parameters: ``l2`` (any coordinate), ``l1`` (coordinates whose base config
carries an L1 term), ``down_sampling_rate`` (fixed-effect coordinates with a
down-sampling base rate). Transforms: LOG, SQRT, or none.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from photon_ml_tpu.cli.parsers import (
    _pop,
    add_version_argument,
    parse_coordinate_configuration,
    parse_evaluator_spec,
    parse_feature_shard_configuration,
    parse_kv_args,
)
from photon_ml_tpu.data.readers import read_merged_avro
from photon_ml_tpu.estimators.config import RandomEffectDataConfiguration
from photon_ml_tpu.estimators.game_estimator import GameEstimator
from photon_ml_tpu.sweep import SweepAxis, SweepConfig, SweepRunner, SweepSpec
from photon_ml_tpu.types import HyperparameterTuningMode, TaskType
from photon_ml_tpu.util import PhotonLogger, Timed
from photon_ml_tpu.util.date_range import resolve_input_paths

STATS_FILE = "sweep-stats.json"
EXPORT_DIR = "export"


def parse_sweep_axis(spec: str) -> SweepAxis:
    """``coordinate=...,parameter=...,min=...,max=...[,transform=...]`` —
    the shared composite grammar (parse_kv_args: duplicate keys rejected)."""
    kv = parse_kv_args(spec)
    axis = SweepAxis(
        coordinate_id=_pop(kv, "coordinate", required=True),
        parameter=_pop(kv, "parameter", required=True),
        min=float(_pop(kv, "min", required=True)),
        max=float(_pop(kv, "max", required=True)),
        transform=_pop(kv, "transform") or None,
    )
    if kv:
        raise ValueError(f"Unknown sweep-axis keys {sorted(kv)} in {spec!r}")
    return axis


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sweep-driver",
        description="Batched (vmapped) hyperparameter sweep for GAME training.",
    )
    add_version_argument(p)
    p.add_argument("--input-data-directories", required=True,
                   help="Comma-separated training data paths (Avro files/dirs)")
    p.add_argument("--validation-data-directories", required=True,
                   help="Held-out data the candidates are selected on")
    p.add_argument("--input-data-date-range", default=None)
    p.add_argument("--input-data-days-range", default=None)
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--override-output-directory", action="store_true")
    p.add_argument("--feature-shard-configurations", action="append", required=True)
    p.add_argument("--training-task", required=True,
                   choices=[t.value for t in TaskType])
    p.add_argument("--coordinate-configurations", action="append", required=True)
    p.add_argument("--coordinate-update-sequence", required=True)
    p.add_argument("--evaluators", default=None,
                   help="Comma-separated; the FIRST is the selection metric")
    p.add_argument("--sweep-axis", action="append", required=True,
                   help="coordinate=...,parameter=l2|l1|down_sampling_rate,"
                        "min=...,max=...[,transform=LOG|SQRT]")
    p.add_argument("--sweep-rounds", type=int, default=3,
                   help="Bayesian search rounds (each trains one population)")
    p.add_argument("--sweep-population", type=int, default=8,
                   help="Settings trained per round as one vmapped program")
    p.add_argument("--sweep-mode", default="BAYESIAN",
                   choices=["BAYESIAN", "RANDOM"])
    p.add_argument("--sweep-seed", type=int, default=0)
    p.add_argument("--sweep-iterations", type=int, default=1,
                   help="Coordinate-descent passes per candidate")
    p.add_argument("--sweep-path", default="auto",
                   choices=["auto", "vmapped", "sequential", "fused"],
                   help="Population execution path (auto follows the spec: "
                        "dict per-entity L2 overrides need sequential; "
                        "fused = one jit per train call covering all "
                        "settings x coordinates x iterations)")
    p.add_argument("--sweep-warm-start", action="store_true",
                   help="Seed each round's lanes from the committed table "
                        "of the nearest previous-round setting (glmnet-style "
                        "paths across Bayesian rounds; implies the fused "
                        "path)")
    p.add_argument("--sweep-freeze-tol", type=float, default=None,
                   help="Per-lane early exit: freeze a lane whose total "
                        "training score moved at most tol*(1+max|score|) "
                        "across a pass (implies the fused path; frozen "
                        "lanes carry their committed state bitwise)")
    p.add_argument("--sweep-freeze-min-iterations", type=int, default=1,
                   help="Completed passes before any lane may freeze")
    p.add_argument("--sweep-domination-bound", type=float, default=None,
                   help="Freeze lanes whose training loss exceeds this "
                        "bound (requires --sweep-freeze-tol to arm early "
                        "exit; use a negative --sweep-freeze-tol for "
                        "domination-only freezing)")
    p.add_argument("--checkpoint-directory", required=True,
                   help="Winner commits here as a generational checkpoint "
                        "(the layout serving/hotswap.GenerationWatcher polls)")
    p.add_argument("--checkpoint-keep-generations", type=int, default=4)
    p.add_argument("--fault-plan", default=None,
                   help="Deterministic fault injection plan "
                        "(resilience/faultpoints.py; also PHOTON_FAULT_PLAN)")
    p.add_argument("--compilation-cache-directory", default=None)
    from photon_ml_tpu.cli.runtime import add_ingest_arguments

    add_ingest_arguments(p)
    return p


def run(args: argparse.Namespace) -> dict:
    """Ingest → sweep → winner commit + export. Returns a summary dict."""
    from photon_ml_tpu.cli.runtime import (
        arm_fault_plan_from_args,
        configure_compilation_cache,
        prepare_output_root,
    )

    arm_fault_plan_from_args(args)
    configure_compilation_cache(args)
    root = args.root_output_directory
    prepare_output_root(root, args.override_output_directory, 0, 1)
    logger = PhotonLogger(os.path.join(root, "logs", "photon.log"))
    try:
        task = TaskType(args.training_task)
        shard_configs = dict(
            parse_feature_shard_configuration(a)
            for a in args.feature_shard_configurations
        )
        coord_configs = dict(
            parse_coordinate_configuration(a) for a in args.coordinate_configurations
        )
        update_sequence = [c for c in args.coordinate_update_sequence.split(",") if c]
        unknown = set(update_sequence) - set(coord_configs)
        if unknown:
            raise ValueError(
                f"Update sequence references unknown coordinates: {sorted(unknown)}"
            )
        coord_configs = {c: coord_configs[c] for c in update_sequence}
        from photon_ml_tpu.evaluation.evaluators import MultiEvaluator

        evaluator_specs = (
            [parse_evaluator_spec(e) for e in args.evaluators.split(",") if e.strip()]
            if args.evaluators
            else []
        )
        evaluator_tags = sorted(
            {ev.id_tag for ev in evaluator_specs if isinstance(ev, MultiEvaluator)}
        )
        id_tags = sorted(
            {
                cfg.data_config.random_effect_type
                for cfg in coord_configs.values()
                if isinstance(cfg.data_config, RandomEffectDataConfiguration)
            }
        )

        GameEstimator.warm_up_backend()
        ingest_workers = getattr(args, "ingest_workers", None)
        train_paths = resolve_input_paths(
            args.input_data_directories,
            getattr(args, "input_data_date_range", None),
            getattr(args, "input_data_days_range", None),
        )
        with Timed("read training data", logger):
            train_input, index_maps, _uids = read_merged_avro(
                train_paths, shard_configs, {}, id_tags,
                ingest_workers=ingest_workers,
            )
        validation_paths = resolve_input_paths(
            args.validation_data_directories, None, None
        )
        with Timed("read validation data", logger):
            validation_input, _, _ = read_merged_avro(
                validation_paths, shard_configs, index_maps,
                sorted(set(id_tags) | set(evaluator_tags)),
                ingest_workers=ingest_workers,
            )
        logger.info(
            "sweep data: %d train / %d validation samples",
            train_input.n,
            validation_input.n,
        )

        estimator = GameEstimator(
            task=task,
            coordinate_configurations=coord_configs,
            n_iterations=args.sweep_iterations,
            validation_evaluators=evaluator_specs,
        )
        spec = SweepSpec(axes=tuple(parse_sweep_axis(a) for a in args.sweep_axis))
        vmapped: object = "auto"
        fused: object = "auto"
        if args.sweep_path == "fused":
            fused = True
        elif args.sweep_path != "auto":
            vmapped = args.sweep_path == "vmapped"
            fused = False
        early_exit = None
        if args.sweep_freeze_tol is not None:
            from photon_ml_tpu.sweep import EarlyExitConfig

            early_exit = EarlyExitConfig(
                freeze_tol=args.sweep_freeze_tol,
                min_iterations=args.sweep_freeze_min_iterations,
                domination_bound=args.sweep_domination_bound,
            )
        elif args.sweep_domination_bound is not None:
            raise ValueError(
                "--sweep-domination-bound needs --sweep-freeze-tol to arm "
                "early exit (use a negative tol for domination-only)"
            )
        config = SweepConfig(
            checkpoint_directory=args.checkpoint_directory,
            rounds=args.sweep_rounds,
            population=args.sweep_population,
            mode=HyperparameterTuningMode(args.sweep_mode),
            seed=args.sweep_seed,
            n_iterations=args.sweep_iterations,
            vmapped=vmapped,
            fused=fused,
            early_exit=early_exit,
            warm_start=args.sweep_warm_start,
            export_directory=os.path.join(root, EXPORT_DIR),
            keep_generations=args.checkpoint_keep_generations,
        )
        runner = SweepRunner(estimator, spec, config)
        index_maps_by_coord = {
            cid: index_maps[cfg.data_config.feature_shard_id]
            for cid, cfg in coord_configs.items()
        }
        with Timed("sweep", logger):
            result = runner.run(
                train_input, validation_input, index_maps=index_maps_by_coord
            )

        stats = {
            "task": task.value,
            "axes": spec.describe(),
            "mode": config.mode.value,
            "rounds": config.rounds,
            "population": config.population,
            "seed": config.seed,
            "path": result.path,
            "warm_start": config.warm_start,
            "early_exit": (
                None
                if early_exit is None
                else {
                    "freeze_tol": early_exit.freeze_tol,
                    "min_iterations": early_exit.min_iterations,
                    "domination_bound": early_exit.domination_bound,
                }
            ),
            "restored": result.restored,
            "models_evaluated": result.models_evaluated,
            "winner": {
                "settings": result.winner_settings,
                "metric": result.winner_metric,
                "metrics": result.winner_metrics,
                "round": result.winner_round,
                "lane": result.winner_lane,
            },
            "history": [r.to_dict() for r in result.rounds],
            "incidents": result.incidents,
            "checkpoint_path": result.checkpoint_path,
            "export_path": result.export_path,
            # per-lane observability: the history rows above carry each
            # round's lane_iterations / frozen_at / freeze_fraction; these
            # are the sweep-level rollups + per-round acquisition seconds
            "total_solver_iterations": result.total_solver_iterations,
            "freeze_fraction": result.freeze_fraction,
            "timings": result.timings,
        }
        with open(os.path.join(root, STATS_FILE), "w") as f:
            json.dump(stats, f, indent=2)
        logger.info(
            "sweep winner %s (%s) -> %s",
            result.winner_settings,
            result.winner_metrics,
            result.checkpoint_path,
        )
        return stats
    finally:
        logger.close()


def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)
    run(args)
    return 0


if __name__ == "__main__":
    sys.exit(main())
