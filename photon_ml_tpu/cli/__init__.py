"""Command-line drivers (the photon-client layer).

- ``python -m photon_ml_tpu.cli.game_training_driver`` — GAME training
  (GameTrainingDriver.scala:55-855 equivalent)
- ``python -m photon_ml_tpu.cli.game_scoring_driver`` — GAME scoring
  (GameScoringDriver.scala:39-284 equivalent)
- ``python -m photon_ml_tpu.cli.feature_indexing_driver`` — offline feature
  index building (FeatureIndexingDriver.scala:41-320 equivalent)
- ``python -m photon_ml_tpu.cli.name_and_term_bags_driver`` — distinct
  (name, term) extraction per bag (NameAndTermFeatureBagsDriver equivalent)
- ``python -m photon_ml_tpu.cli.sweep_driver`` — batched (vmapped) Bayesian
  hyperparameter sweep; the winner commits as a generational checkpoint the
  serving hot-swap watcher picks up (photon_ml_tpu/sweep)

Flag names and composite-argument grammar mirror the reference's scopt parsers
(io/scopt/*), so reference invocations translate 1:1:
``--coordinate-configurations "name=global,feature.shard=shardA,min.partitions=1,
optimizer=LBFGS,max.iter=50,tolerance=1e-7,regularization=L2,reg.weights=0.1|1|10"``.
"""
