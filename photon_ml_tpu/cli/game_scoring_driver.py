"""GAME scoring CLI driver.

Parity target: photon-client cli/game/scoring/GameScoringDriver.scala:39-284 —
read data, load a saved GAME model, score through GameTransformer, write
ScoringResultAvro files, optionally evaluate when the data has labels.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from photon_ml_tpu.cli.game_training_driver import _load_index_maps
from photon_ml_tpu.cli.parsers import (
    add_version_argument,
    parse_evaluator_spec,
    parse_feature_shard_configuration,
)
from photon_ml_tpu.data import avro_io
from photon_ml_tpu.data.readers import read_merged_avro
from photon_ml_tpu.io.model_io import load_game_model
from photon_ml_tpu.models.game import RandomEffectModel
from photon_ml_tpu.transformers.game_transformer import GameTransformer
from photon_ml_tpu.util import PhotonLogger, Timed
from photon_ml_tpu.util.date_range import resolve_input_paths

SCORES_DIR = "scores"


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game-scoring-driver", description="Score data with a saved GAME model."
    )
    add_version_argument(p)
    p.add_argument("--input-data-directories", required=True)
    p.add_argument("--input-data-date-range", default=None,
                   help="yyyyMMdd-yyyyMMdd inclusive; expands each input dir to "
                        "its <dir>/yyyy/MM/dd day partitions")
    p.add_argument("--input-data-days-range", default=None,
                   help="START-END in days ago (START >= END), e.g. 90-1")
    p.add_argument("--model-input-directory", required=True)
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--override-output-directory", action="store_true")
    p.add_argument("--feature-shard-configurations", action="append", required=True)
    p.add_argument("--off-heap-index-map-directory", default=None)
    p.add_argument("--evaluators", default=None)
    p.add_argument("--model-id", default=None, help="ID to tag scores with")
    p.add_argument("--compilation-cache-directory", default=None,
                   help="Persistent XLA compilation cache: repeated runs skip "
                        "recompiling the optimizer programs (jit warm start "
                        "across processes)")
    p.add_argument("--compute-backend", default="host", choices=["host", "mesh"],
                   help="'mesh' scores with datasets sharded over the device mesh")
    p.add_argument("--scoring-engine", default="fused", choices=["fused", "eager"],
                   help="'fused' (default) compiles the whole scoring pipeline "
                        "into one jit-cached XLA program per batch bucket with "
                        "device-resident coefficient tables; 'eager' keeps the "
                        "per-coordinate dataset-rebuild path")
    p.add_argument("--mesh-devices", type=int, default=None,
                   help="Device count for --compute-backend=mesh (default: all)")
    from photon_ml_tpu.cli.runtime import add_distributed_arguments, add_ingest_arguments

    add_ingest_arguments(p)
    add_distributed_arguments(
        p,
        "multi-process scoring: each process scores its round-robin slice of "
        "the input part files and writes its own output part file (the "
        "executor-parallel form of GameScoringDriver)",
    )
    p.add_argument("--log-data-and-model-stats", action="store_true")
    p.add_argument("--log-level", default="INFO")
    p.add_argument("--application-name", default="game-scoring")
    # Spark-isms, accepted and ignored
    p.add_argument("--spill-scores-to-disk", action="store_true", help=argparse.SUPPRESS)
    return p


def run(args: argparse.Namespace) -> dict:
    from photon_ml_tpu.cli.runtime import initialize_distributed_from_args

    rank, nproc = initialize_distributed_from_args(args)
    if nproc > 1:
        if args.evaluators:
            raise ValueError(
                "evaluators need globally sorted scores; run them single-process "
                "on the written score files instead of multi-process scoring"
            )
        if getattr(args, "compute_backend", "host") == "mesh":
            raise ValueError(
                "--compute-backend=mesh and multi-process scoring are exclusive: "
                "each process already scores its own input slice host-locally"
            )

    from photon_ml_tpu.cli.runtime import configure_compilation_cache

    configure_compilation_cache(args)
    root = args.root_output_directory
    from photon_ml_tpu.cli.runtime import prepare_output_root

    prepare_output_root(root, args.override_output_directory, rank, nproc)
    logger = PhotonLogger(
        os.path.join(
            root, "logs", "photon.log" if nproc == 1 else f"photon-r{rank}.log"
        ),
        level=args.log_level,
    )
    try:
        shard_configs = dict(
            parse_feature_shard_configuration(a) for a in args.feature_shard_configurations
        )
        # prefer index maps saved by the training driver at <root>/index-maps —
        # the model may live at <root>/best (one level up) or <root>/models/<i>
        # (two levels up) — then the explicit off-heap dir
        # farthest first so the NEAREST directory wins the dict.update
        index_maps = {}
        for rel in (os.path.join("..", ".."), ".."):
            index_maps.update(
                _load_index_maps(
                    os.path.join(args.model_input_directory, rel, "index-maps"),
                    shard_configs,
                )
            )
        index_maps.update(
            _load_index_maps(args.off_heap_index_map_directory, shard_configs) or {}
        )
        maps_for_load = dict(index_maps)

        # model first: its coordinates define the id tags the data needs.
        # load_game_model keys index maps by COORDINATE id; model dirs carry
        # the shard id in id-info, so map via an initial listing pass.
        coord_shards = _coordinate_shards(args.model_input_directory)
        missing = sorted({s for s in coord_shards.values() if s not in maps_for_load})
        if missing:
            raise FileNotFoundError(
                f"No saved index maps found for shard(s) {missing}; expected "
                f"<model-dir>/../index-maps/<shard>.npz (training driver output) "
                f"or --off-heap-index-map-directory"
            )
        with Timed("load model", logger):
            model = load_game_model(
                args.model_input_directory,
                {cid: maps_for_load[shard] for cid, shard in coord_shards.items()},
            )
        id_tags = sorted(
            {m.re_type for _, m in model if isinstance(m, RandomEffectModel)}
        )

        input_paths = resolve_input_paths(
            args.input_data_directories,
            getattr(args, "input_data_date_range", None),
            getattr(args, "input_data_days_range", None),
        )
        if nproc > 1:
            # file-level round-robin: every process reads and scores only its
            # slice of the part files (index maps come from the saved training
            # maps, so processes agree on the feature space by construction)
            all_files = avro_io.container_files(input_paths)
            input_paths = all_files[rank::nproc]
            logger.info(
                "process %d/%d scoring %d of %d part files",
                rank, nproc, len(input_paths), len(all_files),
            )
            if not input_paths:
                logger.info("no part files for this process; nothing to score")
                return {"scores": np.zeros(0), "metrics": {}, "output_directory": root}
        # scoring-program compile latency hides behind ingest (pipeline.py)
        from photon_ml_tpu.estimators.game_estimator import GameEstimator

        GameEstimator.warm_up_backend()
        with Timed("read data", logger):
            data, index_maps, uids = read_merged_avro(
                input_paths, shard_configs, index_maps, id_tags,
                ingest_workers=getattr(args, "ingest_workers", None),
            )
        logger.info("scoring %d samples", data.n)

        evaluator_specs = (
            [parse_evaluator_spec(e) for e in args.evaluators.split(",") if e]
            if args.evaluators
            else []
        )
        mesh = None
        if getattr(args, "compute_backend", "host") == "mesh":
            from photon_ml_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(getattr(args, "mesh_devices", None))
        transformer = GameTransformer(
            model=model, evaluators=evaluator_specs, mesh=mesh,
            engine=getattr(args, "scoring_engine", "fused"),
        )
        with Timed("score", logger):
            scores, metrics = transformer.transform(data)
        if metrics:
            for name, value in metrics.items():
                logger.info("metric %s = %.6f", name, value)

        with Timed("write scores", logger):
            _write_scores(
                os.path.join(root, SCORES_DIR, f"part-{rank:05d}.avro"),
                uids, scores, data, args.model_id or "",
            )
        return {"scores": scores, "metrics": metrics, "output_directory": root}
    finally:
        logger.close()


def _coordinate_shards(model_dir: str) -> dict[str, str]:
    """coordinate id -> feature shard id from the saved model's id-info files
    (both this framework's JSON dialect and the reference's plain-text one —
    model_io._read_id_info)."""
    from photon_ml_tpu.io.model_io import _read_id_info

    out: dict[str, str] = {}
    for section, is_re in (("fixed-effect", False), ("random-effect", True)):
        base = os.path.join(model_dir, section)
        if not os.path.isdir(base):
            continue
        for cid in os.listdir(base):
            info = os.path.join(base, cid, "id-info")  # model_io.ID_INFO
            if os.path.exists(info):
                out[cid] = _read_id_info(info, random_effect=is_re).get(
                    "featureShardId", "global"
                )
    return out


def _write_scores(path, uids, scores, data, model_id: str, use_native: bool = True) -> None:
    """ScoringResultAvro records (GameScoringDriver.saveScoresToHDFS:229-256).

    The record payloads are encoded natively (photon_ml_tpu/native/avro_block_decoder.cpp
    photon_encode_scores — the output analog of the ingest decoder) when the
    library is available, falling back to the pure-Python encoder otherwise;
    both produce the same records (block boundaries differ: 65536 records per
    native block vs write_container's 4096)."""
    import numpy as np

    has_labels = data.has_labels
    os.makedirs(os.path.dirname(path), exist_ok=True)

    n = len(scores)
    from photon_ml_tpu.data import native_avro

    if use_native and native_avro.available():
        labels = np.asarray(data.labels, dtype=np.float64) if has_labels else None
        weights = np.asarray(data.weights, dtype=np.float64)
        scores_arr = np.asarray(scores, dtype=np.float64)

        def blocks(block_count=65536):
            for start in range(0, n, block_count):
                stop = min(start + block_count, n)
                uid_slice = (
                    uids[start:stop]
                    if uids is not None
                    else (str(i) for i in range(start, stop))
                )
                payload = native_avro.encode_scores(
                    uid_slice,
                    None if labels is None else labels[start:stop],
                    model_id,
                    scores_arr[start:stop],
                    weights[start:stop],
                )
                if payload is None:  # lib vanished mid-write: surface loudly
                    raise RuntimeError("native encoder failed mid-write")
                yield stop - start, payload

        avro_io.write_container_raw(path, avro_io.SCORING_RESULT_SCHEMA, blocks())
        return

    def records():
        for i in range(n):
            yield {
                "uid": str(uids[i]) if uids is not None else str(i),
                "label": float(data.labels[i]) if has_labels else None,
                "modelId": model_id,
                "predictionScore": float(scores[i]),
                "weight": float(data.weights[i]),
                "metadataMap": None,
            }

    avro_io.write_container(path, avro_io.SCORING_RESULT_SCHEMA, records())


def main(argv=None) -> int:
    run(build_arg_parser().parse_args(argv))
    return 0


if __name__ == "__main__":
    sys.exit(main())
