"""AST machinery behind jaxlint: jit-context discovery, value taint, rule checks.

Two passes per module, stdlib-``ast`` only (the linter must run without jax
installed — CI's lint job analyzes source, it never imports it):

**Pass A (ModuleIndex)** resolves import aliases to canonical dotted names
(``jnp.array`` -> ``jax.numpy.array``), collects every function/lambda, and
decides which execute under tracing: direct ``@jax.jit`` / ``jax.jit(f)``
wrapping (including ``functools.partial(jax.jit, ...)`` decorators), bodies
handed to traced higher-order functions (``lax.scan`` / ``while_loop`` /
``cond`` / ``vmap`` / ``grad`` / ...), functions *called from* any of those
(intra-module call-graph closure over simple names), and functions nested
inside a traced function (their bodies run at trace time).

**Pass B (FunctionAnalyzer)** walks each function with a "likely-traced"
taint set: parameters of traced functions (minus ``static_argnums`` /
``static_argnames``), names assigned from ``jax.* / jax.numpy.* / jax.lax.*``
calls or from calls to known-jitted functions, and anything arithmetic
derived from those. Static metadata access (``x.shape``, ``x.ndim``,
``x.dtype``, ``len(x)``, ``isinstance(x, ...)``, ``x is None``) never taints
a use — those are the false-positive guards the fixture suite pins.

The taint pass is linear per statement with loop bodies walked twice, so
loop-carried flows (``w = step(w)`` then ``float(loss(w))``) are seen without
a general fixpoint. It is a heuristic, not an escape analysis: it under-reports
flows through unannotated helper calls, and the committed baseline absorbs
what it does find in existing code.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from photon_ml_tpu.analysis.rules import Finding, RuleConfig, RULES, Severity

# canonical dotted prefixes whose calls return device values
_TRACED_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.nn.", "jax.random.", "jax.scipy.")
# jnp calls that return HOST metadata, not device values (carved out of the
# traced prefixes): dtype introspection is static under tracing
_STATIC_JNP_CALLS = {"jax.numpy.finfo", "jax.numpy.iinfo", "jax.numpy.dtype",
                     "jax.numpy.issubdtype", "jax.numpy.result_type"}
# canonical callables that wrap a function in jit
_JIT_WRAPPERS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
# canonical higher-order functions -> positional indices of traced callables
# ("rest" = every argument from that index on may be a callable / list of them)
_TRACED_HOF: dict[str, tuple] = {
    "jax.lax.scan": (0,),
    "jax.lax.while_loop": (0, 1),
    "jax.lax.fori_loop": (2,),
    "jax.lax.cond": (1, 2),
    "jax.lax.switch": (1, 2, 3, 4, 5, 6, 7),
    "jax.lax.map": (0,),
    "jax.lax.associative_scan": (0,),
    "jax.lax.custom_root": (1, 2),
    "jax.vmap": (0,),
    "jax.pmap": (0,),
    "jax.grad": (0,),
    "jax.value_and_grad": (0,),
    "jax.jacfwd": (0,),
    "jax.jacrev": (0,),
    "jax.hessian": (0,),
    "jax.checkpoint": (0,),
    "jax.remat": (0,),
    "jax.linearize": (0,),
    "jax.custom_jvp": (0,),
    "jax.custom_vjp": (0,),
}
# host-sync canonical function calls (argument must be likely-traced)
_SYNC_CALLS = {"numpy.asarray", "numpy.array", "float", "int", "bool", "complex"}
# host-sync method names (receiver must be likely-traced)
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# attribute reads that are static under tracing (never taint a use, and
# control flow on them is fine)
_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "nbytes", "itemsize", "sharding",
    "aval", "weak_type", "name", "names",
    # project design-matrix metadata: shape-derived host ints (data/matrix.py)
    "n_cols", "n_rows",
}
# builtins whose result is host/static even on traced arguments
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr", "id", "repr", "str"}
_LOGGER_NAMES = {"logging", "logger", "log", "LOG", "LOGGER", "_logger", "_log"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception", "critical", "log"}

_TAINT_TRACED = "traced"  # value lives on device / is a tracer
_TAINT_NPVIEW = "npview"  # np.asarray of a device value: host, but read-only

# --- MP001 (mixed-precision hazards) ---------------------------------------
_LOW_PRECISION_NAMES = {"bfloat16", "float16"}
_F64_NAMES = {"float64", "double"}
# whole-array reductions whose accumulator silently inherits the input dtype
_REDUCTION_CALLS = {
    "jax.numpy.sum", "jax.numpy.mean", "jax.numpy.dot", "jax.numpy.vdot",
    "jax.numpy.matmul", "jax.numpy.einsum", "jax.numpy.tensordot",
    "jax.lax.dot", "jax.lax.dot_general",
}
_REDUCTION_METHODS = {"sum", "mean", "dot"}
# fresh allocations whose dtype-less default (f32) can silently diverge from
# a module's reduced storage policy; value = first positional index at which
# a dtype may appear (zeros(shape, dtype) / full(shape, fill, dtype))
_DTYPELESS_ALLOCS = {
    "jax.numpy.zeros": 1,
    "jax.numpy.ones": 1,
    "jax.numpy.empty": 1,
    "jax.numpy.full": 2,
}


def _dtype_ref_in(node, names: set) -> bool:
    """True when the expression names one of ``names`` as a dtype: an
    attribute (jnp.bfloat16 / np.float64), a bare name, or a string literal."""
    if isinstance(node, ast.Attribute):
        return node.attr in names
    if isinstance(node, ast.Name):
        return node.id in names
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str) and node.value in names
    return False


def module_mentions_low_precision(tree: ast.Module) -> bool:
    """A module is a MIXED-PRECISION SCOPE when it references a reduced
    storage dtype anywhere (jnp.bfloat16, 'float16', ...): dtype-less
    allocations in its jitted bodies then risk diverging from the storage
    policy, which is when MP001's allocation check arms."""
    for node in ast.walk(tree):
        if _dtype_ref_in(node, _LOW_PRECISION_NAMES):
            return True
    return False


@dataclasses.dataclass
class JitParams:
    static_argnums: tuple = ()
    static_argnames: tuple = ()
    has_donate: bool = False


@dataclasses.dataclass
class FuncInfo:
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    name: str
    parent: Optional["FuncInfo"]
    jitted: bool = False  # directly wrapped / traced-HOF body
    jit_params: JitParams = dataclasses.field(default_factory=JitParams)
    callees: set = dataclasses.field(default_factory=set)
    jit_context: bool = False  # jitted, reachable from jitted, or nested in one


def _const_tuple(node) -> tuple:
    """Extract a tuple of constants from Constant / Tuple / List, else ()."""
    if isinstance(node, ast.Constant):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts if isinstance(e, ast.Constant)
        )
    return ()


def _is_literal_display(node) -> bool:
    """A Python literal a jit boundary would re-trace on / fail on: a scalar
    constant (not None/str), or a dict/list display."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float, bool, complex)) and not isinstance(
            node.value, str
        ) and node.value is not None
    return isinstance(node, (ast.Dict, ast.List, ast.DictComp, ast.ListComp))


class ModuleIndex(ast.NodeVisitor):
    """Pass A: import aliases, function table, jit marking, call graph."""

    def __init__(self):
        self.aliases: dict[str, str] = {}
        self.functions: dict[int, FuncInfo] = {}  # id(node) -> info
        self.by_name: dict[str, list[FuncInfo]] = {}
        self.jit_aliases: dict[str, JitParams] = {}  # name bound to jax.jit(f)
        self._stack: list[FuncInfo] = []
        # set by analyze_module (module_mentions_low_precision): arms MP001's
        # dtype-less-allocation check for this module's jitted bodies
        self.mixed_precision_scope = False

    # -- imports --------------------------------------------------------
    def visit_Import(self, node: ast.Import):
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module and node.level == 0:
            for a in node.names:
                self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        self.generic_visit(node)

    def canonical(self, node) -> Optional[str]:
        """Dotted name of an expression with the first segment de-aliased, or
        None for non-name expressions."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        parts[0] = self.aliases.get(parts[0], parts[0])
        return ".".join(parts)

    # -- functions ------------------------------------------------------
    def _add_function(self, node, name: str):
        info = FuncInfo(node=node, name=name, parent=self._stack[-1] if self._stack else None)
        self.functions[id(node)] = info
        self.by_name.setdefault(name, []).append(info)
        return info

    def _jit_params_from_call(self, call: ast.Call) -> JitParams:
        p = JitParams()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                p.static_argnums = _const_tuple(kw.value)
            elif kw.arg == "static_argnames":
                p.static_argnames = _const_tuple(kw.value)
            elif kw.arg in ("donate_argnums", "donate_argnames"):
                p.has_donate = True
        return p

    def _decorator_jit(self, dec) -> Optional[JitParams]:
        """JitParams if this decorator jits the function, else None."""
        if self.canonical(dec) in _JIT_WRAPPERS:
            return JitParams()
        if isinstance(dec, ast.Call):
            c = self.canonical(dec.func)
            if c in _JIT_WRAPPERS:
                return self._jit_params_from_call(dec)
            if c == "functools.partial" and dec.args:
                if self.canonical(dec.args[0]) in _JIT_WRAPPERS:
                    return self._jit_params_from_call(dec)
        return None

    def _visit_funcdef(self, node):
        info = self._add_function(node, node.name)
        for dec in node.decorator_list:
            p = self._decorator_jit(dec)
            if p is not None:
                info.jitted = True
                info.jit_params = p
            elif self.canonical(dec) in _TRACED_HOF or (
                isinstance(dec, ast.Call) and self.canonical(dec.func) in _TRACED_HOF
            ):
                info.jitted = True
        self._stack.append(info)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_funcdef
    visit_AsyncFunctionDef = _visit_funcdef

    def visit_Lambda(self, node: ast.Lambda):
        self._add_function(node, "<lambda>")
        info = self.functions[id(node)]
        self._stack.append(info)
        self.generic_visit(node)
        self._stack.pop()

    # -- jit wrapping & traced-HOF call sites ---------------------------
    def _mark_name_jitted(self, name: str, params: JitParams):
        for info in self.by_name.get(name, []):
            info.jitted = True
            if params.static_argnums or params.static_argnames or params.has_donate:
                info.jit_params = params

    def _mark_callable_arg(self, node, params: JitParams):
        if isinstance(node, ast.Name):
            self._mark_name_jitted(node.id, params)
        elif isinstance(node, ast.Lambda):
            info = self.functions.get(id(node))
            if info:
                info.jitted = True
        elif isinstance(node, (ast.List, ast.Tuple)):
            for e in node.elts:
                self._mark_callable_arg(e, params)
        elif isinstance(node, ast.Attribute):
            # self.method / obj.method: mark same-named functions in module
            self._mark_name_jitted(node.attr, params)

    def visit_Call(self, node: ast.Call):
        c = self.canonical(node.func)
        if c in _JIT_WRAPPERS and node.args:
            self._mark_callable_arg(node.args[0], self._jit_params_from_call(node))
        elif c in _TRACED_HOF:
            for pos in _TRACED_HOF[c]:
                if pos < len(node.args):
                    self._mark_callable_arg(node.args[pos], JitParams())
            for kw in node.keywords:
                if kw.arg in ("body_fun", "cond_fun", "f", "fun", "true_fun", "false_fun"):
                    self._mark_callable_arg(kw.value, JitParams())
        # call graph edge: simple callee name from the innermost function
        if self._stack:
            if isinstance(node.func, ast.Name):
                self._stack[-1].callees.add(node.func.id)
            elif isinstance(node.func, ast.Attribute):
                self._stack[-1].callees.add(node.func.attr)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign):
        # g = jax.jit(f, ...): f becomes jitted, g becomes a jitted alias
        if isinstance(node.value, ast.Call):
            c = self.canonical(node.value.func)
            if c in _JIT_WRAPPERS:
                params = self._jit_params_from_call(node.value)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.jit_aliases[t.id] = params
                    elif isinstance(t, ast.Attribute):
                        self.jit_aliases[t.attr] = params
        self.generic_visit(node)

    # -- closure --------------------------------------------------------
    def close_jit_reachability(self, reset: bool = True):
        """jit_context = jitted ∪ nested-in-jitted ∪ called-from-jit-context,
        iterated to fixpoint over the intra-module call graph. With
        ``reset=False`` existing jit_context marks (e.g. applied from a
        whole-program context) seed the closure instead of being cleared."""
        if reset:
            for info in self.functions.values():
                info.jit_context = info.jitted
        changed = True
        while changed:
            changed = False
            for info in self.functions.values():
                if not info.jit_context:
                    p = info.parent
                    if p is not None and p.jit_context:
                        info.jit_context = True
                        changed = True
                        continue
                else:
                    for callee in info.callees:
                        for target in self.by_name.get(callee, []):
                            if not target.jit_context:
                                target.jit_context = True
                                changed = True


class FunctionAnalyzer:
    """Pass B: walk one function, tracking taint and loop depth, emit findings."""

    def __init__(self, index: ModuleIndex, info: FuncInfo, path: str,
                 config: RuleConfig, findings: list, cross=None):
        self.index = index
        self.info = info
        self.path = path
        self.config = config
        self.findings = findings
        # whole-program context (analysis.project.ProjectContext) or None:
        # adds cross-module resolution to taint and the call checks below
        self.cross = cross
        self._lineno = getattr(info.node, "lineno", 0)
        self.taint: dict[str, str] = {}
        # names bound to a genuine PYTHON container (list()/dict()/display):
        # subscript stores into these are host mutations of the container,
        # not of an array, however traced the elements are — NP001 exempts
        # them (re_coeffs = list(params[...]); re_coeffs[i] = w is legal)
        self.containers: set[str] = set()
        # names currently bound to a REDUCED-PRECISION (bf16/f16) array —
        # tracked separately from `taint` so MP001 never perturbs the
        # host-sync/tracer rules' device-value reasoning
        self.lowp: set[str] = set()
        self.loop_depth = 0
        self._quiet = 0  # >0 during taint-only pre-passes over loop bodies

    # -- reporting ------------------------------------------------------
    def report(self, rule_id: str, node, message: str,
               severity: Optional[Severity] = None):
        if self._quiet or not self.config.enabled(rule_id):
            return
        self.findings.append(
            Finding(
                rule=rule_id,
                severity=severity or self.config.severity(rule_id),
                path=self.path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0) + 1,
                message=message,
                hint=RULES[rule_id].hint,
            )
        )

    # -- taint ----------------------------------------------------------
    def seed_params(self):
        # Only DIRECTLY traced boundaries (jit decorator/wrap, lax body fn)
        # guarantee tracer parameters. Functions merely reachable from jit
        # often mix arrays with python-static config args; tainting those
        # would flood TR001 with false positives.
        node = self.info.node
        if not self.info.jitted:
            return
        args = node.args
        params = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
        if args.vararg:
            params.append(args.vararg.arg)
        static = set(self.info.jit_params.static_argnames)
        for i in self.info.jit_params.static_argnums:
            if isinstance(i, int) and 0 <= i < len(params):
                static.add(params[i])
        for p in params:
            if p not in static and p != "self":
                self.taint[p] = _TAINT_TRACED

    def seed_cross_params(self):
        """Parameters some resolved call site was OBSERVED passing a traced
        value into (project fixed point) are traced here too — the cross-
        module half of seed_params, precise per-parameter rather than
        all-or-nothing."""
        if self.cross is None:
            return
        s = self.cross.lookup(self.path, self._lineno)
        if s is None:
            return
        for p in s.traced_params:
            self.taint.setdefault(p, _TAINT_TRACED)

    def _cross_resolve(self, node: ast.Call, canonical):
        if self.cross is None:
            return None
        return self.cross.resolve_call_node(self.path, self._lineno, node, canonical)

    def expr_taint(self, node) -> Optional[str]:
        """Taint kind of the value this expression produces, or None."""
        if isinstance(node, ast.Name):
            return self.taint.get(node.id)
        if isinstance(node, ast.Call):
            c = self.index.canonical(node.func)
            if c is not None:
                if c in ("jax.device_get", "float", "int", "bool", "complex"):
                    return None  # host result
                if c in ("numpy.asarray",):
                    inner = node.args and self.expr_taint(node.args[0])
                    return _TAINT_NPVIEW if inner == _TAINT_TRACED else None
                if c.startswith("numpy."):
                    return None  # numpy call result: host, writable
                if c in _STATIC_JNP_CALLS:
                    return None  # dtype introspection: host metadata
                if c.startswith(_TRACED_PREFIXES) or c in ("jax.device_put",):
                    return _TAINT_TRACED
                if c in _STATIC_CALLS:
                    return None
                if c in ("zip", "enumerate", "reversed", "sorted", "list", "tuple"):
                    # transparent containers: iterating them yields their
                    # arguments' values
                    for a in node.args:
                        t = self.expr_taint(a)
                        if t:
                            return t
                    return None
            # call of a known-jitted local function / alias returns device values
            if isinstance(node.func, ast.Name):
                if node.func.id in self.index.jit_aliases or any(
                    f.jitted for f in self.index.by_name.get(node.func.id, [])
                ):
                    return _TAINT_TRACED
            # method call on a traced receiver stays traced (x.sum(), x.astype())
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in _SYNC_METHODS:
                    return None  # host extraction
                if self.expr_taint(node.func.value) == _TAINT_TRACED:
                    return _TAINT_TRACED
            # cross-module: an internal function that returns a device value
            # (or is jitted in ITS module) taints this call's result
            s = self._cross_resolve(node, c)
            if s is not None and (s.returns_traced or s.jitted):
                return _TAINT_TRACED
            return None
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return None
            return self.expr_taint(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr_taint(node.value)
        # Arithmetic on an NPVIEW allocates a NEW writable ndarray, so only
        # TRACED survives these; a view stays a view only through direct
        # aliasing (Name), slicing (Subscript) and attributes (.T) above.
        if isinstance(node, (ast.BinOp,)):
            t = self.expr_taint(node.left) or self.expr_taint(node.right)
            return t if t == _TAINT_TRACED else None
        if isinstance(node, ast.UnaryOp):
            t = self.expr_taint(node.operand)
            return t if t == _TAINT_TRACED else None
        if isinstance(node, ast.Compare):
            t = self.expr_taint(node.left)
            for comp in node.comparators:
                t = t or self.expr_taint(comp)
            return t if t == _TAINT_TRACED else None
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                t = self.expr_taint(v)
                if t:
                    return t
            return None
        if isinstance(node, ast.IfExp):
            return self.expr_taint(node.body) or self.expr_taint(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                t = self.expr_taint(e)
                if t:
                    return t
            return None
        if isinstance(node, ast.NamedExpr):
            return self.expr_taint(node.value)
        return None

    def _taint_loop_target(self, target, iter_node):
        """Positional taint through transparent iterator wrappers: ``zip``
        pairs each target element with the matching argument and
        ``enumerate`` prepends a host int, so
        ``for i, (rc, cfg) in enumerate(zip(traced_parts, configs))`` taints
        ``rc`` but neither ``i`` nor ``cfg``. Anything else falls back to
        whole-target element taint."""
        if isinstance(iter_node, ast.Call):
            c = self.index.canonical(iter_node.func)
            args = iter_node.args
            if (
                c == "enumerate" and args
                and isinstance(target, (ast.Tuple, ast.List))
                and len(target.elts) == 2
            ):
                self._assign_taint(target.elts[0], None)
                self._taint_loop_target(target.elts[1], args[0])
                return
            if (
                c == "zip"
                and isinstance(target, (ast.Tuple, ast.List))
                and len(args) == len(target.elts)
                and not any(isinstance(a, ast.Starred) for a in args)
            ):
                for t, a in zip(target.elts, args):
                    self._taint_loop_target(t, a)
                return
            if c in ("reversed", "sorted", "list", "tuple") and len(args) == 1:
                self._taint_loop_target(target, args[0])
                return
        self._assign_taint(target, self.expr_taint(iter_node))

    def _assign_taint(self, target, kind: Optional[str]):
        if isinstance(target, ast.Name):
            if kind is None:
                self.taint.pop(target.id, None)
            else:
                self.taint[target.id] = kind
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._assign_taint(e, kind)
        elif isinstance(target, ast.Starred):
            self._assign_taint(target.value, kind)

    def _assign_lowp(self, target, is_lowp: bool):
        if isinstance(target, ast.Name):
            if is_lowp:
                self.lowp.add(target.id)
            else:
                self.lowp.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._assign_lowp(e, is_lowp)
        elif isinstance(target, ast.Starred):
            self._assign_lowp(target.value, is_lowp)

    def _is_container_expr(self, node) -> bool:
        """A display or constructor that yields a real Python container."""
        if isinstance(node, (ast.List, ast.Dict, ast.Set,
                             ast.ListComp, ast.DictComp, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            c = self.index.canonical(node.func)
            return c in (
                "list", "dict", "set",
                "collections.deque", "collections.defaultdict",
                "collections.OrderedDict", "deque", "defaultdict",
                "OrderedDict",
            )
        return False

    def _mark_container(self, target, is_container: bool):
        if isinstance(target, ast.Name):
            if is_container:
                self.containers.add(target.id)
            else:
                self.containers.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            # tuple unpacking never binds the RHS container itself
            for e in target.elts:
                self._mark_container(e, False)

    def _is_lowp_expr(self, node) -> bool:
        """True when the expression's value is (conservatively) a reduced-
        precision array: a name assigned from .astype(<bf16/f16>) or a
        creation with dtype=<bf16/f16>, propagated through attributes,
        slices and non-casting method calls. Arithmetic results are NOT
        propagated (binary ops promote, which is exactly the repair)."""
        if isinstance(node, ast.Name):
            return node.id in self.lowp
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self._is_lowp_expr(node.value)
        if isinstance(node, ast.Subscript):
            return self._is_lowp_expr(node.value)
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and node.func.attr == "astype":
                # a cast decides the dtype outright, whatever the receiver
                # was — positional OR keyword spelling
                if node.args:
                    return _dtype_ref_in(node.args[0], _LOW_PRECISION_NAMES)
                return any(
                    kw.arg == "dtype"
                    and _dtype_ref_in(kw.value, _LOW_PRECISION_NAMES)
                    for kw in node.keywords
                )
            for kw in node.keywords:
                if kw.arg == "dtype":
                    return _dtype_ref_in(kw.value, _LOW_PRECISION_NAMES)
            # cross-module: internal call returning a reduced-precision array
            # (resolved BEFORE receiver propagation — module.helper(x) has a
            # module name as its receiver, which is never lowp)
            s = self._cross_resolve(node, self.index.canonical(node.func))
            if s is not None:
                return s.returns_lowp
            if isinstance(node.func, ast.Attribute):
                # dtype-preserving method on a lowp receiver (.reshape, .T...)
                return self._is_lowp_expr(node.func.value)
            return False
        return False

    # -- control-flow-on-tracer helper ----------------------------------
    def uses_traced_value(self, node) -> bool:
        """True if evaluating this expression's *truthiness/value* forces a
        traced value — excluding static metadata (.shape/.ndim/len/isinstance/
        `is None`) so those guard patterns never fire TR001."""
        if isinstance(node, ast.Name):
            return self.taint.get(node.id) == _TAINT_TRACED
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.uses_traced_value(node.value)
        if isinstance(node, ast.Call):
            c = self.index.canonical(node.func)
            if c in _STATIC_CALLS:
                return False
            if isinstance(node.func, ast.Attribute) and self.uses_traced_value(node.func.value):
                return True
            s = self._cross_resolve(node, c)
            if s is not None:
                # a resolved project summary decides outright: a helper that
                # returns host/static metadata (shape gating, eligibility
                # booleans) never forces a tracer, whatever its arguments are
                return bool(s.returns_traced or s.jitted)
            return any(self.uses_traced_value(a) for a in node.args)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # `x is None` etc. — identity is static
            return self.uses_traced_value(node.left) or any(
                self.uses_traced_value(c) for c in node.comparators
            )
        if isinstance(node, ast.BoolOp):
            return any(self.uses_traced_value(v) for v in node.values)
        if isinstance(node, (ast.BinOp,)):
            return self.uses_traced_value(node.left) or self.uses_traced_value(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.uses_traced_value(node.operand)
        if isinstance(node, ast.Subscript):
            return self.uses_traced_value(node.value)
        if isinstance(node, ast.IfExp):
            return self.uses_traced_value(node.test)
        return False

    # -- statement walk --------------------------------------------------
    def run(self):
        self.seed_params()
        self.seed_cross_params()
        node = self.info.node
        body = node.body if isinstance(node.body, list) else [ast.Expr(node.body)]
        self.walk_body(body)
        self.check_donate()

    def walk_body(self, stmts):
        for st in stmts:
            self.walk_stmt(st)

    def walk_stmt(self, st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs are analyzed as their own functions
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self.visit_exprs(st.iter)
            # iterating a traced/array iterable yields traced elements
            self._taint_loop_target(st.target, st.iter)
            self.loop_depth += 1
            # taint-only pre-pass so the reporting pass sees loop-carried taint
            self._quiet += 1
            self.walk_body(st.body)
            self._quiet -= 1
            self.walk_body(st.body)
            self.loop_depth -= 1
            self.walk_body(st.orelse)
            return
        if isinstance(st, ast.While):
            if self.info.jit_context and self.uses_traced_value(st.test):
                self.report("TR001", st, "while-loop condition on a traced value inside jit-traced code")
            self.visit_exprs(st.test)
            self.loop_depth += 1
            self._quiet += 1
            self.walk_body(st.body)
            self._quiet -= 1
            self.walk_body(st.body)
            self.loop_depth -= 1
            self.walk_body(st.orelse)
            return
        if isinstance(st, ast.If):
            if self.info.jit_context and self.uses_traced_value(st.test):
                self.report("TR001", st, "if-condition on a traced value inside jit-traced code")
            self.visit_exprs(st.test)
            self.walk_body(st.body)
            self.walk_body(st.orelse)
            return
        if isinstance(st, ast.Assert):
            if self.info.jit_context and self.uses_traced_value(st.test):
                self.report("TR001", st, "assert on a traced value inside jit-traced code")
            self.visit_exprs(st.test)
            return
        if isinstance(st, ast.Assign):
            self.visit_exprs(st.value)
            kind = self.expr_taint(st.value)
            is_lowp = self._is_lowp_expr(st.value)
            is_container = self._is_container_expr(st.value)
            for t in st.targets:
                if isinstance(t, ast.Subscript):
                    self.check_np_mutation(t, st)
                    self.visit_exprs(t.value, t.slice)
                else:
                    self._assign_taint(t, kind)
                    self._assign_lowp(t, is_lowp)
                    self._mark_container(t, is_container)
            return
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self.visit_exprs(st.value)
                if isinstance(st.target, ast.Name):
                    self._assign_taint(st.target, self.expr_taint(st.value))
            return
        if isinstance(st, ast.AugAssign):
            self.visit_exprs(st.value)
            if isinstance(st.target, ast.Subscript):
                self.check_np_mutation(st.target, st)
                self.visit_exprs(st.target.value, st.target.slice)
            elif isinstance(st.target, ast.Name):
                if self.expr_taint(st.value) == _TAINT_TRACED:
                    self.taint[st.target.id] = _TAINT_TRACED
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self.visit_exprs(item.context_expr)
                if item.optional_vars is not None:
                    self._assign_taint(item.optional_vars, self.expr_taint(item.context_expr))
            self.walk_body(st.body)
            return
        if isinstance(st, ast.Try):
            self.walk_body(st.body)
            for h in st.handlers:
                self.walk_body(h.body)
            self.walk_body(st.orelse)
            self.walk_body(st.finalbody)
            return
        if isinstance(st, ast.Return) and st.value is not None:
            self.visit_exprs(st.value)
            return
        if isinstance(st, ast.Expr):
            self.visit_exprs(st.value)
            return
        # default: visit any expression children (Raise, Delete, ...)
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self.visit_exprs(child)

    def visit_exprs(self, *exprs):
        for e in exprs:
            for node in self._walk_skip_lambda(e):
                if isinstance(node, ast.Call):
                    self.check_call(node)
                elif isinstance(node, ast.IfExp):
                    if self.info.jit_context and self.uses_traced_value(node.test):
                        self.report("TR001", node, "ternary condition on a traced value inside jit-traced code")

    @staticmethod
    def _walk_skip_lambda(root):
        """ast.walk, but do not descend into nested lambdas — those are
        analyzed as their own functions with their own jit context."""
        stack = [root]
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, ast.Lambda):
                stack.extend(ast.iter_child_nodes(n))

    # -- rule checks on calls --------------------------------------------
    def check_call(self, node: ast.Call):
        c = self.index.canonical(node.func)
        in_jit = self.info.jit_context
        in_loop = self.loop_depth > 0

        # HS001: explicit device_get
        if c == "jax.device_get":
            if in_jit:
                self.report("HS001", node,
                            "jax.device_get inside jit-traced code forces a host sync at trace time",
                            severity=Severity.ERROR)
            elif in_loop:
                self.report("HS001", node,
                            "per-iteration jax.device_get; batch transfers into one device_get after the loop")
        # HS001: float()/int()/bool()/np.asarray()/np.array() on a traced value
        elif c in _SYNC_CALLS and node.args:
            arg_t = self.expr_taint(node.args[0])
            if arg_t == _TAINT_TRACED:
                if in_jit:
                    self.report("HS001", node,
                                f"{c}() on a traced value inside jit-traced code "
                                "(raises ConcretizationTypeError under trace)",
                                severity=Severity.ERROR)
                elif in_loop:
                    self.report("HS001", node,
                                f"per-iteration {c}() on a device value blocks dispatch pipelining")
        # HS001: .item() / .tolist() / .block_until_ready()
        elif isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
            recv_t = self.expr_taint(node.func.value)
            if recv_t == _TAINT_TRACED:
                if in_jit:
                    self.report("HS001", node,
                                f".{node.func.attr}() on a traced value inside jit-traced code",
                                severity=Severity.ERROR)
                elif in_loop:
                    self.report("HS001", node,
                                f"per-iteration .{node.func.attr}() on a device value blocks dispatch pipelining")

        # PR001: print / logging inside jitted body
        if in_jit:
            if isinstance(node.func, ast.Name) and node.func.id == "print":
                self.report("PR001", node, "print() inside a jitted body runs at trace time only")
            elif isinstance(node.func, ast.Attribute) and node.func.attr in _LOG_METHODS:
                base = node.func.value
                if isinstance(base, ast.Name) and base.id in _LOGGER_NAMES:
                    self.report("PR001", node,
                                f"{base.id}.{node.func.attr}() inside a jitted body runs at trace time only")

        # RT001b: constant ARRAY literal constructed inside a jitted body.
        # Scalar jnp.asarray(0) state inits are idiomatic, consteval'd, and
        # free — only list/tuple displays (real embedded tables) are worth
        # hoisting.
        if in_jit and c in ("jax.numpy.array", "jax.numpy.asarray") and node.args:
            a0 = node.args[0]
            if isinstance(a0, (ast.List, ast.Tuple)) and a0.elts and all(
                isinstance(e, (ast.Constant, ast.List, ast.Tuple)) for e in a0.elts
            ):
                self.report("RT001", node,
                            f"{c}(<literal array>) inside a jitted body re-embeds the constant on every trace; hoist it")

        # MP001: precision hazards inside jitted bodies
        if in_jit:
            self.check_mixed_precision(node, c)

        # HS001 (cross-module): a traced value handed to an internal function
        # that host-syncs that parameter — the flow v1's module-local taint
        # could not see (the PR 2 tracker-sync class)
        if self.cross is not None and (in_jit or in_loop):
            self.check_cross_sync(node, c, in_jit)

        # RT001a: literal python arg to a known-jitted callable without static marking
        self.check_jitted_call_args(node)

    def check_cross_sync(self, node: ast.Call, c: Optional[str], in_jit: bool):
        s = self._cross_resolve(node, c)
        if s is None or not s.sync_params:
            return
        via_attr = isinstance(node.func, ast.Attribute)
        offset = 1 if (via_attr and s.is_method) else 0
        synced = []
        for i, a in enumerate(node.args):
            idx = i + offset
            if idx < len(s.params) and s.params[idx] in s.sync_params:
                if self.expr_taint(a) == _TAINT_TRACED:
                    synced.append(s.params[idx])
        for kw in node.keywords:
            if kw.arg in s.sync_params and self.expr_taint(kw.value) == _TAINT_TRACED:
                synced.append(kw.arg)
        if not synced:
            return
        where = f"{s.qualname} (parameter(s) {sorted(set(synced))})"
        if in_jit:
            self.report(
                "HS001", node,
                f"traced value host-synced inside {where}, called from jit-traced code",
                severity=Severity.ERROR,
            )
        else:
            self.report(
                "HS001", node,
                f"per-iteration host sync: this loop passes a device value into {where}, "
                "which synchronizes it every call",
            )

    def check_mixed_precision(self, node: ast.Call, c: Optional[str]):
        """MP001 (jitted bodies only): explicit f64 promotion, accumulation
        in a reduced storage dtype, dtype-less allocation in a module that
        works with reduced storage dtypes."""
        # explicit f64 promotion: .astype(float64) or dtype=float64
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and node.args
            and _dtype_ref_in(node.args[0], _F64_NAMES)
        ):
            self.report(
                "MP001", node,
                ".astype(float64) inside a jitted body: f64 is emulated/slow "
                "on accelerators and silently widens a mixed-precision program",
            )
            return
        for kw in node.keywords:
            if kw.arg == "dtype" and _dtype_ref_in(kw.value, _F64_NAMES):
                self.report(
                    "MP001", node,
                    "dtype=float64 inside a jitted body: f64 is emulated/slow "
                    "on accelerators and silently widens a mixed-precision program",
                )
                return

        # accumulation in the storage dtype: a reduction over a bf16/f16
        # value without an explicit WIDE accumulator loses mass silently —
        # a dtype=/preferred_element_type= kwarg only counts as the repair
        # when it does not itself name a reduced dtype
        has_accumulator = any(
            kw.arg in ("dtype", "preferred_element_type")
            and not _dtype_ref_in(kw.value, _LOW_PRECISION_NAMES)
            for kw in node.keywords
        )
        if not has_accumulator:
            if c in _REDUCTION_CALLS and any(
                self._is_lowp_expr(a) for a in node.args
            ):
                self.report(
                    "MP001", node,
                    f"{c} over a reduced-precision value accumulates in the "
                    "storage dtype; pass preferred_element_type/dtype="
                    "jnp.float32 or upcast the operand first",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _REDUCTION_METHODS
                and self._is_lowp_expr(node.func.value)
            ):
                self.report(
                    "MP001", node,
                    f".{node.func.attr}() on a reduced-precision value "
                    "accumulates in the storage dtype; pass dtype=jnp.float32 "
                    "or upcast the receiver first",
                )

        # dtype-less fresh allocation in a mixed-precision module: the f32
        # default silently diverges from the storage policy
        if self.index.mixed_precision_scope and c in _DTYPELESS_ALLOCS:
            dtype_pos = _DTYPELESS_ALLOCS[c]
            if len(node.args) <= dtype_pos and not any(
                kw.arg == "dtype" for kw in node.keywords
            ):
                self.report(
                    "MP001", node,
                    f"dtype-less {c} in a mixed-precision program scope: the "
                    "default dtype can diverge from the storage policy; pass "
                    "an explicit dtype=",
                )

    def check_jitted_call_args(self, node: ast.Call):
        params = None
        name = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        if name is None:
            return
        if name in self.index.jit_aliases:
            params = self.index.jit_aliases[name]
        else:
            for f in self.index.by_name.get(name, []):
                if f.jitted:
                    params = f.jit_params
                    break
        if params is None:
            return
        for i, a in enumerate(node.args):
            if i in params.static_argnums:
                continue
            if _is_literal_display(a):
                self.report("RT001", a,
                            f"literal python argument #{i} to jitted {name!r} is not in "
                            "static_argnums/static_argnames")
        for kw in node.keywords:
            if kw.arg and kw.arg not in params.static_argnames and _is_literal_display(kw.value):
                self.report("RT001", kw.value,
                            f"literal python argument {kw.arg!r} to jitted {name!r} is not in "
                            "static_argnums/static_argnames")

    # -- NP001 -----------------------------------------------------------
    def check_np_mutation(self, target: ast.Subscript, st):
        base = target.value
        while isinstance(base, ast.Subscript):
            base = base.value
        if isinstance(base, ast.Name) and base.id in self.containers:
            return  # store into a host list/dict, not an array
        kind = self.expr_taint(base)
        if kind == _TAINT_TRACED:
            self.report("NP001", st,
                        "in-place subscript store on a jax array (immutable; raises TypeError)")
        elif kind == _TAINT_NPVIEW:
            self.report("NP001", st,
                        "in-place subscript store on np.asarray(<jax value>) — the view is "
                        "read-only; copy with np.array(...) first")

    # -- DN001 -----------------------------------------------------------
    def check_donate(self):
        info = self.info
        node = info.node
        if not info.jitted or info.jit_params.has_donate or isinstance(node, ast.Lambda):
            return
        args = node.args
        params = {a.arg for a in args.posonlyargs + args.args + args.kwonlyargs} - {"self"}
        static = set(info.jit_params.static_argnames)
        ordered = [a.arg for a in args.posonlyargs + args.args]
        for i in info.jit_params.static_argnums:
            if isinstance(i, int) and 0 <= i < len(ordered):
                static.add(ordered[i])
        updated = set()
        for sub in ast.walk(node):
            if (
                isinstance(sub, ast.Attribute)
                and sub.attr == "at"
                and isinstance(sub.value, ast.Name)
                and sub.value.id in params - static
            ):
                updated.add(sub.value.id)
        if updated:
            self.report(
                "DN001", node,
                f"jitted {info.name!r} updates parameter(s) {sorted(updated)} via .at[...] "
                "without donate_argnums/donate_argnames",
            )


def analyze_module(tree: ast.Module, path: str, config: RuleConfig, cross=None) -> list:
    """Run both passes over a parsed module; returns raw (unsuppressed)
    findings. ``cross`` (analysis.project.ProjectContext) adds whole-program
    resolution: project-closed jit reachability, traced-parameter seeds and
    cross-module sync/taint checks."""
    index = ModuleIndex()
    index.visit(tree)
    index.close_jit_reachability()
    if cross is not None:
        # jit reachability closed over the PROJECT call graph: a function
        # jit-reachable only through another module's call chain arms the
        # in-jit rules here too
        for info in index.functions.values():
            s = cross.lookup(path, getattr(info.node, "lineno", -1))
            if s is not None and s.jit_context:
                info.jit_context = True
        index.close_jit_reachability(reset=False)
    index.mixed_precision_scope = module_mentions_low_precision(tree)
    findings: list = []
    # module-level statements: analyze as a pseudo-function (not jit context)
    pseudo = ast.FunctionDef(
        name="<module>", args=ast.arguments(
            posonlyargs=[], args=[], kwonlyargs=[], kw_defaults=[], defaults=[]
        ),
        body=[s for s in tree.body], decorator_list=[], returns=None,
    )
    mod_info = FuncInfo(node=pseudo, name="<module>", parent=None)
    FunctionAnalyzer(index, mod_info, path, config, findings).run()
    for info in index.functions.values():
        FunctionAnalyzer(index, info, path, config, findings, cross=cross).run()
    seen = set()
    unique = []
    for f in findings:
        key = (f.rule, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    unique.sort(key=lambda f: (f.line, f.col, f.rule))
    return unique
