"""jaxlint rule registry: ids, default severities, messages, fix hints.

Each rule names one JAX dispatch-discipline hazard (docs/PERFORMANCE.md
"Static analysis & sync discipline"). The registry is data, not behavior —
detection lives in ``visitor.py`` — so per-rule enable/severity config and the
docs' rule catalog both read from one table.

Severity semantics:

- ``error``   — near-certain defect: raises under trace, or a per-iteration
                host sync in jit-reachable code (the hazard class PR 1's
                serving engine removed; arXiv:1612.01437 measures this
                sync/serialization overhead dominating distributed ML time).
- ``warning`` — likely stall: a host sync inside a Python loop on a value
                that flows from a jax op, or a retrace-prone call pattern.
- ``info``    — improvement opportunity (e.g. a missing ``donate_argnums``).

Suppressions are inline and must carry a reason:
``# jaxlint: disable=HS001 boundary transfer, scores leave the device here``.
A bare ``# jaxlint: disable=HS001`` is itself an error (SUP001): the lint is
only useful if every suppression documents why the transfer is intentional.
"""

from __future__ import annotations

import dataclasses
import enum


class Severity(enum.IntEnum):
    INFO = 10
    WARNING = 20
    ERROR = 30

    @classmethod
    def parse(cls, text: str) -> "Severity":
        try:
            return cls[text.strip().upper()]
        except KeyError:
            raise ValueError(
                f"unknown severity {text!r}; expected one of "
                f"{[s.name.lower() for s in cls]}"
            ) from None


@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    name: str
    default_severity: Severity
    description: str
    hint: str


RULES: dict[str, Rule] = {
    r.id: r
    for r in (
        Rule(
            id="HS001",
            name="host-sync",
            default_severity=Severity.WARNING,
            description=(
                "Host synchronization (.item()/float()/int()/bool()/"
                "np.asarray/np.array/jax.device_get/.block_until_ready) on a "
                "likely-traced value inside jit-reachable code or a Python loop"
            ),
            hint=(
                "batch device reads into one jax.device_get after the loop, or "
                "keep the value device-resident (jnp.where instead of Python "
                "branching on it)"
            ),
        ),
        Rule(
            id="RT001",
            name="retrace-hazard",
            default_severity=Severity.WARNING,
            description=(
                "Retrace hazard: non-array Python argument (scalar literal, "
                "dict, list) passed to a jitted callable without "
                "static_argnums/static_argnames, or a jnp.array(...) literal "
                "constructed inside a jitted body"
            ),
            hint=(
                "declare config-like arguments in static_argnames (or close "
                "over them); hoist constant arrays out of the jitted body"
            ),
        ),
        Rule(
            id="TR001",
            name="tracer-control-flow",
            default_severity=Severity.ERROR,
            description=(
                "Python control flow (if/while/assert/ternary) on a traced "
                "value inside a jitted function — raises "
                "ConcretizationTypeError at trace time or silently bakes one "
                "branch into the program"
            ),
            hint=(
                "use lax.cond/lax.while_loop/jnp.where, or mark the driving "
                "argument static"
            ),
        ),
        Rule(
            id="PR001",
            name="print-in-jit",
            default_severity=Severity.WARNING,
            description=(
                "print()/logging call inside a jitted body: runs only at "
                "trace time, so it prints tracers once and then never again"
            ),
            hint="use jax.debug.print(...) or hoist the logging out of the jitted body",
        ),
        Rule(
            id="DN001",
            name="missing-donate",
            default_severity=Severity.INFO,
            description=(
                "Jitted function functionally updates a parameter buffer "
                "(x.at[...] usage) without donate_argnums/donate_argnames — "
                "XLA must keep both the input and output buffers live"
            ),
            hint=(
                "add donate_argnums/donate_argnames for update-in-place "
                "parameters the caller no longer needs"
            ),
        ),
        Rule(
            id="NP001",
            name="numpy-inplace-on-jax",
            default_severity=Severity.ERROR,
            description=(
                "In-place numpy mutation (arr[...] = v, arr += v) of a value "
                "that flows from a jax op — jax arrays are immutable and "
                "np.asarray views of them are read-only; this raises or "
                "silently diverges"
            ),
            hint=(
                "use arr = arr.at[...].set(v) on device, or copy explicitly "
                "with np.array(arr) before mutating on host"
            ),
        ),
        Rule(
            id="MP001",
            name="mixed-precision-hazard",
            default_severity=Severity.WARNING,
            description=(
                "Precision hazard in a jitted body: accumulation (sum/mean/"
                "dot/matmul/einsum) directly in a reduced storage dtype "
                "(bf16/f16) without an f32 accumulator, an explicit float64 "
                "promotion (astype/dtype=float64 — emulated and slow on "
                "accelerators, and it silently widens a mixed-precision "
                "program), or a dtype-less jnp.array/zeros/ones/full/empty "
                "in a module that works with reduced storage dtypes (the "
                "default dtype diverges from the storage policy)"
            ),
            hint=(
                "accumulate via preferred_element_type=jnp.float32 / "
                "dtype=jnp.float32 (or upcast with .astype(jnp.float32) "
                "before reducing); avoid float64 in jitted bodies; pass an "
                "explicit dtype= where storage and compute dtypes differ"
            ),
        ),
        Rule(
            id="CC001",
            name="unguarded-write",
            default_severity=Severity.WARNING,
            description=(
                "Write to a lock-owned attribute outside its owning lock: "
                "the attribute's other mutations consistently hold a "
                "specific lock (inferred ownership), and this site does "
                "not. Unlocked READS of owned attributes are not flagged — "
                "snapshot/atomic-pointer read idioms are intentional"
            ),
            hint=(
                "take the owning lock around the write, or document the "
                "attribute as single-writer with an inline suppression"
            ),
        ),
        Rule(
            id="CC002",
            name="lock-order-inversion",
            default_severity=Severity.WARNING,
            description=(
                "Two locks acquired in both nesting orders within one "
                "class/module — the classic deadlock shape once two "
                "threads interleave the two paths"
            ),
            hint=(
                "pick one acquisition order for the pair and refactor the "
                "rarer path to match it"
            ),
        ),
        Rule(
            id="CC003",
            name="unlocked-collection-mutation",
            default_severity=Severity.ERROR,
            description=(
                "Collection mutation (append/add/pop/update/subscript "
                "store, deque/list/dict/set) on thread-shared state "
                "outside its owning lock — including module-global "
                "registries — or on a never-locked collection mutated both "
                "from a thread-entry path and from ordinary callers"
            ),
            hint=(
                "hold the collection's owning lock around every mutation; "
                "for a lock-free design, say why it is safe in an inline "
                "suppression (e.g. bounded deque, references only)"
            ),
        ),
        Rule(
            id="CC004",
            name="daemon-jax-teardown",
            default_severity=Severity.WARNING,
            description=(
                "A daemon thread's target (transitively) drives jax "
                "dispatch, and its scope registers neither an atexit hook "
                "nor a bounded join(timeout)/result(timeout) stop path — "
                "interpreter teardown can kill the thread mid-dispatch and "
                "abort the process"
            ),
            hint=(
                "register an atexit hook that waits (bounded) for the "
                "thread, add a stop()/close() that joins with a timeout, or "
                "bound the wait on the task's result(timeout)"
            ),
        ),
        Rule(
            id="SUP001",
            name="suppression-missing-reason",
            default_severity=Severity.ERROR,
            description=(
                "Inline suppression without a reason: every "
                "'# jaxlint: disable=...' must say why the hazard is "
                "intentional"
            ),
            hint="append a reason: '# jaxlint: disable=HS001 <why this sync is intended>'",
        ),
    )
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One reported hazard. ``line_text`` (the stripped source line) keys the
    baseline so entries survive unrelated line-number drift."""

    rule: str
    severity: Severity
    path: str
    line: int
    col: int
    message: str
    hint: str
    line_text: str = ""
    suppressed: bool = False

    def format_human(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{self.severity.name.lower()}: {self.message}\n"
            f"    hint: {self.hint}"
        )

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity.name.lower(),
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "hint": self.hint,
            "suppressed": self.suppressed,
        }


@dataclasses.dataclass(frozen=True)
class RuleConfig:
    """Per-run rule configuration: which rules run and at what severity."""

    disabled: frozenset[str] = frozenset()
    severity_overrides: dict[str, Severity] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        unknown = (set(self.disabled) | set(self.severity_overrides)) - set(RULES)
        if unknown:
            raise ValueError(f"unknown rule ids: {sorted(unknown)}")

    def enabled(self, rule_id: str) -> bool:
        return rule_id not in self.disabled

    def severity(self, rule_id: str) -> Severity:
        return self.severity_overrides.get(rule_id, RULES[rule_id].default_severity)
