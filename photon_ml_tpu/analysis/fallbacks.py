"""Once-per-cause fallback telemetry: make silent slow paths loud, once.

Several hot paths in the codebase carry a slower twin they can quietly drop
to: the single-program random-effect coordinate update falls back to the
per-bucket host loop when a coordinate opts out (``use_update_program=False``
or a foreign coordinate type), and the serving layer falls back to eager
per-coordinate scoring when the fused engine cannot cover a configuration.
Historically these demotions were SILENT — a misplaced ``device_put`` (a
mesh-sharded dataset before PR 10 lifted the restriction) demoted a whole
training run to the slow path with no signal anywhere.

``log_fallback_once(component, fingerprint, cause)`` is the one logging
discipline for such demotions: exactly ONE structured warning per
(component, fingerprint, cause) key per process, so a 10k-iteration descent
run or a million-request serving process reports the demotion without
flooding. The ``fingerprint`` identifies the demoted object (a dataset or
model — callers pass a short stable description, not an ``id()``, so the log
line is actionable); ``cause`` is the structured reason.

Pure stdlib on purpose (this package's contract): importable without jax.
"""

from __future__ import annotations

import logging
import threading

logger = logging.getLogger(__name__)

_seen: set = set()
_lock = threading.Lock()


def log_fallback_once(component: str, fingerprint: str, cause: str) -> bool:
    """Log one structured fallback warning per (component, fingerprint,
    cause). Returns True when this call was the first (and logged), False for
    every repeat — callers can branch on it for metrics if they need to."""
    key = (component, fingerprint, cause)
    with _lock:
        if key in _seen:
            return False
        _seen.add(key)
    logger.warning(
        "fallback: %s dropped to its slow path for %s — %s "
        "(logged once per cause)",
        component,
        fingerprint,
        cause,
    )
    return True


def reset_fallback_log() -> None:
    """Forget every logged key (tests; long-lived processes that reload
    models and want the next demotion reported again)."""
    with _lock:
        _seen.clear()
