"""Whole-program (cross-module) analysis context for jaxlint v2.

v1's taint pass is module-local: a traced value handed to a helper in
ANOTHER module vanishes at the call boundary, so a `float(v)` inside the
helper — per-iteration, in the caller's descent loop — goes unreported
(docs/PERFORMANCE.md documented exactly this under-report; the PR 2
tracker-sync hazard was this shape). This module closes that hole with
per-function *summaries* and a bounded fixed point over the project call
graph, still pure stdlib ``ast`` (the lint job never imports the code it
scans).

Per function we summarize, without keeping the AST alive (summaries are
plain picklable data so ``--jobs`` workers can receive them):

- ``sync_params``     — parameters whose VALUE is host-synced inside the
                        function (``float(p)``, ``np.asarray(p)``,
                        ``p.item()``, ``jax.device_get(p)``), directly or
                        transitively through callees. Static-metadata reads
                        (``p.shape``, ``len(p)``) never count — same guards
                        as v1.
- ``traced_params``   — parameters observed RECEIVING a likely-traced
                        argument at some resolved call site (fixed point).
- ``returns_traced``  — the function unconditionally returns a device
                        value (a ``jnp.*``/``jax.*`` call result, a traced
                        local, a jitted function's result, or the result of
                        an internal callee that itself returns traced).
- ``returns_lowp``    — returns a reduced-precision (bf16/f16) array.
- ``jit_context``     — jitted / reachable from jitted code, closed over
                        the PROJECT call graph (v1 closed per-module only).
- ``touches_jax``     — calls into ``jax.*`` directly or transitively
                        (feeds CC004's daemon-teardown reachability).

Call resolution is deliberately bounded: an internal dotted name
(``module.fn`` through import aliases), ``self.method`` within the same
class, or a bare/attribute name that is UNIQUE project-wide. Anything
else stays unresolved — the fixed point under-approximates rather than
guesses.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from photon_ml_tpu.analysis.visitor import (
    _STATIC_ATTRS,
    _STATIC_CALLS,
    _SYNC_CALLS,
    _SYNC_METHODS,
    _TRACED_PREFIXES,
    ModuleIndex,
    _dtype_ref_in,
    _LOW_PRECISION_NAMES,
)

# fixed-point iteration bound: summaries propagate at most this many call
# edges deep, which comfortably covers the repo's real call chains while
# keeping the pass linear in practice
MAX_PASSES = 8

# bare names too generic to resolve by project-wide uniqueness (method
# names like these appear on stdlib/third-party objects constantly; a
# unique same-named local function would be a coincidence, not a target)
_GENERIC_NAMES = {
    "get", "put", "set", "add", "pop", "run", "call", "close", "open",
    "read", "write", "update", "append", "send", "start", "stop", "copy",
    "items", "keys", "values", "join", "split", "main", "build", "make",
    # ndarray/tracer method names: obj.sum() is almost always an array
    # reduction, never a coincidentally same-named project function
    "sum", "mean", "max", "min", "any", "all", "astype", "reshape",
    "ravel", "flatten", "transpose", "squeeze", "result", "wait",
}


@dataclasses.dataclass
class CallArg:
    """One argument at a recorded call site: which callee slot it lands in
    (positional index or keyword name), whether it is unconditionally
    traced per the light local taint, and which caller parameters its
    expression reads (for the transitive-sync fixed point)."""

    slot: object  # int (positional) | str (keyword)
    traced: bool
    param_deps: frozenset


@dataclasses.dataclass
class CallRecord:
    kind: str  # "qual" | "self" | "name"
    target: str  # dotted qualname, method name, or bare name
    args: tuple  # tuple[CallArg, ...]
    via_attribute: bool  # spelled obj.m(...) — callee's `self` slot is bound


@dataclasses.dataclass
class FunctionSummary:
    qualname: str
    module: str
    name: str
    cls: Optional[str]
    path: str
    lineno: int
    params: tuple
    is_method: bool
    jitted: bool
    jit_context: bool
    sync_params: set = dataclasses.field(default_factory=set)
    traced_params: set = dataclasses.field(default_factory=set)
    returns_traced: bool = False
    returns_lowp: bool = False
    touches_jax: bool = False
    calls: list = dataclasses.field(default_factory=list)
    # internal callees whose RESULT this function returns (returns_traced /
    # returns_lowp propagate through these edges at the fixed point)
    returns_calls: list = dataclasses.field(default_factory=list)
    # parameters returned DIRECTLY (``return v`` / tuple element): if a call
    # site is observed passing a traced value into one, the function returns
    # traced too — the `_psum`-style passthrough the local scan can't see
    returns_params: set = dataclasses.field(default_factory=set)


def _param_names(node) -> tuple:
    args = node.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return tuple(names)


def _base_param(node, params: set) -> Optional[str]:
    """The parameter whose VALUE this expression reads, or None. Attribute
    chains through static metadata (``p.shape[0]``) do not count."""
    while True:
        if isinstance(node, ast.Name):
            return node.id if node.id in params else None
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return None
            node = node.value
            continue
        if isinstance(node, ast.Subscript):
            node = node.value
            continue
        return None


def _name_deps(node, params: set) -> frozenset:
    """Caller parameters an expression's value depends on (value reads
    only — static-metadata chains are excluded like everywhere else)."""
    deps = set()
    for sub in ast.walk(node):
        p = _base_param(sub, params)
        if p:
            deps.add(p)
    return frozenset(deps)


class _FunctionScanner:
    """Light, linear, per-function scan producing one FunctionSummary.

    The taint here is a cheap subset of visitor.FunctionAnalyzer's: names
    assigned from ``jnp.*``/``jax.*`` calls (or from already-traced names)
    become traced; loop bodies are walked once (summaries feed a fixed
    point anyway, so the double-walk precision is not needed here)."""

    def __init__(self, index: ModuleIndex, summary: FunctionSummary):
        self.index = index
        self.s = summary
        self.params = set(summary.params)
        self.traced: set = set()
        if summary.jitted:
            self.traced |= self.params - {"self"}

    # -- expression classification --------------------------------------
    def _expr_traced(self, node) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self._expr_traced(node.value)
        if isinstance(node, ast.Subscript):
            return self._expr_traced(node.value)
        if isinstance(node, ast.Call):
            c = self.index.canonical(node.func)
            if c is not None:
                if c in _STATIC_CALLS or c.startswith("numpy."):
                    return False
                if c.startswith(_TRACED_PREFIXES) or c == "jax.device_put":
                    return True
            if isinstance(node.func, ast.Attribute):
                if node.func.attr in _SYNC_METHODS:
                    return False
                return self._expr_traced(node.func.value)
            return False
        if isinstance(node, ast.BinOp):
            return self._expr_traced(node.left) or self._expr_traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._expr_traced(node.operand)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._expr_traced(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self._expr_traced(node.body) or self._expr_traced(node.orelse)
        if isinstance(node, ast.Compare):
            return self._expr_traced(node.left) or any(
                self._expr_traced(cmp) for cmp in node.comparators
            )
        if isinstance(node, ast.BoolOp):
            return any(self._expr_traced(v) for v in node.values)
        return False

    def _expr_lowp(self, node) -> bool:
        if isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and node.args
                and _dtype_ref_in(node.args[0], _LOW_PRECISION_NAMES)
            ):
                return True
            return any(
                kw.arg == "dtype" and _dtype_ref_in(kw.value, _LOW_PRECISION_NAMES)
                for kw in node.keywords
            )
        return False

    # -- walk ------------------------------------------------------------
    def scan(self, node):
        body = node.body if isinstance(node.body, list) else [ast.Expr(node.body)]
        for st in body:
            self._stmt(st)

    def _stmt(self, st):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs are summarized separately
        if isinstance(st, ast.Assign):
            self._exprs(st.value)
            if self._expr_traced(st.value):
                for t in st.targets:
                    self._bind(t)
            return
        if isinstance(st, ast.AugAssign):
            self._exprs(st.value)
            if self._expr_traced(st.value):
                self._bind(st.target)
            return
        if isinstance(st, (ast.For, ast.AsyncFor)):
            self._exprs(st.iter)
            if self._expr_traced(st.iter):
                self._bind(st.target)
            for s in st.body + st.orelse:
                self._stmt(s)
            return
        if isinstance(st, (ast.While, ast.If)):
            self._exprs(st.test)
            for s in st.body + st.orelse:
                self._stmt(s)
            return
        if isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                self._exprs(item.context_expr)
            for s in st.body:
                self._stmt(s)
            return
        if isinstance(st, ast.Try):
            for s in st.body:
                self._stmt(s)
            for h in st.handlers:
                for s in h.body:
                    self._stmt(s)
            for s in st.orelse + st.finalbody:
                self._stmt(s)
            return
        if isinstance(st, ast.Return):
            if st.value is not None:
                self._exprs(st.value)
                self._return_expr(st.value)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._exprs(child)

    def _bind(self, target):
        if isinstance(target, ast.Name):
            self.traced.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e)
        elif isinstance(target, ast.Starred):
            self._bind(target.value)

    def _return_expr(self, node):
        if self._expr_traced(node):
            self.s.returns_traced = True
        if self._expr_lowp(node):
            self.s.returns_lowp = True
        p = _base_param(node, self.params)
        if p:
            self.s.returns_params.add(p)
        if isinstance(node, ast.Call):
            rec = self._call_record(node)
            if rec is not None:
                self.s.returns_calls.append(rec)
        elif isinstance(node, (ast.Tuple, ast.List)):
            for e in node.elts:
                self._return_expr(e)

    def _exprs(self, *exprs):
        for e in exprs:
            for node in ast.walk(e):
                if isinstance(node, ast.Call):
                    self._call(node)

    # -- call handling ---------------------------------------------------
    def _call(self, node: ast.Call):
        c = self.index.canonical(node.func)
        # direct host sync of a parameter's value
        if c in _SYNC_CALLS and node.args:
            p = _base_param(node.args[0], self.params)
            if p:
                self.s.sync_params.add(p)
        elif c == "jax.device_get" and node.args:
            p = _base_param(node.args[0], self.params)
            if p:
                self.s.sync_params.add(p)
        elif isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
            p = _base_param(node.func.value, self.params)
            if p:
                self.s.sync_params.add(p)
        if c is not None and (c == "jax" or c.startswith("jax.")):
            self.s.touches_jax = True
        rec = self._call_record(node)
        if rec is not None:
            self.s.calls.append(rec)

    def _call_record(self, node: ast.Call) -> Optional[CallRecord]:
        func = node.func
        kind = target = None
        via_attribute = False
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            kind, target, via_attribute = "self", func.attr, True
        else:
            c = self.index.canonical(func)
            if c is not None and "." in c:
                kind, target = "qual", c
                via_attribute = isinstance(func, ast.Attribute)
            elif isinstance(func, ast.Name):
                kind, target = "name", func.id
            elif isinstance(func, ast.Attribute):
                kind, target, via_attribute = "name", func.attr, True
        if kind is None:
            return None
        args = []
        for i, a in enumerate(node.args):
            if isinstance(a, ast.Starred):
                continue
            args.append(CallArg(i, self._expr_traced(a), _name_deps(a, self.params)))
        for kw in node.keywords:
            if kw.arg is None:
                continue
            args.append(
                CallArg(kw.arg, self._expr_traced(kw.value), _name_deps(kw.value, self.params))
            )
        return CallRecord(kind=kind, target=target, args=tuple(args), via_attribute=via_attribute)


class ProjectContext:
    """Project-wide function summaries + resolution, built once per scan
    and handed (picklable) into each module's analysis."""

    def __init__(self):
        self.by_qual: dict[str, FunctionSummary] = {}
        self.by_name: dict[str, list] = {}
        self.by_site: dict[tuple, FunctionSummary] = {}  # (path, lineno)
        self.modules: set = set()

    # -- construction ----------------------------------------------------
    @classmethod
    def build(cls, sources: list) -> "ProjectContext":
        """``sources``: [(rel_path, source_text)]. Module dotted names come
        from the relative paths, matching the absolute imports the project
        uses internally."""
        ctx = cls()
        scanned = []
        for rel, source in sources:
            try:
                tree = ast.parse(source, filename=rel)
            except SyntaxError:
                continue
            module = _module_name(rel)
            ctx.modules.add(module)
            scanned.append((rel, module, tree))
        for rel, module, tree in scanned:
            ctx._scan_module(rel, module, tree)
        ctx._fixed_point()
        return ctx

    def _scan_module(self, rel: str, module: str, tree: ast.Module):
        index = ModuleIndex()
        index.visit(tree)
        index.close_jit_reachability()
        _resolve_relative_imports(index, module, tree)
        # map each function node to its enclosing class (one level: methods)
        cls_of: dict[int, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        cls_of[id(item)] = node.name
        for info in index.functions.values():
            node = info.node
            if isinstance(node, ast.Lambda):
                continue
            cname = cls_of.get(id(node))
            qual = f"{module}.{cname}.{info.name}" if cname else f"{module}.{info.name}"
            params = _param_names(node)
            s = FunctionSummary(
                qualname=qual,
                module=module,
                name=info.name,
                cls=cname,
                path=rel,
                lineno=node.lineno,
                params=params,
                is_method=bool(params) and params[0] == "self",
                jitted=info.jitted,
                jit_context=info.jit_context,
            )
            _FunctionScanner(index, s).scan(node)
            # last-definition-wins for duplicate quals (overloads via if/else
            # are rare; either branch's summary is a fair approximation)
            self.by_qual[qual] = s
            self.by_name.setdefault(info.name, []).append(s)
            self.by_site[(rel, node.lineno)] = s

    # -- resolution ------------------------------------------------------
    def lookup(self, path: str, lineno: int) -> Optional[FunctionSummary]:
        return self.by_site.get((path, lineno))

    def resolve(self, caller: Optional[FunctionSummary], rec: CallRecord) -> Optional[FunctionSummary]:
        if rec.kind == "qual":
            s = self.by_qual.get(rec.target)
            if s is not None:
                return s
            # module.Class(...) constructor or unresolvable dotted name:
            # fall through to unique-name resolution on the last segment
            tail = rec.target.rsplit(".", 1)[-1]
            return self._unique(tail)
        if rec.kind == "self":
            if caller is not None and caller.cls is not None:
                s = self.by_qual.get(f"{caller.module}.{caller.cls}.{rec.target}")
                if s is not None:
                    return s
            return self._unique(rec.target)
        return self._unique(rec.target)

    def _unique(self, name: str) -> Optional[FunctionSummary]:
        if name in _GENERIC_NAMES or name.startswith("__"):
            return None
        cands = self.by_name.get(name, [])
        return cands[0] if len(cands) == 1 else None

    def resolve_call_node(self, caller_path: str, caller_lineno: int,
                          node: ast.Call, canonical: Optional[str]) -> Optional[FunctionSummary]:
        """Resolution entry point for visitor.FunctionAnalyzer: the analyzer
        already computed the canonical dotted name through ITS module's
        aliases, so reuse it instead of re-deriving."""
        caller = self.lookup(caller_path, caller_lineno)
        func = node.func
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
        ):
            rec = CallRecord("self", func.attr, (), True)
        elif canonical is not None and "." in canonical:
            rec = CallRecord("qual", canonical, (), isinstance(func, ast.Attribute))
        elif isinstance(func, ast.Name):
            rec = CallRecord("name", func.id, (), False)
        elif isinstance(func, ast.Attribute):
            rec = CallRecord("name", func.attr, (), True)
        else:
            return None
        return self.resolve(caller, rec)

    @staticmethod
    def map_args(callee: FunctionSummary, rec_args, via_attribute: bool):
        """Yield (param_name, arg) pairs for a call's recorded args. A
        bound-method spelling (obj.m(...)) skips the callee's `self`."""
        offset = 1 if (via_attribute and callee.is_method) else 0
        for a in rec_args:
            if isinstance(a.slot, int):
                idx = a.slot + offset
                if idx < len(callee.params):
                    yield callee.params[idx], a
            elif a.slot in callee.params:
                yield a.slot, a

    # -- fixed point -----------------------------------------------------
    def _fixed_point(self):
        summaries = list(self.by_qual.values())
        for _ in range(MAX_PASSES):
            changed = False
            for s in summaries:
                if not s.returns_traced and s.returns_params & s.traced_params:
                    s.returns_traced = True
                    changed = True
                for rec in s.calls:
                    t = self.resolve(s, rec)
                    if t is None:
                        continue
                    # cross-boundary jit reachability
                    if s.jit_context and not t.jit_context:
                        t.jit_context = True
                        changed = True
                    # transitive jax reachability (CC004)
                    if t.touches_jax and not s.touches_jax:
                        s.touches_jax = True
                        changed = True
                    for pname, arg in self.map_args(t, rec.args, rec.via_attribute):
                        # traced values observed entering the callee
                        traced = arg.traced or bool(
                            arg.param_deps & (s.traced_params | (set(s.params) - {"self"} if s.jitted else set()))
                        )
                        if traced and pname not in t.traced_params and pname != "self":
                            t.traced_params.add(pname)
                            changed = True
                        # a callee that syncs this param syncs the caller's
                        # feeding params transitively
                        if pname in t.sync_params:
                            for dep in arg.param_deps:
                                if dep not in s.sync_params:
                                    s.sync_params.add(dep)
                                    changed = True
                for rec in s.returns_calls:
                    t = self.resolve(s, rec)
                    if t is None:
                        continue
                    if (t.returns_traced or t.jitted) and not s.returns_traced:
                        s.returns_traced = True
                        changed = True
                    if t.returns_lowp and not s.returns_lowp:
                        s.returns_lowp = True
                        changed = True
            if not changed:
                break


def _module_name(rel: str) -> str:
    rel = rel.replace("\\", "/")
    if rel.endswith(".py"):
        rel = rel[:-3]
    if rel.endswith("/__init__"):
        rel = rel[: -len("/__init__")]
    return rel.replace("/", ".")


def _resolve_relative_imports(index: ModuleIndex, module: str, tree: ast.Module):
    """ModuleIndex skips relative imports (it has no module identity); with
    one, `from . import x` / `from .sib import f` resolve like absolutes."""
    pkg_parts = module.split(".")
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.level > 0:
            base = pkg_parts[: len(pkg_parts) - node.level]
            if node.module:
                base = base + node.module.split(".")
            if not base:
                continue
            prefix = ".".join(base)
            for a in node.names:
                index.aliases[a.asname or a.name] = f"{prefix}.{a.name}"
