"""jaxlint baseline: accept existing findings, fail only on drift.

The committed baseline (``tools/jaxlint_baseline.json``) lets the lint gate
new code without first paying down every historical finding. Two invariants
make it a ratchet instead of a rug:

- a finding NOT covered by the baseline fails the run (new hazards can't
  land), and
- a baseline entry with no matching finding ALSO fails the run (fixing a
  hazard forces the shrunken baseline to be committed, so the baseline only
  ever gets smaller).

Entries are keyed ``path::rule::<stripped source line text>`` with a count,
NOT by line number: inserting an unrelated line above a baselined finding
must not break CI. Moving or duplicating the offending line does change the
key/count — that is drift and should be re-reviewed.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterable

from photon_ml_tpu.analysis.rules import Finding

BASELINE_VERSION = 1


def finding_key(f: Finding) -> str:
    return f"{f.path}::{f.rule}::{f.line_text}"


def to_counts(findings: Iterable[Finding]) -> dict[str, int]:
    counts: dict[str, int] = {}
    for f in findings:
        k = finding_key(f)
        counts[k] = counts.get(k, 0) + 1
    return counts


@dataclasses.dataclass
class BaselineDiff:
    new: list  # findings beyond the baselined count for their key
    stale: list  # baseline keys whose finding no longer exists (count deficit)

    @property
    def clean(self) -> bool:
        return not self.new and not self.stale


def diff(findings: list, baseline_counts: dict[str, int],
         scanned_paths: set | None = None) -> BaselineDiff:
    """``scanned_paths`` (reported-relative paths actually linted this run)
    scopes the staleness check: a baseline entry for a file outside this
    scan's paths is not stale, it just wasn't looked at — so a narrow scan
    (e.g. one package dir) can run clean against a repo-wide baseline."""
    new: list = []
    per_key: dict[str, list] = {}
    for f in findings:
        per_key.setdefault(finding_key(f), []).append(f)
    for key, fs in per_key.items():
        allowed = baseline_counts.get(key, 0)
        if len(fs) > allowed:
            new.extend(fs[allowed:])
    stale = [
        {"key": key, "missing": count - len(per_key.get(key, []))}
        for key, count in sorted(baseline_counts.items())
        if len(per_key.get(key, [])) < count
        and (scanned_paths is None or key.split("::", 1)[0] in scanned_paths)
    ]
    return BaselineDiff(new=new, stale=stale)


def load(path: str) -> dict[str, int]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"expected {BASELINE_VERSION}"
        )
    entries = data.get("entries", {})
    if not all(isinstance(v, int) and v > 0 for v in entries.values()):
        raise ValueError(f"baseline {path} has non-positive entry counts")
    return entries


def save(path: str, findings: list, scanned_paths: set | None = None) -> dict:
    """Write the baseline. Mirrors diff()'s staleness scoping: entries for
    files OUTSIDE ``scanned_paths`` are preserved from the existing file, so
    regenerating from a narrow scan cannot silently drop (and thereby
    re-arm) accepted findings in files that scan never looked at."""
    counts = to_counts(findings)
    if scanned_paths is not None:
        try:
            existing = load(path)
        except (OSError, ValueError):
            existing = {}
        for key, count in existing.items():
            if key.split("::", 1)[0] not in scanned_paths:
                counts[key] = count
    doc = {
        "version": BASELINE_VERSION,
        "comment": (
            "jaxlint accepted-findings baseline. Entries are "
            "'path::rule::stripped-source-line' -> count. Do not add entries "
            "by hand: fix the finding or suppress it inline with a reason. "
            "Regenerate (only ever smaller) with: python tools/jaxlint.py "
            "photon_ml_tpu benchmarks tests bench.py tools --update-baseline"
        ),
        "total": sum(counts.values()),
        "entries": {k: counts[k] for k in sorted(counts)},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return doc
