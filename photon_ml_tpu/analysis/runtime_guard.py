"""Runtime complement to jaxlint: enforce sync/retrace discipline in regions.

jaxlint catches hazards statically; this module turns the two properties PR 1
only *documented* into assertions tests and benchmarks can enforce:

- **no retraces**: a process-wide trace counter fed by ``jax.monitoring``'s
  ``/jax/core/compile/jaxpr_trace_duration`` event, which fires on every
  jaxpr trace (including nested sub-traces) and never on a jit cache hit —
  so "zero events in the guarded region" is exactly "the compile cache held".
  It counts traces, not XLA compiles: a persistent-compilation-cache hit
  still traces, and still counts, which is what a steady-state gate wants.
- **no implicit device->host transfers**: ``jax.transfer_guard_device_to_host
  ("disallow")`` scoped to the region. Explicit ``jax.device_get`` stays
  allowed — the point is to force boundary transfers to be *named*, exactly
  jaxlint's suppression policy at runtime. Host->device stays permitted by
  default because dispatching numpy request buffers into a jitted program is
  the normal serving entry path.

  Backend caveat: the transfer guard is authoritative on real accelerators
  (TPU/GPU), where any device->host read is a real transfer. On the CPU
  backend, device buffers are host memory and numpy reads them zero-copy
  through the buffer protocol, below the guard — so d2h enforcement there is
  best-effort. Implicit HOST->DEVICE transfers (np operands mixed into
  device math, scalar fills) ARE guarded on every backend, which is what the
  guard-wiring tests pin. The retrace guard is authoritative everywhere.

Usage::

    from photon_ml_tpu.analysis.runtime_guard import sync_discipline

    engine.score(warmup_request)                    # compiles outside the guard
    with sync_discipline() as region:
        for req in requests:
            engine.score(req)
    # leaving the region raises RetraceError if anything retraced;
    # region.traces is also readable mid-region for reporting.

The trace counter is process-global (jax.monitoring has no per-thread
listeners): guard one region at a time, and keep unrelated background
compilation out of guarded regions.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading

import jax

_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

_lock = threading.Lock()
_listener_installed = False
_trace_events = 0


def _install_listener() -> None:
    """Register the monitoring listener once per process (listeners cannot be
    unregistered through public jax API, so a counter + snapshots it is)."""
    global _listener_installed
    with _lock:
        if _listener_installed:
            return

        def _on_event_duration(event: str, duration: float, **kwargs) -> None:
            global _trace_events
            if event == _TRACE_EVENT:
                _trace_events += 1

        jax.monitoring.register_event_duration_secs_listener(_on_event_duration)
        _listener_installed = True


def trace_events() -> int:
    """Process-lifetime count of jaxpr traces observed so far (0 until the
    first guarded region installs the listener)."""
    return _trace_events


class RetraceError(AssertionError):
    """A guarded region traced when it promised not to."""


@dataclasses.dataclass
class GuardedRegion:
    """Live view of a guard region; ``traces`` is current at any point inside."""

    _start: int = 0
    allow_retraces: int = 0

    @property
    def traces(self) -> int:
        return _trace_events - self._start


@contextlib.contextmanager
def no_retrace(allow_retraces: int = 0, what: str = "guarded region"):
    """Fail if more than ``allow_retraces`` jaxpr traces happen inside.

    Warmup belongs OUTSIDE the region: compile first, then guard the steady
    state. Raises RetraceError on exit; raises nothing if the body itself
    raised (the original error is more informative than the trace count)."""
    _install_listener()
    region = GuardedRegion(_start=_trace_events, allow_retraces=allow_retraces)
    try:
        yield region
    except BaseException:
        raise
    else:
        if region.traces > allow_retraces:
            raise RetraceError(
                f"{what}: {region.traces} jaxpr trace(s) occurred "
                f"(allowed {allow_retraces}). A retrace after warmup means a "
                "jit cache miss: check for shape/dtype drift, unhashed static "
                "args, or a fresh wrapper per call. jaxlint rule RT001 finds "
                "the static culprits."
            )


@contextlib.contextmanager
def no_implicit_transfers(device_to_host: str = "disallow",
                          host_to_device: str | None = None):
    """Scope jax transfer guards: implicit device->host transfers (np.asarray
    on a device array, float(), .item()) raise inside; explicit
    jax.device_get stays allowed. Pass ``host_to_device="disallow"`` too for
    fully-device-resident regions."""
    with contextlib.ExitStack() as stack:
        stack.enter_context(jax.transfer_guard_device_to_host(device_to_host))
        if host_to_device is not None:
            stack.enter_context(jax.transfer_guard_host_to_device(host_to_device))
        yield


@contextlib.contextmanager
def sync_discipline(allow_retraces: int = 0,
                    device_to_host: str = "disallow",
                    what: str = "guarded region"):
    """Both guards at once: the contract a warmed serving/benchmark steady
    state must meet — zero retraces AND no unnamed device->host transfer."""
    with no_retrace(allow_retraces=allow_retraces, what=what) as region:
        with no_implicit_transfers(device_to_host=device_to_host):
            yield region
