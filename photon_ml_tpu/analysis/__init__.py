"""Static analysis (jaxlint) + runtime guards for JAX dispatch discipline.

Two halves, one hazard class (docs/PERFORMANCE.md "Static analysis & sync
discipline"):

- ``rules`` / ``visitor`` / ``linter`` / ``baseline`` — the jaxlint AST
  engine. Pure stdlib by design: importing them must never pull in jax, so
  the CI lint job and editor integrations can run against source alone.
  CLI entry point: ``python tools/jaxlint.py photon_ml_tpu``.
- ``runtime_guard`` — the runtime complement (``jax.transfer_guard`` +
  jaxpr-trace counter). Imports jax; import it explicitly as
  ``photon_ml_tpu.analysis.runtime_guard`` (or via the lazy names below).
"""

from photon_ml_tpu.analysis.rules import (
    Finding,
    Rule,
    RuleConfig,
    RULES,
    Severity,
)
from photon_ml_tpu.analysis.linter import (
    LintResult,
    lint_paths,
    lint_source,
)

# Lazy: runtime_guard needs jax; the static half must stay importable without it.
_LAZY = {
    "no_retrace": "photon_ml_tpu.analysis.runtime_guard",
    "no_implicit_transfers": "photon_ml_tpu.analysis.runtime_guard",
    "sync_discipline": "photon_ml_tpu.analysis.runtime_guard",
    "RetraceError": "photon_ml_tpu.analysis.runtime_guard",
    "trace_events": "photon_ml_tpu.analysis.runtime_guard",
}


def __getattr__(name):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(target), name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))


__all__ = [
    "Finding",
    "Rule",
    "RuleConfig",
    "RULES",
    "Severity",
    "LintResult",
    "lint_paths",
    "lint_source",
    *sorted(_LAZY),
]
